// Native volume engine: the hot data plane of the volume server in C++.
//
// The reference's volume server is compiled Go; its published headline
// benchmark (15.7k writes/s, 47k reads/s on one laptop core —
// /root/reference/README.md:342-391) is unreachable from a GIL-bound
// Python handler loop.  This engine moves the per-request path of the
// storage engine out of Python:
//
//  1. Needle index (weed/storage/needle_map/compact_map.go semantics):
//     an open-addressing u64->(offset,size) map with the reference's
//     deletion convention (entries keep a negated size so reads can
//     distinguish deleted from absent) plus the counter set the
//     heartbeat reports (file/deleted counts and byte totals).
//  2. Append path (volume_write.go:109-231): serialized appends to the
//     .dat with the 16-byte big-endian .idx entry log
//     (weed/storage/idx/walk.go:12-50), cookie checks against the
//     existing needle, identical-rewrite dedup, and tombstone deletes.
//  3. A framed-TCP server speaking the framework's fast-path protocol
//     (G/W/D lines + >II status/len replies — the same wire format the
//     Python TCP fast path serves, so VolumeTcpClient works unchanged)
//     with request handling entirely off the GIL.
//  4. A load-generator (svn_bench) so the benchmark harness can drive
//     the server at native speed, like the reference's compiled Go
//     `weed benchmark` client (weed/command/benchmark.go:27-90).
//
// Python (storage/native_engine.py) keeps the control plane: volume
// lifecycle, vacuum, EC, replication and HTTP stay in the daemon; both
// sides share this index and append path, so each is always coherent
// with writes made by the other.
//
// Needle layouts mirrored here: weed/storage/needle/needle_write.go:20-113
// (v1/v2/v3), CRC32C over data only (needle/crc.go:12-33, legacy rotated
// Value() accepted on read).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

#if defined(__x86_64__)
#include <nmmintrin.h>
#endif

#include <zlib.h>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) — same dispatch as ec_native.cpp
// ---------------------------------------------------------------------------

struct Crc32cTables {
    uint32_t t[8][256];
    Crc32cTables() {
        const uint32_t poly = 0x82F63B78u;
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t crc = i;
            for (int j = 0; j < 8; j++)
                crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
            t[0][i] = crc;
        }
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t crc = t[0][i];
            for (int s = 1; s < 8; s++) {
                crc = t[0][crc & 0xFF] ^ (crc >> 8);
                t[s][i] = crc;
            }
        }
    }
};

uint32_t crc32c_sw_impl(uint32_t crc, const uint8_t* data, size_t len) {
    static const Crc32cTables tables;
    const uint32_t(*t)[256] = tables.t;
    crc = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, data, 8);
        word ^= (uint64_t)crc;
        crc = t[7][word & 0xFF] ^ t[6][(word >> 8) & 0xFF] ^
              t[5][(word >> 16) & 0xFF] ^ t[4][(word >> 24) & 0xFF] ^
              t[3][(word >> 32) & 0xFF] ^ t[2][(word >> 40) & 0xFF] ^
              t[1][(word >> 48) & 0xFF] ^ t[0][(word >> 56) & 0xFF];
        data += 8;
        len -= 8;
    }
    while (len--) crc = t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw_impl(uint32_t crc, const uint8_t* data, size_t len) {
    uint64_t c = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, data, 8);
        c = _mm_crc32_u64(c, word);
        data += 8;
        len -= 8;
    }
    while (len--) c = _mm_crc32_u8((uint32_t)c, *data++);
    return ~(uint32_t)c;
}
#endif

uint32_t crc32c(const uint8_t* data, size_t len) {
#if defined(__x86_64__)
    if (__builtin_cpu_supports("sse4.2")) return crc32c_hw_impl(0, data, len);
#endif
    return crc32c_sw_impl(0, data, len);
}

// Legacy CRC.Value() form accepted on read (needle_read.go:73-80)
uint32_t crc_legacy_value(uint32_t crc) {
    uint32_t rotated = (crc >> 15) | (crc << 17);
    return rotated + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Big-endian helpers (all on-disk integers are big-endian)
// ---------------------------------------------------------------------------

inline void put_be32(uint8_t* p, uint32_t v) {
    p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
inline void put_be64(uint8_t* p, uint64_t v) {
    put_be32(p, (uint32_t)(v >> 32));
    put_be32(p + 4, (uint32_t)v);
}
inline uint32_t get_be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}
inline uint64_t get_be64(const uint8_t* p) {
    return ((uint64_t)get_be32(p) << 32) | get_be32(p + 4);
}

// ---------------------------------------------------------------------------
// Needle format constants (storage/types.py <-> weed/storage/types)
// ---------------------------------------------------------------------------

constexpr int kHeaderSize = 16;     // cookie4 + id8 + size4
constexpr int kChecksumSize = 4;
constexpr int kTimestampSize = 8;
constexpr int kPaddingSize = 8;
constexpr int32_t kTombstone = -1;
constexpr int64_t kMaxVolumeSize = 32LL * 1024 * 1024 * 1024;
constexpr uint8_t kFlagHasLastModified = 0x08;
constexpr uint8_t kFlagHasTtl = 0x10;
constexpr int kLastModifiedBytes = 5;
constexpr int kTtlBytes = 2;  // count, unit (storage/ttl.py to_bytes)

// Cumulative request counters (exposed to Prometheus via
// svn_server_stats; native requests never enter Python, so the
// observability surface must be fed from here)
std::atomic<int64_t> g_stat_reads{0}, g_stat_ec_reads{0};
std::atomic<int64_t> g_stat_writes{0}, g_stat_deletes{0};
std::atomic<int64_t> g_stat_http_reads{0}, g_stat_fallbacks{0};
std::atomic<int64_t> g_stat_errors{0};

void count_reply(uint32_t status) {
    if (status == 307) g_stat_fallbacks.fetch_add(1);
    else if (status >= 400) g_stat_errors.fetch_add(1);
}

int padding_length(int64_t needle_size, int version) {
    int64_t base = kHeaderSize + needle_size + kChecksumSize;
    if (version == 3) base += kTimestampSize;
    return kPaddingSize - (int)(base % kPaddingSize);
}

int64_t get_actual_size(int64_t size, int version) {
    int64_t body = size + kChecksumSize + padding_length(size, version);
    if (version == 3) body += kTimestampSize;
    return kHeaderSize + body;
}

// ---------------------------------------------------------------------------
// Needle map: open addressing, linear probing, grow-only (deletes negate
// the stored size in place — compact_map.go Delete keeps the slot)
// ---------------------------------------------------------------------------

struct NeedleMapN {
    std::vector<uint64_t> keys;
    std::vector<uint64_t> offsets;   // actual byte offsets
    std::vector<int32_t> sizes;
    std::vector<uint8_t> used;
    size_t cap = 0, count = 0;
    // counters mirroring BaseNeedleMap (needle_map.py:53-110)
    int64_t file_count = 0, deleted_count = 0;
    int64_t content_bytes = 0, deleted_bytes = 0;
    uint64_t max_key = 0;
    mutable std::shared_mutex mu;

    NeedleMapN() { rehash(1024); }

    void rehash(size_t new_cap) {
        std::vector<uint64_t> ok = std::move(keys), oo = std::move(offsets);
        std::vector<int32_t> os = std::move(sizes);
        std::vector<uint8_t> ou = std::move(used);
        size_t old_cap = cap;
        cap = new_cap;
        keys.assign(cap, 0);
        offsets.assign(cap, 0);
        sizes.assign(cap, 0);
        used.assign(cap, 0);
        count = 0;
        for (size_t i = 0; i < old_cap; i++) {
            if (ou[i]) raw_insert(ok[i], oo[i], os[i]);
        }
    }

    size_t slot_for(uint64_t key) const {
        // splitmix64 finalizer as the hash
        uint64_t h = key + 0x9E3779B97F4A7C15ull;
        h = (h ^ (h >> 30)) * 0xBF58476D1CE4E5B9ull;
        h = (h ^ (h >> 27)) * 0x94D049BB133111EBull;
        h ^= h >> 31;
        size_t i = (size_t)(h & (cap - 1));
        while (used[i] && keys[i] != key) i = (i + 1) & (cap - 1);
        return i;
    }

    void raw_insert(uint64_t key, uint64_t off, int32_t size) {
        size_t i = slot_for(key);
        if (!used[i]) {
            used[i] = 1;
            keys[i] = key;
            count++;
        }
        offsets[i] = off;
        sizes[i] = size;
    }

    void maybe_grow() {
        if (count * 10 >= cap * 7) rehash(cap * 2);
    }

    // _apply (needle_map.py:92-110): replay/record one idx-entry worth of
    // state change, maintaining the counter set.
    void apply(uint64_t nid, uint64_t off, int32_t size) {
        if (nid > max_key) max_key = nid;
        if (off > 0 && size != kTombstone) {
            size_t i = slot_for(nid);
            if (used[i] && sizes[i] > 0) {
                deleted_count++;
                deleted_bytes += sizes[i];
            }
            maybe_grow();
            raw_insert(nid, off, size);
            file_count++;
            content_bytes += size;
        } else {
            size_t i = slot_for(nid);
            if (used[i] && sizes[i] > 0) {
                deleted_count++;
                deleted_bytes += sizes[i];
                sizes[i] = -sizes[i];  // keep offset, negate size
            }
        }
    }

    bool get(uint64_t nid, uint64_t* off, int32_t* size) const {
        size_t i = slot_for(nid);
        if (!used[i]) return false;
        *off = offsets[i];
        *size = sizes[i];
        return true;
    }
};

// ---------------------------------------------------------------------------
// Volume handle
// ---------------------------------------------------------------------------

struct NVolume {
    int dat_fd = -1, idx_fd = -1;
    int version = 3;
    std::mutex wmu;  // serializes .dat appends across Python + native paths
    NeedleMapN nm;
    std::atomic<uint64_t> last_append_ns{0};
    std::atomic<int64_t> last_modified_ts{0};
    std::atomic<bool> writable{false};   // native W/D allowed
    std::atomic<bool> read_only{false};
    std::atomic<bool> do_fsync{false};
    // TTL volumes: reads 404 expired needles (volume_read.go:27-35);
    // the daemon's vacuum still reclaims them.  ttl_raw is the volume
    // TTL's on-disk uint32 form ((count<<8)|unit, storage/ttl.py):
    // native writes stamp it into every needle so natively-written
    // needles on TTL volumes expire and vacuum like Python-written ones
    std::atomic<int64_t> ttl_sec{0};
    std::atomic<uint32_t> ttl_raw{0};
    // replicated volumes: native writes must fan out to this many other
    // locations (store_replicate.go:24-141); when the replica address
    // set is smaller, writes 307 to the Python handler instead
    std::atomic<int> extra_copies{0};

    // group commit for -fsync volumes (volume_write.go:233-306 /
    // _FsyncBatcher semantics): tickets issued under wmu; one leader
    // fsyncs for every ticket issued so far, the rest wait.  A failed
    // leader fsync fails EVERY ticket it covered (volume.py
    // _FsyncBatcher "_failed_upto = target" — an acknowledged write
    // must never ride a sync whose pages the kernel dropped).
    std::mutex fs_mu;
    std::condition_variable fs_cv;
    std::atomic<uint64_t> fs_seq{0};  // tickets issued (under wmu, but
                                      // read concurrently by leaders)
    uint64_t fs_done = 0;    // durable through this ticket
    uint64_t fs_failed = 0;  // failed-batch watermark
    bool fs_running = false;

    // Wait until `ticket` is covered by a group fsync; false when the
    // commit covering it failed (the write must be answered 500).
    bool fsync_ticket(uint64_t ticket) {
        std::unique_lock<std::mutex> lk(fs_mu);
        while (fs_done < ticket && fs_failed < ticket) {
            if (!fs_running) {
                fs_running = true;
                uint64_t target = fs_seq.load();
                lk.unlock();
                bool ok = fdatasync(dat_fd) == 0 && fdatasync(idx_fd) == 0;
                lk.lock();
                if (ok) {
                    if (target > fs_done) fs_done = target;
                } else if (target > fs_failed) {
                    fs_failed = target;
                }
                fs_running = false;
                fs_cv.notify_all();
            } else {
                fs_cv.wait(lk);
            }
        }
        return fs_done >= ticket;
    }

    ~NVolume() {
        if (dat_fd >= 0) close(dat_fd);
        if (idx_fd >= 0) close(idx_fd);
    }
};

using VolPtr = std::shared_ptr<NVolume>;

// GF(2^8)/0x11D multiplication table for degraded-read reconstruction
// (same construction as ec_native.cpp / ops/gf256.py).
struct GfMulTables {
    uint8_t mul[256][256];
    GfMulTables() {
        uint8_t exp_t[510];
        int log_t[256] = {0};
        int x = 1;
        for (int i = 0; i < 255; i++) {
            exp_t[i] = (uint8_t)x;
            log_t[x] = i;
            x <<= 1;
            if (x & 0x100) x ^= 0x11D;
        }
        for (int i = 255; i < 510; i++) exp_t[i] = exp_t[i - 255];
        for (int a = 0; a < 256; a++)
            for (int b = 0; b < 256; b++)
                mul[a][b] = (a && b) ? exp_t[log_t[a] + log_t[b]] : 0;
    }
};

const uint8_t (*gf_mul())[256] {
    static const GfMulTables t;
    return t.mul;
}

// Per-missing-shard recovery plan: reconstruct its bytes at any offset
// as XOR_j mul(coeffs[j], survivor_j bytes at the SAME offset) — the
// one-matmul survivor->missing row the daemon derives with
// rebuild_matrix (RS parity is columnwise, so spans align).
struct EcRecovery {
    uint8_t survivors[10];
    uint8_t coeffs[10];
};

// EC volume handle: sorted .ecx + local shard files.  Serves reads whose
// intervals all hit local shards; a missing shard's span reconstructs
// on the fly from 10 local survivors when the daemon pushed a recovery
// plan (native degraded reads — recoverOneRemoteEcShardInterval,
// store_ec.go:328-382, minus the remote fetches); anything else answers
// 307 and the client falls back to the HTTP ladder (local -> remote ->
// reconstruct, store_ec.go:125-163).  Writes/deletes stay in Python.
struct NEcVolume {
    int ecx_fd = -1;
    std::atomic<int64_t> ecx_entries{0};
    int version = 3;
    int64_t large_block = 0, small_block = 0;
    std::atomic<int64_t> shard_size{0};  // any local shard's file size
    // atomic slots: server threads read them lock-free mid-request.
    // Replaced/removed fds are RETIRED, not closed — an in-flight pread
    // must never hit EBADF or a reused descriptor; the handful of fds a
    // remount churn leaves open are released in the destructor.
    std::atomic<int> shard_fds[14];
    std::mutex retired_mu;
    std::vector<int> retired;
    mutable std::shared_mutex recovery_mu;
    std::unique_ptr<EcRecovery> recovery[14];
    NEcVolume() {
        for (int i = 0; i < 14; i++) shard_fds[i].store(-1);
    }
    // copy of shard sid's recovery plan, or false when none is set
    bool get_recovery(int sid, EcRecovery* out) const {
        std::shared_lock<std::shared_mutex> lk(recovery_mu);
        if (!recovery[sid]) return false;
        *out = *recovery[sid];
        return true;
    }
    void retire(int fd) {
        if (fd < 0) return;
        std::lock_guard<std::mutex> lk(retired_mu);
        retired.push_back(fd);
    }
    ~NEcVolume() {
        if (ecx_fd >= 0) close(ecx_fd);
        for (int i = 0; i < 14; i++) {
            int fd = shard_fds[i].load();
            if (fd >= 0) close(fd);
        }
        for (int fd : retired) close(fd);
    }
};

using EcPtr = std::shared_ptr<NEcVolume>;

std::shared_mutex g_reg_mu;
std::unordered_map<int64_t, VolPtr> g_handles;     // handle -> volume
std::unordered_map<uint32_t, int64_t> g_serving;   // vid -> handle
std::unordered_map<int64_t, EcPtr> g_ec_handles;   // handle -> EC volume
std::unordered_map<uint32_t, int64_t> g_ec_serving;  // vid -> EC handle
std::atomic<int64_t> g_next_handle{1};

// JWT keys for the fast-path port; set before svn_server_start (the
// Python daemon configures them from security.toml at startup).
std::mutex g_jwt_mu;
std::string g_jwt_write_key, g_jwt_read_key;
int g_jwt_expire_s = 10;

// Signature-verification memo: a count>N assign shares ONE token across
// all N chunk writes (plus every replica forward re-verifies it), so
// the same (key, token) pair is HMAC'd over and over on the hottest
// write path.  Only successful signature checks are cached and `exp` is
// re-evaluated on every lookup, so a hit can never outlive the token.
// Cleared whenever a signing key changes.
struct JwtVerified {
    std::string fid;
    int64_t exp = 0;
    bool has_exp = false;
};
std::mutex g_jwt_cache_mu;
std::unordered_map<std::string, JwtVerified> g_jwt_cache;
constexpr size_t kJwtCacheMax = 4096;

void jwt_cache_clear() {
    std::lock_guard<std::mutex> lk(g_jwt_cache_mu);
    g_jwt_cache.clear();
}

// Replica fan-out registry: vid -> peer fast-path addresses.
std::shared_mutex g_replica_mu;
std::unordered_map<uint32_t, std::vector<std::string>> g_replicas;

VolPtr handle_vol(int64_t h) {
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_handles.find(h);
    return it == g_handles.end() ? nullptr : it->second;
}

VolPtr serving_vol(uint32_t vid) {
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_serving.find(vid);
    if (it == g_serving.end()) return nullptr;
    auto hit = g_handles.find(it->second);
    return hit == g_handles.end() ? nullptr : hit->second;
}

EcPtr serving_ec(uint32_t vid) {
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_ec_serving.find(vid);
    if (it == g_ec_serving.end()) return nullptr;
    auto hit = g_ec_handles.find(it->second);
    return hit == g_ec_handles.end() ? nullptr : hit->second;
}

bool append_idx_entry(NVolume* v, uint64_t nid, uint64_t off, int32_t size) {
    uint8_t e[16];
    put_be64(e, nid);
    put_be32(e + 8, (uint32_t)(off / kPaddingSize));  // stored ÷8 (offset.go)
    put_be32(e + 12, (uint32_t)size);
    return write(v->idx_fd, e, 16) == 16;  // O_APPEND: atomic
}

// pread exactly n bytes; false on short read / error
bool pread_full(int fd, uint8_t* buf, size_t n, int64_t off) {
    size_t got = 0;
    while (got < n) {
        ssize_t r = pread(fd, buf + got, n - got, off + got);
        if (r <= 0) return false;
        got += (size_t)r;
    }
    return true;
}

bool pwrite_full(int fd, const uint8_t* buf, size_t n, int64_t off) {
    size_t put = 0;
    while (put < n) {
        ssize_t r = pwrite(fd, buf + put, n - put, off + put);
        if (r < 0) return false;
        put += (size_t)r;
    }
    return true;
}

uint64_t now_unix_ns() {
    return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::system_clock::now().time_since_epoch())
        .count();
}

// Parse a needle record's data section (needle_read.go:98-177).  Returns
// false on structural error.  `data_off`/`data_len` locate the payload
// inside `blob`; `cookie` and CRC are verified by the caller.
bool parse_needle_data(const uint8_t* blob, int64_t blob_len, int32_t size,
                       int version, int64_t* data_off, int64_t* data_len) {
    if (version == 1) {
        if (kHeaderSize + size > blob_len) return false;
        *data_off = kHeaderSize;
        *data_len = size;
        return true;
    }
    if (size == 0) {
        *data_off = kHeaderSize;
        *data_len = 0;
        return true;
    }
    if (kHeaderSize + 4 > blob_len) return false;
    uint32_t dsize = get_be32(blob + kHeaderSize);
    if (kHeaderSize + 4 + (int64_t)dsize > blob_len) return false;
    *data_off = kHeaderSize + 4;
    *data_len = dsize;
    return true;
}

// Walk the needle body's optional fields to the 5-byte lastModified
// (needle layout: Data, Flags, [Name], [Mime], [LastModified], ... —
// needle_read.go:114-177).  0 when absent/unparseable.
int64_t needle_last_modified(const uint8_t* b, int64_t blob_len,
                             int32_t size, int version) {
    if (version == 1 || size <= 0) return 0;
    if (kHeaderSize + 4 > blob_len) return 0;
    uint32_t dsize = get_be32(b + kHeaderSize);
    int64_t p = kHeaderSize + 4 + (int64_t)dsize;
    int64_t end = std::min<int64_t>(kHeaderSize + size, blob_len);
    if (p >= end) return 0;
    uint8_t flags = b[p++];
    if (flags & 0x02) {  // HAS_NAME
        if (p >= end) return 0;
        p += 1 + b[p];
    }
    if (flags & 0x04) {  // HAS_MIME
        if (p >= end) return 0;
        p += 1 + b[p];
    }
    if (!(flags & kFlagHasLastModified)) return 0;
    if (p + kLastModifiedBytes > end) return 0;
    int64_t v = 0;
    for (int i = 0; i < kLastModifiedBytes; i++) v = (v << 8) | b[p + i];
    return v;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Registration / needle-map API (ctypes surface)
// ---------------------------------------------------------------------------

// Open the volume's .dat/.idx, replay the idx into the in-RAM map, and
// return a handle (>0) or -errno.
int64_t svn_register(const char* dat_path, const char* idx_path, int version,
                     int writable, int read_only, int do_fsync) {
    auto v = std::make_shared<NVolume>();
    v->version = version;
    v->writable.store(writable != 0);
    v->read_only.store(read_only != 0);
    v->do_fsync.store(do_fsync != 0);
    v->dat_fd = open(dat_path, O_RDWR);
    if (v->dat_fd < 0) return -errno;
    v->idx_fd = open(idx_path, O_RDWR | O_CREAT | O_APPEND, 0644);
    if (v->idx_fd < 0) return -errno;
    // replay existing idx entries (needle_map_memory.go doLoading)
    struct stat st;
    if (fstat(v->idx_fd, &st) == 0 && st.st_size >= 16) {
        int64_t n_entries = st.st_size / 16;
        std::vector<uint8_t> buf(1 << 20);
        int64_t pos = 0;
        while (pos < n_entries * 16) {
            int64_t chunk =
                std::min<int64_t>((int64_t)buf.size(), n_entries * 16 - pos);
            chunk -= chunk % 16;
            if (!pread_full(v->idx_fd, buf.data(), (size_t)chunk, pos))
                break;
            for (int64_t e = 0; e < chunk; e += 16) {
                uint64_t nid = get_be64(&buf[e]);
                uint64_t off =
                    (uint64_t)get_be32(&buf[e + 8]) * kPaddingSize;
                int32_t size = (int32_t)get_be32(&buf[e + 12]);
                v->nm.apply(nid, off, size);
            }
            pos += chunk;
        }
    }
    int64_t h = g_next_handle.fetch_add(1);
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    g_handles[h] = std::move(v);
    return h;
}

int svn_unregister(int64_t handle) {
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    for (auto it = g_serving.begin(); it != g_serving.end();) {
        if (it->second == handle) it = g_serving.erase(it);
        else ++it;
    }
    return g_handles.erase(handle) ? 0 : -1;
}

int svn_set_flags(int64_t handle, int writable, int read_only) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    if (writable >= 0) v->writable.store(writable != 0);
    if (read_only >= 0) v->read_only.store(read_only != 0);
    return 0;
}

// TTL volumes: native reads 404 needles older than ttl_sec (0 = none);
// native writes append ttl_raw ((count<<8)|unit) to each needle.
int svn_set_ttl(int64_t handle, int64_t ttl_sec, uint32_t ttl_raw) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    v->ttl_sec.store(ttl_sec);
    v->ttl_raw.store(ttl_raw);
    return 0;
}

// Replicated volumes: native writes fan out to `extra_copies` other
// locations (or 307 when the replica set is not configured).
int svn_set_replication(int64_t handle, int extra_copies) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    v->extra_copies.store(extra_copies);
    return 0;
}

// Replace vid's peer fast-path addresses ("host:port,host:port"; empty
// or NULL clears).  The daemon refreshes these from master lookups.
int svn_set_replicas(uint32_t vid, const char* csv) {
    std::vector<std::string> addrs;
    if (csv) {
        const char* p = csv;
        while (*p) {
            const char* comma = strchr(p, ',');
            size_t n = comma ? (size_t)(comma - p) : strlen(p);
            if (n) addrs.emplace_back(p, n);
            p += n + (comma ? 1 : 0);
        }
    }
    std::unique_lock<std::shared_mutex> lk(g_replica_mu);
    if (addrs.empty()) g_replicas.erase(vid);
    else g_replicas[vid] = std::move(addrs);
    return 0;
}

// HS256 signing keys for the fast-path port (security.toml jwt.signing
// / jwt.signing.read — guard.go:18-50).  Empty string disables a key;
// NULL leaves that key untouched.  The keys are ENGINE-global and the
// engine is shared by every in-process daemon, so each owner (master
// guard, volume guard) must only ever set/clear ITS key — a master
// shutting down must not also clear the volume server's read key.
int svn_server_set_jwt(const char* write_key, const char* read_key,
                       int expire_s) {
    {
        std::lock_guard<std::mutex> lk(g_jwt_mu);
        if (write_key) g_jwt_write_key = write_key;
        if (read_key) g_jwt_read_key = read_key;
        if (expire_s > 0) g_jwt_expire_s = expire_s;
    }
    // verified signatures are key-dependent: a rotated/cleared key must
    // not keep honoring tokens minted under the old one
    if (write_key || read_key) jwt_cache_clear();
    return 0;
}

// Bind/unbind a volume id to a handle for the TCP server
int svn_serve(uint32_t vid, int64_t handle) {
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    if (handle <= 0) {
        g_serving.erase(vid);
        return 0;
    }
    if (!g_handles.count(handle)) return -1;
    g_serving[vid] = handle;
    return 0;
}

int svn_nm_put(int64_t handle, uint64_t nid, uint64_t off, int64_t size) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::unique_lock<std::shared_mutex> lk(v->nm.mu);
    v->nm.apply(nid, off, (int32_t)size);
    return append_idx_entry(v.get(), nid, off, (int32_t)size) ? 0 : -errno;
}

int svn_nm_delete(int64_t handle, uint64_t nid, uint64_t tomb_off) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::unique_lock<std::shared_mutex> lk(v->nm.mu);
    // idx log FIRST: an ENOSPC/EIO append must fail the request before
    // the in-RAM map records a state the log never will (the Python
    // caller raises on a negative return)
    if (!append_idx_entry(v.get(), nid, tomb_off, kTombstone))
        return -(errno ? errno : EIO);
    v->nm.apply(nid, 0, kTombstone);
    return 0;
}

// Apply + log the entry only when it is newer than the current mapping
// (the volume_write.go:160-165 "nv.Offset < offset" guard, evaluated
// atomically under the map lock so a racing native-port write to the
// same id cannot be clobbered by a stale Python-side put).
// Returns 1 applied, 0 superseded, <0 error.
int svn_nm_put_if_newer(int64_t handle, uint64_t nid, uint64_t off,
                        int64_t size) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::unique_lock<std::shared_mutex> lk(v->nm.mu);
    uint64_t cur_off;
    int32_t cur_size;
    if (v->nm.get(nid, &cur_off, &cur_size) && cur_off >= off) return 0;
    if (!append_idx_entry(v.get(), nid, off, (int32_t)size))
        return -(errno ? errno : EIO);
    v->nm.apply(nid, off, (int32_t)size);
    return 1;
}

int svn_nm_set_memory(int64_t handle, uint64_t nid, uint64_t off,
                      int64_t size) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::unique_lock<std::shared_mutex> lk(v->nm.mu);
    v->nm.apply(nid, off, (int32_t)size);
    return 0;
}

// -> 1 found (fills off/size; negative size = deleted), 0 absent, <0 error
int svn_nm_get(int64_t handle, uint64_t nid, uint64_t* off, int64_t* size) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::shared_lock<std::shared_mutex> lk(v->nm.mu);
    uint64_t o;
    int32_t s;
    if (!v->nm.get(nid, &o, &s)) return 0;
    *off = o;
    *size = s;
    return 1;
}

// out[0..6] = file_count, deleted_count, content_bytes, deleted_bytes,
//             max_key, live_slot_count, last_append_ns
int svn_nm_stats(int64_t handle, int64_t* out) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::shared_lock<std::shared_mutex> lk(v->nm.mu);
    out[0] = v->nm.file_count;
    out[1] = v->nm.deleted_count;
    out[2] = v->nm.content_bytes;
    out[3] = v->nm.deleted_bytes;
    out[4] = (int64_t)v->nm.max_key;
    out[5] = (int64_t)v->nm.count;
    out[6] = (int64_t)v->last_append_ns.load();
    return 0;
}

// Fill `out` with (nid, offset, size) int64 triples in ascending nid order.
// Returns the entry count, -needed when cap_entries is too small, or
// INT64_MIN for an unknown handle (distinguishable from any capacity ask).
int64_t svn_nm_visit(int64_t handle, int64_t* out, int64_t cap_entries) {
    auto v = handle_vol(handle);
    if (!v) return INT64_MIN;
    std::shared_lock<std::shared_mutex> lk(v->nm.mu);
    int64_t n = (int64_t)v->nm.count;
    if (n > cap_entries) return -n;
    std::vector<size_t> idx;
    idx.reserve((size_t)n);
    for (size_t i = 0; i < v->nm.cap; i++)
        if (v->nm.used[i]) idx.push_back(i);
    std::sort(idx.begin(), idx.end(), [&](size_t a, size_t b) {
        return v->nm.keys[a] < v->nm.keys[b];
    });
    int64_t w = 0;
    for (size_t i : idx) {
        out[w * 3] = (int64_t)v->nm.keys[i];
        out[w * 3 + 1] = (int64_t)v->nm.offsets[i];
        out[w * 3 + 2] = v->nm.sizes[i];
        w++;
    }
    return n;
}

// Append a pre-built record blob to the .dat; returns the landing offset
// or -errno.  The append mutex is shared with the native write path, so
// Python-side writes and native-port writes never interleave.
int64_t svn_append(int64_t handle, const uint8_t* blob, int64_t len) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    std::lock_guard<std::mutex> lk(v->wmu);
    int64_t end = lseek(v->dat_fd, 0, SEEK_END);
    if (end < 0) return -errno;
    if (!pwrite_full(v->dat_fd, blob, (size_t)len, end)) return -errno;
    return end;
}

int64_t svn_size(int64_t handle) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    struct stat st;
    if (fstat(v->dat_fd, &st) != 0) return -errno;
    return st.st_size;
}

int svn_sync(int64_t handle) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    if (fdatasync(v->idx_fd) != 0) return -errno;
    if (fdatasync(v->dat_fd) != 0) return -errno;
    return 0;
}

int svn_touch(int64_t handle, uint64_t append_ns, int64_t modified_ts) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    if (append_ns > v->last_append_ns.load())
        v->last_append_ns.store(append_ns);
    if (modified_ts > v->last_modified_ts.load())
        v->last_modified_ts.store(modified_ts);
    return 0;
}

int64_t svn_last_modified(int64_t handle) {
    auto v = handle_vol(handle);
    return v ? v->last_modified_ts.load() : -1;
}

// Disable native writes and drain any in-flight append (vacuum commit
// barrier: after this returns, no native write can touch the old files)
int svn_quiesce(int64_t handle) {
    auto v = handle_vol(handle);
    if (!v) return -1;
    v->writable.store(false);
    std::lock_guard<std::mutex> lk(v->wmu);
    return 0;
}

// ---------------------------------------------------------------------------
// EC volume API
// ---------------------------------------------------------------------------

int64_t svn_ec_register(const char* ecx_path, int version,
                        int64_t large_block, int64_t small_block) {
    auto ev = std::make_shared<NEcVolume>();
    ev->version = version;
    ev->large_block = large_block;
    ev->small_block = small_block;
    ev->ecx_fd = open(ecx_path, O_RDONLY);
    if (ev->ecx_fd < 0) return -errno;
    struct stat st;
    if (fstat(ev->ecx_fd, &st) != 0) return -errno;
    ev->ecx_entries.store(st.st_size / 16);
    int64_t h = g_next_handle.fetch_add(1);
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    g_ec_handles[h] = std::move(ev);
    return h;
}

int svn_ec_add_shard(int64_t handle, int shard_id, const char* path) {
    if (shard_id < 0 || shard_id >= 14) return -1;
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_ec_handles.find(handle);
    if (it == g_ec_handles.end()) return -1;
    auto& ev = it->second;
    int fd = open(path, O_RDONLY);
    if (fd < 0) return -errno;
    struct stat st;
    if (fstat(fd, &st) != 0) {
        close(fd);
        return -errno;
    }
    ev->retire(ev->shard_fds[shard_id].exchange(fd));
    ev->shard_size.store(st.st_size);
    return 0;
}

int svn_ec_remove_shard(int64_t handle, int shard_id) {
    if (shard_id < 0 || shard_id >= 14) return -1;
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_ec_handles.find(handle);
    if (it == g_ec_handles.end()) return -1;
    auto& ev = it->second;
    ev->retire(ev->shard_fds[shard_id].exchange(-1));
    return 0;
}

int svn_ec_serve(uint32_t vid, int64_t handle) {
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    if (handle <= 0) {
        g_ec_serving.erase(vid);
        return 0;
    }
    if (!g_ec_handles.count(handle)) return -1;
    g_ec_serving[vid] = handle;
    return 0;
}

int svn_ec_unregister(int64_t handle) {
    std::unique_lock<std::shared_mutex> lk(g_reg_mu);
    for (auto it = g_ec_serving.begin(); it != g_ec_serving.end();) {
        if (it->second == handle) it = g_ec_serving.erase(it);
        else ++it;
    }
    return g_ec_handles.erase(handle) ? 0 : -1;
}

// Install (n=10) or clear (n=0) shard_id's degraded-read recovery plan:
// `survivors` are 10 shard ids whose same-offset bytes, combined with
// `coeffs` under GF(2^8), reproduce shard_id's bytes.  The daemon
// derives the row with rebuild_matrix at shard-sync time.
int svn_ec_set_recovery(int64_t handle, int shard_id,
                        const uint8_t* survivors, const uint8_t* coeffs,
                        int n) {
    std::shared_lock<std::shared_mutex> rlk(g_reg_mu);
    auto it = g_ec_handles.find(handle);
    if (it == g_ec_handles.end()) return -1;
    auto ev = it->second;
    rlk.unlock();
    if (shard_id < 0 || shard_id >= 14) return -1;
    std::unique_lock<std::shared_mutex> lk(ev->recovery_mu);
    if (n != 10) {
        ev->recovery[shard_id].reset();
        return 0;
    }
    for (int j = 0; j < 10; j++)
        if (survivors[j] >= 14) return -1;  // would index OOB on read
    auto rec = std::make_unique<EcRecovery>();
    memcpy(rec->survivors, survivors, 10);
    memcpy(rec->coeffs, coeffs, 10);
    ev->recovery[shard_id] = std::move(rec);
    return 0;
}

// Refresh the cached .ecx entry count (the file grows only on rebuild;
// deletes rewrite size fields in place, which preads observe directly)
int svn_ec_refresh(int64_t handle) {
    std::shared_lock<std::shared_mutex> lk(g_reg_mu);
    auto it = g_ec_handles.find(handle);
    if (it == g_ec_handles.end()) return -1;
    struct stat st;
    if (fstat(it->second->ecx_fd, &st) != 0) return -errno;
    it->second->ecx_entries.store(st.st_size / 16);
    return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Request handling shared by the TCP server
// ---------------------------------------------------------------------------

namespace {

struct Reply {
    uint32_t status;  // 0 = OK (payload = data / JSON); else error code
    std::string payload;
};

// Parse "vid,<idhex><cookie8hex>[_delta]" (storage/types.py:91-111)
bool parse_fid(const std::string& fid, uint32_t* vid, uint64_t* nid,
               uint32_t* cookie) {
    size_t comma = fid.find(',');
    if (comma == std::string::npos) return false;
    errno = 0;
    char* endp = nullptr;
    unsigned long vv = strtoul(fid.c_str(), &endp, 10);
    if (errno || endp != fid.c_str() + comma) return false;
    std::string key = fid.substr(comma + 1);
    uint64_t delta = 0;
    size_t us = key.rfind('_');
    if (us != std::string::npos) {
        delta = strtoull(key.c_str() + us + 1, nullptr, 10);
        key = key.substr(0, us);
    }
    if (key.size() <= 8 || key.size() > 24) return false;
    std::string id_hex = key.substr(0, key.size() - 8);
    std::string ck_hex = key.substr(key.size() - 8);
    errno = 0;
    uint64_t id = strtoull(id_hex.c_str(), &endp, 16);
    if (errno || *endp) return false;
    uint32_t ck = (uint32_t)strtoul(ck_hex.c_str(), &endp, 16);
    if (*endp) return false;
    *vid = (uint32_t)vv;
    *nid = id + delta;
    *cookie = ck;
    return true;
}

// gunzip a stored-compressed needle payload (the HTTP handler without
// Accept-Encoding: gzip decompresses; the fast path must agree —
// volume_server_handlers_read.go:180-199 semantics)
bool gunzip(const std::string& in, std::string* out) {
    z_stream zs{};
    if (inflateInit2(&zs, 15 + 16) != Z_OK) return false;  // gzip wrapper
    out->clear();
    out->reserve(in.size() * 3);
    char buf[1 << 16];
    zs.next_in = (Bytef*)in.data();
    zs.avail_in = (uInt)in.size();
    // loop on Z_OK, not on remaining input: inflate may still hold
    // window output after the last input byte (long back-references);
    // a truncated/non-progressing stream surfaces as Z_BUF_ERROR
    int rc = Z_OK;
    while (rc == Z_OK) {
        zs.next_out = (Bytef*)buf;
        zs.avail_out = sizeof(buf);
        rc = inflate(&zs, Z_NO_FLUSH);
        if (rc != Z_OK && rc != Z_STREAM_END) {
            inflateEnd(&zs);
            return false;
        }
        out->append(buf, sizeof(buf) - zs.avail_out);
    }
    inflateEnd(&zs);
    return rc == Z_STREAM_END;
}

// ---------------------------------------------------------------------------
// SHA-256 / HMAC-SHA256 / base64url — self-contained (no OpenSSL), for
// HS256 JWT verification and minting on the fast-path port.  Semantics
// mirror security/jwt_auth.py (itself weed/security/jwt.go + guard.go:
// fid-scoped claims, exp checked, HS256 only — and because verification
// recomputes HMAC-SHA256 unconditionally, alg-confusion tokens like
// "alg":"none" can never pass).
// ---------------------------------------------------------------------------

struct Sha256 {
    uint32_t h[8];
    uint64_t len = 0;
    uint8_t buf[64];
    size_t fill = 0;
    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
        memcpy(h, init, sizeof(h));
    }
    static uint32_t rotr(uint32_t x, int n) {
        return (x >> n) | (x << (32 - n));
    }
    void block(const uint8_t* p) {
        static const uint32_t k[64] = {
            0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
            0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
            0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
            0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
            0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
            0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
            0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
            0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
            0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
            0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
            0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
            0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
            0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
                   ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                          (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                          (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4],
                 f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + s1 + ch + k[i] + w[i];
            uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = s0 + mj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }
    void update(const void* data, size_t n) {
        const uint8_t* p = (const uint8_t*)data;
        len += n;
        if (fill) {
            size_t take = std::min(n, 64 - fill);
            memcpy(buf + fill, p, take);
            fill += take;
            p += take;
            n -= take;
            if (fill == 64) {
                block(buf);
                fill = 0;
            }
        }
        while (n >= 64) {
            block(p);
            p += 64;
            n -= 64;
        }
        if (n) {
            memcpy(buf, p, n);
            fill = n;
        }
    }
    void final(uint8_t out[32]) {
        uint64_t bits = len * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t zero = 0;
        while (fill != 56) update(&zero, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++)
            lenb[i] = (uint8_t)(bits >> (8 * (7 - i)));
        update(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = (uint8_t)(h[i] >> 24);
            out[4 * i + 1] = (uint8_t)(h[i] >> 16);
            out[4 * i + 2] = (uint8_t)(h[i] >> 8);
            out[4 * i + 3] = (uint8_t)h[i];
        }
    }
};

void hmac_sha256(const std::string& key, const std::string& msg,
                 uint8_t out[32]) {
    uint8_t k[64] = {0};
    if (key.size() > 64) {
        Sha256 kh;
        kh.update(key.data(), key.size());
        kh.final(k);
    } else {
        memcpy(k, key.data(), key.size());
    }
    uint8_t ipad[64], opad[64];
    for (int i = 0; i < 64; i++) {
        ipad[i] = k[i] ^ 0x36;
        opad[i] = k[i] ^ 0x5c;
    }
    uint8_t inner[32];
    Sha256 si;
    si.update(ipad, 64);
    si.update(msg.data(), msg.size());
    si.final(inner);
    Sha256 so;
    so.update(opad, 64);
    so.update(inner, 32);
    so.final(out);
}

const char* kB64Url =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";

std::string b64url_encode(const uint8_t* data, size_t n) {
    std::string out;
    out.reserve((n + 2) / 3 * 4);
    for (size_t i = 0; i < n; i += 3) {
        uint32_t v = (uint32_t)data[i] << 16;
        if (i + 1 < n) v |= (uint32_t)data[i + 1] << 8;
        if (i + 2 < n) v |= data[i + 2];
        out += kB64Url[(v >> 18) & 63];
        out += kB64Url[(v >> 12) & 63];
        if (i + 1 < n) out += kB64Url[(v >> 6) & 63];
        if (i + 2 < n) out += kB64Url[v & 63];
    }
    return out;  // unpadded, like jwt_auth.py _b64url
}

bool b64url_decode(const std::string& in, std::string* out) {
    static int8_t rev[256];
    static bool init = false;
    if (!init) {
        memset(rev, -1, sizeof(rev));
        for (int i = 0; i < 64; i++) rev[(uint8_t)kB64Url[i]] = (int8_t)i;
        rev[(uint8_t)'+'] = 62;  // accept standard alphabet too
        rev[(uint8_t)'/'] = 63;
        init = true;
    }
    out->clear();
    uint32_t acc = 0;
    int bits = 0;
    for (char c : in) {
        if (c == '=') break;
        int8_t v = rev[(uint8_t)c];
        if (v < 0) return false;
        acc = (acc << 6) | (uint32_t)v;
        bits += 6;
        if (bits >= 8) {
            bits -= 8;
            out->push_back((char)((acc >> bits) & 0xFF));
        }
    }
    return true;
}

std::string jwt_key(bool write) {
    std::lock_guard<std::mutex> lk(g_jwt_mu);
    return write ? g_jwt_write_key : g_jwt_read_key;
}

// Extract a string claim ("fid") from a JSON payload minted by the
// framework/reference (flat object, no escapes inside fids).
bool json_str_claim(const std::string& json, const char* name,
                    std::string* out) {
    std::string pat = std::string("\"") + name + "\":";
    size_t p = json.find(pat);
    if (p == std::string::npos) return false;
    p += pat.size();
    while (p < json.size() && json[p] == ' ') p++;
    if (p >= json.size() || json[p] != '"') return false;
    size_t e = json.find('"', p + 1);
    if (e == std::string::npos) return false;
    *out = json.substr(p + 1, e - p - 1);
    return true;
}

bool json_num_claim(const std::string& json, const char* name,
                    int64_t* out) {
    std::string pat = std::string("\"") + name + "\":";
    size_t p = json.find(pat);
    if (p == std::string::npos) return false;
    p += pat.size();
    while (p < json.size() && json[p] == ' ') p++;
    errno = 0;
    char* endp = nullptr;
    long long v = strtoll(json.c_str() + p, &endp, 10);
    if (errno || endp == json.c_str() + p) return false;
    *out = (int64_t)v;
    return true;
}

// Verify an HS256 write/read token scoped to `fid` (guard.go:18-50 /
// jwt_auth.py decode_jwt + the fid-claim checks).  Write semantics
// accept the base fid of a count>1 assign ("fid_3" matches claim "fid",
// the file-id delta convention) and volume-level tokens ("3," claims
// authorize any fid in volume 3) — jwt_auth.py verify_write:134-140;
// read tokens compare exactly (verify_read:151).
bool jwt_verify(const std::string& key, const std::string& token,
                const std::string& fid, bool write_semantics) {
    // cache the expensive part (HMAC + base64 + claim parse) keyed by
    // (key, token); the per-fid claim check and exp re-check below stay
    // per call
    std::string cache_key;
    cache_key.reserve(key.size() + 1 + token.size());
    cache_key.append(key).push_back('\0');
    cache_key.append(token);
    JwtVerified entry;
    bool cached = false;
    {
        std::lock_guard<std::mutex> lk(g_jwt_cache_mu);
        auto it = g_jwt_cache.find(cache_key);
        if (it != g_jwt_cache.end()) {
            entry = it->second;
            cached = true;
        }
    }
    if (!cached) {
        size_t d1 = token.find('.');
        if (d1 == std::string::npos) return false;
        size_t d2 = token.find('.', d1 + 1);
        if (d2 == std::string::npos) return false;
        uint8_t mac[32];
        hmac_sha256(key, token.substr(0, d2), mac);
        std::string sig;
        if (!b64url_decode(token.substr(d2 + 1), &sig) || sig.size() != 32)
            return false;
        // constant-time compare
        uint8_t diff = 0;
        for (int i = 0; i < 32; i++) diff |= mac[i] ^ (uint8_t)sig[i];
        if (diff) return false;
        std::string payload;
        if (!b64url_decode(token.substr(d1 + 1, d2 - d1 - 1), &payload))
            return false;
        int64_t exp;
        entry.has_exp = json_num_claim(payload, "exp", &exp);
        if (entry.has_exp) entry.exp = exp;
        if (!json_str_claim(payload, "fid", &entry.fid)) return false;
        std::lock_guard<std::mutex> lk(g_jwt_cache_mu);
        if (g_jwt_cache.size() >= kJwtCacheMax) g_jwt_cache.clear();
        g_jwt_cache.emplace(std::move(cache_key), entry);
    }
    if (entry.has_exp) {
        int64_t now = (int64_t)(now_unix_ns() / 1000000000ull);
        if (now > entry.exp) return false;
    }
    const std::string& claim_fid = entry.fid;
    if (!write_semantics) return claim_fid == fid;
    if (claim_fid == fid.substr(0, fid.find('_'))) return true;
    return !claim_fid.empty() && claim_fid.back() == ',' &&
           fid.rfind(claim_fid, 0) == 0;
}

// Mint a write token for an assign reply (jwt.go GenJwtForVolumeServer).
std::string jwt_mint(const std::string& key, const std::string& fid,
                     int expire_s) {
    static const char* header_b64 =
        "eyJhbGciOiJIUzI1NiIsInR5cCI6IkpXVCJ9";  // {"alg":"HS256","typ":"JWT"}
    std::string claims = "{\"fid\":\"" + fid + "\"";
    if (expire_s > 0) {
        int64_t now = (int64_t)(now_unix_ns() / 1000000000ull);
        claims += ",\"exp\":" + std::to_string(now + expire_s);
    }
    claims += "}";
    std::string signing = std::string(header_b64) + "." +
                          b64url_encode((const uint8_t*)claims.data(),
                                        claims.size());
    uint8_t mac[32];
    hmac_sha256(key, signing, mac);
    return signing + "." + b64url_encode(mac, 32);
}

// Verify + extract the payload from a full needle record blob: size and
// cookie checks, CRC over data, store-side-gzip decompression
// (needle_read.go ReadBytes:52-95 + the HTTP handler's encoding rules)
Reply finish_needle_read(const std::string& blob, int32_t size, int version,
                         uint32_t cookie) {
    const uint8_t* b = (const uint8_t*)blob.data();
    int64_t actual = (int64_t)blob.size();
    uint32_t rec_cookie = get_be32(b);
    int32_t rec_size = (int32_t)get_be32(b + 12);
    if (rec_size != size) return {500, "size mismatch"};
    if (rec_cookie != cookie) return {404, "cookie mismatch"};
    int64_t data_off, data_len;
    if (!parse_needle_data(b, actual, size, version, &data_off, &data_len))
        return {500, "bad needle"};
    if (size > 0) {
        uint32_t stored = get_be32(b + kHeaderSize + size);
        uint32_t got = crc32c(b + data_off, (size_t)data_len);
        if (stored != got && stored != crc_legacy_value(got))
            return {500, "CRC error! Data On Disk Corrupted"};
    }
    std::string data = blob.substr((size_t)data_off, (size_t)data_len);
    if (version != 1 && data_len > 0 &&
        data_off + data_len < kHeaderSize + size) {
        uint8_t flags = b[data_off + data_len];
        if (flags & 0x01) {  // IS_COMPRESSED: stored gzip, serve plain
            std::string plain;
            if (!gunzip(data, &plain)) return {500, "bad gzip needle"};
            data.swap(plain);
        }
    }
    return {0, std::move(data)};
}

// EC read: .ecx binary search -> interval math -> local shard preads.
// Exactly ec_volume.py locate_needle/read_needle (themselves the
// bit-for-bit port of ec_locate.go + SearchNeedleFromSortedIndex,
// ec_volume.go:206-255); any non-local interval answers 307 so the
// Python ladder (remote fetch / reconstruct) takes over.
Reply handle_ec_read(const EcPtr& ev, uint64_t nid, uint32_t cookie) {
    int64_t lo = 0, hi = ev->ecx_entries.load() - 1;
    uint64_t off = 0;
    int32_t size = 0;
    bool found = false;
    uint8_t e[16];
    while (lo <= hi) {
        int64_t mid = lo + (hi - lo) / 2;
        if (!pread_full(ev->ecx_fd, e, 16, mid * 16))
            return {500, "ecx read failed"};
        uint64_t k = get_be64(e);
        if (k == nid) {
            off = (uint64_t)get_be32(e + 8) * kPaddingSize;
            size = (int32_t)get_be32(e + 12);
            found = true;
            break;
        }
        if (k < nid) lo = mid + 1;
        else hi = mid - 1;
    }
    if (!found) return {404, "not found"};
    if (size < 0) return {404, "already deleted"};
    int64_t shard_size = ev->shard_size.load();
    if (shard_size <= 0) return {307, "no local shards"};

    const int64_t lb = ev->large_block, sb = ev->small_block;
    const int64_t dat_size = 10 * shard_size;
    int64_t actual = get_actual_size(size, ev->version);
    // _locate_offset (ec_locate.go:55-75)
    int64_t large_row_size = lb * 10;
    int64_t rows_by_size = dat_size / large_row_size;
    int64_t block_index, inner;
    bool is_large;
    int64_t pos = (int64_t)off;
    if (pos < rows_by_size * large_row_size) {
        block_index = pos / lb;
        is_large = true;
        inner = pos % lb;
    } else {
        pos -= rows_by_size * large_row_size;
        block_index = pos / sb;
        is_large = false;
        inner = pos % sb;
    }
    // large-row count derivable from shard size (ec_locate.go:18-19)
    int64_t n_large_rows = (dat_size + 10 * sb) / (lb * 10);

    std::string blob((size_t)actual, '\0');
    int64_t want = actual, wrote = 0;
    while (want > 0) {
        int64_t block_len = is_large ? lb : sb;
        int64_t take = std::min(want, block_len - inner);
        // ToShardIdAndOffset (ec_locate.go:77-87)
        int64_t row = block_index / 10;
        int64_t ec_off = inner +
                         (is_large ? row * lb : n_large_rows * lb + row * sb);
        int sid = (int)(block_index % 10);
        int fd = ev->shard_fds[sid].load();
        if (fd >= 0) {
            if (!pread_full(fd, (uint8_t*)blob.data() + wrote,
                            (size_t)take, ec_off))
                return {500, "short shard read"};
        } else {
            // degraded read: rebuild this span from 10 local survivors
            // using the daemon-pushed recovery row; a wrong plan can
            // never serve silently — the needle CRC check downstream
            // rejects it
            EcRecovery rec;
            if (!ev->get_recovery(sid, &rec))
                return {307, "shard not local"};
            std::string sur((size_t)take, '\0');
            uint8_t* out = (uint8_t*)blob.data() + wrote;
            memset(out, 0, (size_t)take);
            const uint8_t (*mt)[256] = gf_mul();
            for (int j = 0; j < 10; j++) {
                int sfd = ev->shard_fds[rec.survivors[j]].load();
                if (sfd < 0) return {307, "survivor not local"};
                if (!pread_full(sfd, (uint8_t*)sur.data(), (size_t)take,
                                ec_off))
                    return {500, "short survivor read"};
                const uint8_t* row = mt[rec.coeffs[j]];
                const uint8_t* in = (const uint8_t*)sur.data();
                for (int64_t k = 0; k < take; k++) out[k] ^= row[in[k]];
            }
        }
        wrote += take;
        want -= take;
        block_index++;
        if (is_large && block_index == n_large_rows * 10) {
            is_large = false;
            block_index = 0;
        }
        inner = 0;
    }
    return finish_needle_read(blob, size, ev->version, cookie);
}

Reply handle_read(uint32_t vid, uint64_t nid, uint32_t cookie,
                  bool* was_ec = nullptr) {
    auto v = serving_vol(vid);
    if (!v) {
        auto ev = serving_ec(vid);
        if (ev) {
            if (was_ec) *was_ec = true;
            return handle_ec_read(ev, nid, cookie);
        }
        return {307, "volume not served natively"};
    }
    uint64_t off;
    int32_t size;
    {
        std::shared_lock<std::shared_mutex> lk(v->nm.mu);
        if (!v->nm.get(nid, &off, &size)) return {404, "not found"};
    }
    if (off == 0 || size == kTombstone) return {404, "not found"};
    if (size < 0) return {404, "already deleted"};
    int64_t actual = get_actual_size(size, v->version);
    std::string blob((size_t)actual, '\0');
    if (!pread_full(v->dat_fd, (uint8_t*)blob.data(), (size_t)actual,
                    (int64_t)off))
        return {500, "short read"};
    int64_t ttl = v->ttl_sec.load();
    if (ttl > 0) {
        // TTL volumes serve natively too; expired needles answer 404
        // exactly like the HTTP handler (volume_read.go:27-35)
        int64_t lm = needle_last_modified(
            (const uint8_t*)blob.data(), actual, size, v->version);
        int64_t now_s = (int64_t)(now_unix_ns() / 1000000000ull);
        if (lm > 0 && now_s >= lm + ttl) return {404, "expired"};
    }
    return finish_needle_read(blob, size, v->version, cookie);
}

// ---------------------------------------------------------------------------
// Replica fan-out: native->native framed forwarding for writes/deletes
// on replicated volumes (store_replicate.go:24-141: write locally, then
// every other location must succeed).  The daemon pushes each vid's
// peer fast-path addresses (svn_set_replicas); a write marked
// replicate ('R') never fans out again.
// ---------------------------------------------------------------------------

int fwd_connect(const std::string& addr) {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) return -1;
    std::string host = addr.substr(0, colon);
    std::string port = addr.substr(colon + 1);
    struct addrinfo hints {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0)
        return -1;
    int fd = -1;
    for (auto* ai = res; ai; ai = ai->ai_next) {
        fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        struct timeval tv {2, 0};
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        struct timeval rtv {10, 0};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &rtv, sizeof(rtv));
        if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
        close(fd);
        fd = -1;
    }
    freeaddrinfo(res);
    if (fd >= 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    return fd;
}

bool fwd_send_all(int fd, const char* data, size_t n) {
    size_t sent = 0;
    while (sent < n) {
        ssize_t r = send(fd, data + sent, n - sent, MSG_NOSIGNAL);
        if (r <= 0) return false;
        sent += (size_t)r;
    }
    return true;
}

// Group-commit forward mux: concurrent forwards to one peer coalesce
// into a single pipelined batch on a shared connection (one send +
// in-order reply reads per batch, like the fsync ticket batching),
// instead of 2 syscalls each way per write on per-thread pooled
// sockets.  The peer's serve_conn drains pipelined frames from its
// buffered recv, so a batch of N costs O(1) wakeups on both sides.
struct FwdItem {
    const std::string* frame;
    uint32_t status = 0;
    bool reached = false;
    bool done = false;
};

struct FwdMux {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<FwdItem*> queue;
    bool leader = false;  // a thread is running a batch on fd
    int fd = -1;          // only the leader touches the socket
};

std::mutex g_fwd_mu;
std::unordered_map<std::string, std::unique_ptr<FwdMux>> g_fwd_muxes;

FwdMux* fwd_mux(const std::string& addr) {
    std::lock_guard<std::mutex> lk(g_fwd_mu);
    auto& m = g_fwd_muxes[addr];
    if (!m) m.reset(new FwdMux());
    return m.get();
}

// Send every queued frame in one write, then read the replies back in
// order (replies on a fast-path connection are strictly sequential).
// Retries the whole batch once on a stale socket — safe for the same
// reason the old per-frame retry was: replicate writes/deletes are
// idempotent, and the Python fallback dedups identical rewrites.
void fwd_run_batch(FwdMux* mux, const std::string& addr,
                   std::vector<FwdItem*>& batch) {
    std::string out;
    size_t total = 0;
    for (FwdItem* it : batch) total += it->frame->size();
    out.reserve(total);
    for (FwdItem* it : batch) out += *it->frame;
    for (int attempt = 0; attempt < 2; attempt++) {
        if (mux->fd < 0) mux->fd = fwd_connect(addr);
        if (mux->fd < 0) return;  // peer unreachable: all stay !reached
        if (!fwd_send_all(mux->fd, out.data(), out.size())) {
            close(mux->fd);
            mux->fd = -1;
            continue;  // stale pooled socket: reconnect, resend batch
        }
        // buffered in-order reply parse: one recv drains many replies,
        // instead of two exact-size recvs per reply
        std::string rbuf;
        size_t off = 0;
        auto ensure = [&](size_t n) -> bool {
            while (rbuf.size() - off < n) {
                char tmp[16384];
                ssize_t r = recv(mux->fd, tmp, sizeof(tmp), 0);
                if (r <= 0) return false;
                rbuf.append(tmp, (size_t)r);
            }
            return true;
        };
        size_t i = 0;
        for (; i < batch.size(); i++) {
            if (!ensure(8)) break;
            const uint8_t* hdr = (const uint8_t*)rbuf.data() + off;
            uint32_t plen = get_be32(hdr + 4);
            batch[i]->status = get_be32(hdr);
            off += 8;
            if (plen && !ensure(plen)) break;
            off += plen;
            batch[i]->reached = true;
        }
        if (i == batch.size()) return;
        close(mux->fd);  // mid-batch drop: reconnect and retry once
        mux->fd = -1;
        for (FwdItem* it : batch) it->reached = false;
    }
}

// One framed request/reply against a peer fast-path port; returns false
// only when the peer is unreachable, otherwise *status carries the
// peer's reply code.  Requests riding in concurrently batch together.
bool fwd_request(const std::string& addr, const std::string& frame,
                 uint32_t* status) {
    FwdMux* mux = fwd_mux(addr);
    FwdItem item;
    item.frame = &frame;
    std::unique_lock<std::mutex> lk(mux->mu);
    mux->queue.push_back(&item);
    while (!item.done) {
        if (!mux->leader) {
            mux->leader = true;
            std::vector<FwdItem*> batch(mux->queue.begin(),
                                        mux->queue.end());
            mux->queue.clear();
            lk.unlock();
            fwd_run_batch(mux, addr, batch);
            lk.lock();
            for (FwdItem* it : batch) it->done = true;
            mux->leader = false;
            mux->cv.notify_all();
        } else {
            mux->cv.wait(lk, [&] {
                return item.done || !mux->leader;
            });
        }
    }
    *status = item.status;
    return item.reached;
}

// Fan a verified local write/delete out to the vid's other locations.
// 0 = all replicas acked; 307 = can't forward natively (the client
// falls back to the Python handler, whose fan-out + identical-rewrite
// dedup make the retry safe); 500 = a replica hard-failed.
uint32_t forward_to_replicas(uint32_t vid, const std::string& fid,
                             const std::string* body,
                             const std::string& jwt, int needed) {
    std::vector<std::string> addrs;
    {
        std::shared_lock<std::shared_mutex> lk(g_replica_mu);
        auto it = g_replicas.find(vid);
        if (it != g_replicas.end()) addrs = it->second;
    }
    if ((int)addrs.size() < needed) return 307;
    std::string frame;
    if (body) {
        frame = "W " + fid + " " + std::to_string(body->size());
        if (!jwt.empty()) frame += " " + jwt;
        frame += " R\n";
        frame += *body;
    } else {
        frame = "D " + fid;
        if (!jwt.empty()) frame += " " + jwt;
        frame += " R\n";
    }
    // one peer: forward inline; several: in parallel like the
    // reference's per-location goroutines (store_replicate.go:63-100),
    // so latency is max(peer RTTs) rather than their sum
    auto classify = [](bool reached, uint32_t status) -> uint32_t {
        if (!reached || status == 307) return 307;
        // 4xx from a peer = it cannot take framed replicate writes
        // (e.g. the Python read-only TCP loop answers 400, or its JWT
        // clock disagrees): hand the whole write to the Python handler
        // rather than failing it — only genuine replica errors (5xx)
        // fail the write, like store_replicate.go
        if (status >= 400 && status < 500) return 307;
        return status == 0 ? 0 : 500;
    };
    if (addrs.size() == 1) {
        uint32_t status = 0;
        bool reached = fwd_request(addrs[0], frame, &status);
        return classify(reached, status);
    }
    std::vector<uint32_t> results(addrs.size(), 500);
    std::vector<std::thread> threads;
    threads.reserve(addrs.size());
    for (size_t i = 0; i < addrs.size(); i++) {
        threads.emplace_back([&, i]() {
            uint32_t status = 0;
            bool reached = fwd_request(addrs[i], frame, &status);
            results[i] = classify(reached, status);
        });
    }
    for (auto& t : threads) t.join();
    uint32_t worst = 0;
    for (uint32_t r : results) {
        if (r == 500) return 500;  // hard replica failure wins
        if (r != 0) worst = r;     // else any 307 -> fallback
    }
    return worst;
}

std::string json_write_reply(int64_t size, uint32_t crc) {
    char etag[16];
    snprintf(etag, sizeof(etag), "%08x", crc);
    char out[96];
    snprintf(out, sizeof(out),
             "{\"name\": \"\", \"size\": %lld, \"eTag\": \"%s\"}",
             (long long)size, etag);
    return out;
}

Reply handle_write(uint32_t vid, uint64_t nid, uint32_t cookie,
                   const std::string& body, const std::string& fid,
                   bool is_replicate, const std::string& jwt) {
    auto v = serving_vol(vid);
    if (!v) return {307, "volume not served natively"};
    if (!v->writable.load() || v->read_only.load() || v->version != 3)
        return {307, "native writes disabled for this volume"};
    std::string wkey = jwt_key(true);
    if (!wkey.empty() && !jwt_verify(wkey, jwt, fid, true))
        return {401, "unauthorized"};
    int extra = v->extra_copies.load();
    if (!is_replicate && extra > 0) {
        // check forwardability BEFORE the local append: if the replica
        // set is unknown, 307 now and let the Python handler own the
        // whole replicated write
        std::shared_lock<std::shared_mutex> lk(g_replica_mu);
        auto it = g_replicas.find(vid);
        if (it == g_replicas.end() || (int)it->second.size() < extra)
            return {307, "replica set not configured"};
    }
    int64_t dlen = (int64_t)body.size();
    uint32_t crc = crc32c((const uint8_t*)body.data(), (size_t)dlen);
    // v3 needle with data + HAS_LAST_MODIFIED (what the HTTP write path
    // produces for a plain body: needle.py Needle.create), plus the
    // volume's TTL on TTL volumes (needle.py stamps ttl the same way;
    // without it the needle would never expire or vacuum)
    uint32_t ttl_raw = v->ttl_sec.load() > 0 ? v->ttl_raw.load() : 0;
    int64_t size = dlen
        ? 4 + dlen + 1 + kLastModifiedBytes + (ttl_raw ? kTtlBytes : 0)
        : 0;
    if (size > INT32_MAX) return {413, "entity too large"};

    // cookie check + identical-rewrite dedup against the existing needle
    // (volume_write.go:34-53,143-160)
    uint64_t old_off = 0;
    int32_t old_size = 0;
    bool have_old;
    {
        std::shared_lock<std::shared_mutex> lk(v->nm.mu);
        have_old = v->nm.get(nid, &old_off, &old_size);
        if ((int64_t)v->nm.content_bytes + get_actual_size(size, 3) >
            kMaxVolumeSize)
            return {500, "volume size limit exceeded"};
    }
    if (have_old && old_off > 0 && old_size >= 0) {
        uint8_t hdr[kHeaderSize];
        if (!pread_full(v->dat_fd, hdr, kHeaderSize, (int64_t)old_off))
            return {500, "short read"};
        uint32_t old_cookie = get_be32(hdr);
        if (old_cookie != cookie) return {403, "mismatching cookie"};
        if (old_size > 0) {
            // identical-rewrite dedup compares cookie + data only, like
            // isFileUnchanged (volume_write.go:34-53) — metadata such as
            // last-modified does not defeat it
            int64_t actual = get_actual_size(old_size, v->version);
            std::string old_blob((size_t)actual, '\0');
            int64_t doff, dl;
            if (pread_full(v->dat_fd, (uint8_t*)old_blob.data(),
                           (size_t)actual, (int64_t)old_off) &&
                parse_needle_data((const uint8_t*)old_blob.data(), actual,
                                  old_size, v->version, &doff, &dl) &&
                dl == dlen &&
                memcmp(old_blob.data() + doff, body.data(), (size_t)dlen)
                    == 0)
                return {0, json_write_reply(dlen, crc)};
        }
    }

    uint64_t append_ns = now_unix_ns();
    int64_t lastmod = (int64_t)(append_ns / 1000000000ull);
    int pad = padding_length(size, 3);
    int64_t rec_len = kHeaderSize + size + kChecksumSize + kTimestampSize + pad;
    std::string rec((size_t)rec_len, '\0');
    uint8_t* p = (uint8_t*)rec.data();
    put_be32(p, cookie);
    put_be64(p + 4, nid);
    put_be32(p + 12, (uint32_t)size);
    int64_t w = kHeaderSize;
    if (dlen) {
        put_be32(p + w, (uint32_t)dlen);
        w += 4;
        memcpy(p + w, body.data(), (size_t)dlen);
        w += dlen;
        p[w++] = ttl_raw ? (kFlagHasLastModified | kFlagHasTtl)
                         : kFlagHasLastModified;
        // 5-byte big-endian seconds (needle_write.go writes the low 5
        // bytes of the u64)
        for (int i = 0; i < kLastModifiedBytes; i++)
            p[w + i] =
                (uint8_t)(lastmod >> (8 * (kLastModifiedBytes - 1 - i)));
        w += kLastModifiedBytes;
        if (ttl_raw) {  // count, unit — after lastModified (needle.py)
            p[w++] = (uint8_t)((ttl_raw >> 8) & 0xFF);
            p[w++] = (uint8_t)(ttl_raw & 0xFF);
        }
    }
    put_be32(p + w, crc);
    w += 4;
    put_be64(p + w, append_ns);

    uint64_t ticket = 0;
    {
        std::lock_guard<std::mutex> lk(v->wmu);
        // re-check under the mutex: svn_quiesce (vacuum commit) flips
        // writable then drains wmu, so no append can land after it
        if (!v->writable.load() || v->read_only.load())
            return {307, "native writes disabled for this volume"};
        int64_t end = lseek(v->dat_fd, 0, SEEK_END);
        if (end < 0 ||
            !pwrite_full(v->dat_fd, (const uint8_t*)rec.data(),
                         (size_t)rec_len, end))
            return {500, "append failed"};
        std::unique_lock<std::shared_mutex> mlk(v->nm.mu);
        if (!append_idx_entry(v.get(), nid, (uint64_t)end, (int32_t)size))
            return {500, "idx append failed"};
        v->nm.apply(nid, (uint64_t)end, (int32_t)size);
        ticket = ++v->fs_seq;
    }
    if (append_ns > v->last_append_ns.load())
        v->last_append_ns.store(append_ns);
    if (lastmod > v->last_modified_ts.load())
        v->last_modified_ts.store(lastmod);
    if (v->do_fsync.load() && !v->fsync_ticket(ticket))
        return {500, "fsync failed"};
    if (!is_replicate && extra > 0) {
        uint32_t st = forward_to_replicas(vid, fid, &body, jwt, extra);
        if (st == 307)
            // local copy stands; the Python retry dedups it
            // (isFileUnchanged) and runs its own fan-out
            return {307, "replica fan-out unavailable"};
        if (st != 0) return {500, "replica write failed"};
    }
    return {0, json_write_reply(size, crc)};
}

Reply handle_delete(uint32_t vid, uint64_t nid, uint32_t cookie,
                    const std::string& fid, bool is_replicate,
                    const std::string& jwt) {
    auto v = serving_vol(vid);
    if (!v) return {307, "volume not served natively"};
    if (!v->writable.load() || v->read_only.load() || v->version != 3)
        return {307, "native writes disabled for this volume"};
    std::string wkey = jwt_key(true);
    if (!wkey.empty() && !jwt_verify(wkey, jwt, fid, true))
        return {401, "unauthorized"};
    int extra = v->extra_copies.load();
    if (!is_replicate && extra > 0) {
        std::shared_lock<std::shared_mutex> lk(g_replica_mu);
        auto it = g_replicas.find(vid);
        if (it == g_replicas.end() || (int)it->second.size() < extra)
            return {307, "replica set not configured"};
    }
    uint64_t old_off = 0;
    int32_t old_size = 0;
    bool absent;
    {
        std::shared_lock<std::shared_mutex> lk(v->nm.mu);
        absent = !v->nm.get(nid, &old_off, &old_size) || old_size < 0;
    }
    if (absent) {
        // absent locally — but a replica may still hold it (a
        // partially-failed earlier fan-out): replicate the delete
        // unconditionally like the Python handler (_delete_object ->
        // _replicate) so orphan copies get healed
        if (!is_replicate && extra > 0) {
            uint32_t st =
                forward_to_replicas(vid, fid, nullptr, jwt, extra);
            if (st == 307) return {307, "replica fan-out unavailable"};
            if (st != 0) return {500, "replica delete failed"};
        }
        return {0, "{\"size\": 0}"};
    }
    // tombstone needle: empty v3 record (volume.py delete_needle)
    uint64_t append_ns = now_unix_ns();
    int pad = padding_length(0, 3);
    int64_t rec_len = kHeaderSize + kChecksumSize + kTimestampSize + pad;
    std::string rec((size_t)rec_len, '\0');
    uint8_t* p = (uint8_t*)rec.data();
    put_be32(p, cookie);
    put_be64(p + 4, nid);
    put_be32(p + 12, 0);
    put_be64(p + kHeaderSize + kChecksumSize, append_ns);
    uint64_t ticket = 0;
    {
        std::lock_guard<std::mutex> lk(v->wmu);
        if (!v->writable.load() || v->read_only.load())
            return {307, "native writes disabled for this volume"};
        int64_t end = lseek(v->dat_fd, 0, SEEK_END);
        if (end < 0 ||
            !pwrite_full(v->dat_fd, (const uint8_t*)rec.data(),
                         (size_t)rec_len, end))
            return {500, "append failed"};
        std::unique_lock<std::shared_mutex> mlk(v->nm.mu);
        if (!append_idx_entry(v.get(), nid, (uint64_t)end, kTombstone))
            return {500, "idx append failed"};
        v->nm.apply(nid, 0, kTombstone);
        ticket = ++v->fs_seq;
    }
    if (append_ns > v->last_append_ns.load())
        v->last_append_ns.store(append_ns);
    if (v->do_fsync.load() && !v->fsync_ticket(ticket))
        return {500, "fsync failed"};
    if (!is_replicate && extra > 0) {
        uint32_t st = forward_to_replicas(vid, fid, nullptr, jwt, extra);
        if (st == 307) return {307, "replica fan-out unavailable"};
        if (st != 0) return {500, "replica delete failed"};
    }
    char out[48];
    snprintf(out, sizeof(out), "{\"size\": %d}", old_size);
    return {0, out};
}

// ---------------------------------------------------------------------------
// Assign-lease pool: the master leases contiguous fid key ranges to the
// engine, which answers per-file assigns ("A [count]\n") off the GIL.
// The reference master serves /dir/assign from compiled Go
// (master_server_handlers.go:102-165); a GIL-bound Python handler caps
// per-file-assign workloads, so the Python master keeps authority
// (placement, growth, sequencing) and refills bounded leases here.
// ---------------------------------------------------------------------------

struct AssignLease {
    uint32_t vid;
    std::string url, public_url;
    std::atomic<uint64_t> next;
    uint64_t end;
    std::chrono::steady_clock::time_point born;
};

std::shared_mutex g_lease_mu;
std::vector<std::shared_ptr<AssignLease>> g_leases;
std::atomic<size_t> g_lease_rr{0};
std::atomic<uint64_t> g_assign_rng{0x9E3779B97F4A7C15ull};

uint64_t assign_rand() {
    // xorshift* — cookies need uniqueness pressure, not crypto (the
    // Python master uses random.getrandbits(32))
    uint64_t x = g_assign_rng.fetch_add(0x9E3779B97F4A7C15ull);
    x ^= x >> 30;
    x *= 0xBF58476D1CE4E5B9ull;
    x ^= x >> 27;
    x *= 0x94D049BB133111EBull;
    return x ^ (x >> 31);
}

// -> JSON assign reply or empty when no lease can cover `count`
std::string assign_take(int64_t count) {
    std::shared_lock<std::shared_mutex> lk(g_lease_mu);
    size_t n = g_leases.size();
    for (size_t attempt = 0; attempt < n; attempt++) {
        auto& lease = g_leases[g_lease_rr.fetch_add(1) % n];
        // CAS, not fetch_add: an oversized request must not burn the
        // lease's remaining keys on its way to failing
        uint64_t key = lease->next.load();
        bool got = false;
        while (key + (uint64_t)count <= lease->end + 1 &&
               key <= lease->end) {
            if (lease->next.compare_exchange_weak(
                    key, key + (uint64_t)count)) {
                got = true;
                break;
            }
        }
        if (!got) continue;  // exhausted or count doesn't fit: next lease
        uint32_t cookie = (uint32_t)assign_rand();
        char fid[64];
        snprintf(fid, sizeof(fid), "%u,%llx%08x", lease->vid,
                 (unsigned long long)key, cookie);
        std::string out = "{\"fid\": \"";
        out += fid;
        out += "\", \"url\": \"" + lease->url + "\", \"publicUrl\": \"" +
               lease->public_url + "\", \"count\": " +
               std::to_string(count);
        // JWT-secured clusters: mint the fid-scoped write token the
        // master would have attached (/dir/assign "auth" field)
        std::string wkey = jwt_key(true);
        if (!wkey.empty()) {
            int exp;
            {
                std::lock_guard<std::mutex> jlk(g_jwt_mu);
                exp = g_jwt_expire_s;
            }
            out += ", \"auth\": \"" + jwt_mint(wkey, fid, exp) + "\"";
        }
        out += "}";
        return out;
    }
    return "";
}

// ---------------------------------------------------------------------------
// Framed-TCP server (same wire protocol as the Python TCP fast path:
// text command line, ">II"-framed replies)
// ---------------------------------------------------------------------------

struct Server {
    int listen_fd = -1;
    std::atomic<bool> stop{false};
    std::atomic<int> active_conns{0};
    std::thread accept_thread;
    std::mutex conns_mu;
    std::vector<int> conns;
};

Server* g_server = nullptr;
std::mutex g_server_mu;
std::string g_http_redirect;  // "host:port" of the full HTTP handler
std::atomic<int> g_server_port{0};  // bound port (0 = not running)

bool recv_some(int fd, std::string& buf);

// Minimal HTTP/1.1 reply on the fast-path port (keep-alive).  Only
// plain needle GET/HEADs are answered here; anything else 302s to the
// full Python handler (g_http_redirect).
bool send_http_reply(int fd, int status, const char* reason,
                     const std::string& body, bool head,
                     const std::string& extra_headers) {
    // compose in std::string: extra_headers carries a client-chosen
    // request target (302 Location), so no fixed-size buffer is safe
    std::string out = "HTTP/1.1 " + std::to_string(status) + " " + reason +
                      "\r\nContent-Length: " + std::to_string(body.size()) +
                      "\r\nContent-Type: application/octet-stream\r\n" +
                      extra_headers + "Connection: keep-alive\r\n\r\n";
    if (!head) out += body;
    size_t sent = 0;
    while (sent < out.size()) {
        ssize_t r = send(fd, out.data() + sent, out.size() - sent, 0);
        if (r <= 0) return false;
        sent += (size_t)r;
    }
    return true;
}

// Percent-escape control characters in a client-supplied request target
// before echoing it into a Location header — a bare CR/LF (or any
// control byte) in the target must never become header structure.
std::string sanitize_target(const std::string& target) {
    std::string out;
    out.reserve(target.size());
    for (unsigned char c : target) {
        if (c < 0x21 || c == 0x7f) {
            char esc[4];
            snprintf(esc, sizeof(esc), "%%%02X", c);
            out += esc;
        } else {
            out += (char)c;
        }
    }
    return out;
}

// Handle one HTTP request whose request line is already parsed off
// `buf` (headers still pending).  Returns false to drop the connection.
bool serve_http_request(Server* srv, int fd, const std::string& method,
                        const std::string& raw_target, std::string& buf) {
    // drain headers until the blank line; keep the bearer token in case
    // the cluster signs reads
    std::string auth_jwt;
    for (;;) {
        size_t nl;
        while ((nl = buf.find('\n')) == std::string::npos) {
            if (!recv_some(fd, buf)) return false;
            if (srv->stop.load()) return false;
        }
        std::string line = buf.substr(0, nl);
        buf.erase(0, nl + 1);
        if (!line.empty() && line.back() == '\r') line.pop_back();
        if (line.empty()) break;
        if (line.size() > 15 &&
            strncasecmp(line.c_str(), "authorization:", 14) == 0) {
            size_t p = 14;
            while (p < line.size() && line[p] == ' ') p++;
            if (strncasecmp(line.c_str() + p, "bearer ", 7) == 0)
                auth_jwt = line.substr(p + 7);
        }
    }
    bool head = (method == "HEAD");
    const std::string target = sanitize_target(raw_target);
    std::string path = target;
    size_t q = path.find('?');
    bool has_query = q != std::string::npos;
    if (has_query) {
        // a bare ?jwt=<token> stays on the fast path (the reference's
        // query-parameter token convention, security/jwt.go GetJwt);
        // any other parameter means full-handler semantics -> 302
        std::string query = path.substr(q + 1);
        path = path.substr(0, q);
        bool only_jwt = true;
        size_t pos = 0;
        while (pos <= query.size() && only_jwt) {
            size_t amp = query.find('&', pos);
            std::string kv = query.substr(
                pos, amp == std::string::npos ? std::string::npos
                                              : amp - pos);
            if (!kv.empty()) {
                if (kv.rfind("jwt=", 0) == 0) {
                    if (auth_jwt.empty()) auth_jwt = kv.substr(4);
                } else {
                    only_jwt = false;
                }
            }
            if (amp == std::string::npos) break;
            pos = amp + 1;
        }
        has_query = !only_jwt;
    }
    uint32_t vid;
    uint64_t nid;
    uint32_t cookie;
    std::string fid = path.substr(path.find('/') == 0 ? 1 : 0);
    // volume-server fid paths may use "vid/fid" form; normalize to comma
    size_t slash = fid.find('/');
    if (slash != std::string::npos) fid[slash] = ',';
    if (has_query || !parse_fid(fid, &vid, &nid, &cookie)) {
        g_stat_fallbacks.fetch_add(1);  // 302 = the HTTP-shaped 307
        if (g_http_redirect.empty())
            return send_http_reply(fd, 404, "Not Found", "not found",
                                   head, "");
        return send_http_reply(
            fd, 302, "Found", "", head,
            "Location: http://" + g_http_redirect + target + "\r\n");
    }
    std::string rkey = jwt_key(false);
    if (!rkey.empty() && !jwt_verify(rkey, auth_jwt, fid, false)) {
        count_reply(401);
        return send_http_reply(fd, 401, "Unauthorized", "unauthorized",
                               head, "");
    }
    Reply r = handle_read(vid, nid, cookie);
    count_reply(r.status);
    if (r.status == 0)
        return send_http_reply(fd, 200, "OK", r.payload, head,
                               "Accept-Ranges: bytes\r\n");
    if (r.status == 307) {
        if (g_http_redirect.empty())
            return send_http_reply(fd, 404, "Not Found", r.payload, head,
                                   "");
        return send_http_reply(
            fd, 302, "Found", "", head,
            "Location: http://" + g_http_redirect + target + "\r\n");
    }
    if (r.status == 404)
        return send_http_reply(fd, 404, "Not Found", r.payload, head, "");
    return send_http_reply(fd, 500, "Internal Server Error", r.payload,
                           head, "");
}

bool recv_some(int fd, std::string& buf) {
    char tmp[16384];
    ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
    if (r <= 0) return false;
    buf.append(tmp, (size_t)r);
    return true;
}

// Reply outbox: framed replies accumulate and go out in one send just
// before the connection would block on recv.  A pipelined batch (the
// replica side of the forward mux) then costs one reply syscall and
// one peer wakeup instead of one per frame; unpipelined clients see a
// flush per request, exactly like the old per-reply writev.
struct Outbox {
    int fd;
    std::string pending;

    bool queue(uint32_t status, const std::string& payload) {
        size_t n = pending.size();
        pending.resize(n + 8);
        put_be32((uint8_t*)&pending[n], status);
        put_be32((uint8_t*)&pending[n] + 4, (uint32_t)payload.size());
        pending += payload;
        if (pending.size() >= 131072) return flush();
        return true;
    }

    bool flush() {
        if (pending.empty()) return true;
        bool ok = fwd_send_all(fd, pending.data(), pending.size());
        pending.clear();
        return ok;
    }
};

void serve_conn(Server* srv, int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::string buf;
    Outbox ob{fd};
    while (!srv->stop.load()) {
        size_t nl;
        while ((nl = buf.find('\n')) == std::string::npos) {
            if (!ob.flush() || !recv_some(fd, buf)) goto done;
            if (srv->stop.load()) goto done;
        }
        {
            std::string line = buf.substr(0, nl);
            buf.erase(0, nl + 1);
            if (!line.empty() && line.back() == '\r') line.pop_back();
            // tokenize
            std::vector<std::string> parts;
            size_t i = 0;
            while (i < line.size()) {
                while (i < line.size() && line[i] == ' ') i++;
                size_t j = i;
                while (j < line.size() && line[j] != ' ') j++;
                if (j > i) parts.push_back(line.substr(i, j - i));
                i = j;
            }
            if (parts.empty()) {
                if (!ob.queue(400, "bad request")) goto done;
                continue;
            }
            const std::string& op = parts[0];
            uint32_t vid;
            uint64_t nid;
            uint32_t cookie;
            if ((op == "GET" || op == "HEAD") && parts.size() == 3) {
                // plain HTTP clients may hit the fast-path port too
                g_stat_http_reads.fetch_add(1);
                if (!ob.flush() ||
                    !serve_http_request(srv, fd, op, parts[1], buf))
                    goto done;
            } else if (op == "G"
                       && (parts.size() == 2 || parts.size() == 3)) {
                if (!parse_fid(parts[1], &vid, &nid, &cookie)) {
                    g_stat_reads.fetch_add(1);
                    g_stat_errors.fetch_add(1);
                    if (!ob.queue(400, "bad fid")) goto done;
                    continue;
                }
                std::string rkey = jwt_key(false);
                if (!rkey.empty() &&
                    !jwt_verify(rkey,
                                parts.size() == 3 ? parts[2] : "",
                                parts[1], false)) {
                    g_stat_reads.fetch_add(1);
                    count_reply(401);
                    if (!ob.queue(401, "unauthorized")) goto done;
                    continue;
                }
                bool was_ec = false;
                Reply r = handle_read(vid, nid, cookie, &was_ec);
                // exactly one type per request: framed reads split into
                // read/ec_read by the path that served them
                (was_ec ? g_stat_ec_reads : g_stat_reads).fetch_add(1);
                count_reply(r.status);
                if (!ob.queue(r.status, r.payload)) goto done;
            } else if (op == "W" && parts.size() >= 3
                       && parts.size() <= 5) {
                errno = 0;
                long long blen = strtoll(parts[2].c_str(), nullptr, 10);
                if (errno || blen < 0 || blen > INT32_MAX) {
                    // body length unknowable: the stream cannot be
                    // resynchronized, so reply and drop the connection
                    ob.queue(400, "bad length");
                    goto done;
                }
                while (buf.size() < (size_t)blen) {
                    if (!ob.flush() || !recv_some(fd, buf)) goto done;
                }
                std::string body = buf.substr(0, (size_t)blen);
                buf.erase(0, (size_t)blen);
                if (!parse_fid(parts[1], &vid, &nid, &cookie)) {
                    // body already drained: framing stays intact
                    if (!ob.queue(400, "bad fid")) goto done;
                    continue;
                }
                // optional trailing tokens: a write JWT and/or the
                // replicate marker "R" (a JWT always contains '.')
                std::string jwt;
                bool is_replicate = false;
                for (size_t t = 3; t < parts.size(); t++) {
                    if (parts[t] == "R") is_replicate = true;
                    else if (parts[t] != "-") jwt = parts[t];
                }
                g_stat_writes.fetch_add(1);
                Reply r = handle_write(vid, nid, cookie, body, parts[1],
                                       is_replicate, jwt);
                count_reply(r.status);
                if (!ob.queue(r.status, r.payload)) goto done;
            } else if (op == "A" && parts.size() <= 2) {
                long long count = 1;
                if (parts.size() == 2) {
                    errno = 0;
                    count = strtoll(parts[1].c_str(), nullptr, 10);
                    if (errno || count <= 0 || count > 1000000) {
                        if (!ob.queue(400, "bad count")) goto done;
                        continue;
                    }
                }
                std::string out = assign_take(count);
                if (out.empty()) {
                    // no live lease: the client retries /dir/assign
                    if (!ob.queue(503, "no assign lease"))
                        goto done;
                    continue;
                }
                if (!ob.queue(0, out)) goto done;
            } else if (op == "D" && parts.size() >= 2
                       && parts.size() <= 4) {
                g_stat_deletes.fetch_add(1);
                if (!parse_fid(parts[1], &vid, &nid, &cookie)) {
                    if (!ob.queue(400, "bad fid")) goto done;
                    continue;
                }
                std::string jwt;
                bool is_replicate = false;
                for (size_t t = 2; t < parts.size(); t++) {
                    if (parts[t] == "R") is_replicate = true;
                    else if (parts[t] != "-") jwt = parts[t];
                }
                Reply r = handle_delete(vid, nid, cookie, parts[1],
                                        is_replicate, jwt);
                count_reply(r.status);
                if (!ob.queue(r.status, r.payload)) goto done;
            } else {
                if (!ob.queue(400, "bad request")) goto done;
            }
        }
    }
done:
    ob.flush();  // best effort: drop queued replies with the conn
    close(fd);
    {
        std::lock_guard<std::mutex> lk(srv->conns_mu);
        for (auto it = srv->conns.begin(); it != srv->conns.end(); ++it) {
            if (*it == fd) {
                srv->conns.erase(it);
                break;
            }
        }
    }
    // LAST touch of srv: svn_server_stop spins on this before delete
    srv->active_conns.fetch_sub(1);
}

}  // namespace

extern "C" {

// -- assign leases ----------------------------------------------------------

int svn_assign_add_lease(uint32_t vid, const char* url,
                         const char* public_url, uint64_t key_start,
                         uint64_t key_end) {
    auto lease = std::make_shared<AssignLease>();
    lease->vid = vid;
    lease->url = url;
    lease->public_url = public_url && *public_url ? public_url : url;
    lease->next.store(key_start);
    lease->end = key_end;
    lease->born = std::chrono::steady_clock::now();
    std::unique_lock<std::shared_mutex> lk(g_lease_mu);
    g_leases.push_back(std::move(lease));
    return 0;
}

// Remaining assignable keys across live leases; prunes exhausted ones
// and (when max_age_ms > 0) leases older than max_age_ms, so placement
// staleness expires per-lease instead of via a global clear that would
// stall every assigner at once.
int64_t svn_assign_remaining(int64_t max_age_ms) {
    auto now = std::chrono::steady_clock::now();
    std::unique_lock<std::shared_mutex> lk(g_lease_mu);
    int64_t total = 0;
    for (auto it = g_leases.begin(); it != g_leases.end();) {
        uint64_t next = (*it)->next.load();
        bool expired =
            max_age_ms > 0 &&
            std::chrono::duration_cast<std::chrono::milliseconds>(
                now - (*it)->born)
                    .count() > max_age_ms;
        if (next > (*it)->end || expired) {
            it = g_leases.erase(it);
        } else {
            total += (int64_t)((*it)->end - next + 1);
            ++it;
        }
    }
    return total;
}

int svn_assign_clear() {
    std::unique_lock<std::shared_mutex> lk(g_lease_mu);
    g_leases.clear();
    return 0;
}

// Where the fast-path port 302s HTTP requests it cannot serve (the
// volume server's full handler).  Set before svn_server_start.
int svn_server_set_redirect(const char* addr) {
    std::lock_guard<std::mutex> lk(g_server_mu);
    g_http_redirect = addr ? addr : "";
    return 0;
}

// Start the native fast-path server; returns the bound port or -errno.
int svn_server_start(const char* host, int port) {
    std::lock_guard<std::mutex> lk(g_server_mu);
    if (g_server) return -EALREADY;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        // hostname (e.g. "localhost", a configured DNS name): resolve it
        // rather than silently binding loopback and advertising a port
        // nobody can reach
        struct addrinfo hints {};
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_STREAM;
        struct addrinfo* res = nullptr;
        if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
            close(fd);
            return -EADDRNOTAVAIL;
        }
        addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
        freeaddrinfo(res);
    }
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        // requested port taken: fall back to ephemeral (clients discover
        // the real port via /admin/status, volume_server/server.py)
        addr.sin_port = 0;
        if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            int e = errno;
            close(fd);
            return -e;
        }
    }
    if (listen(fd, 256) != 0) {
        int e = errno;
        close(fd);
        return -e;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, (sockaddr*)&addr, &alen);
    int bound = ntohs(addr.sin_port);
    auto* srv = new Server();
    srv->listen_fd = fd;
    srv->accept_thread = std::thread([srv]() {
        while (!srv->stop.load()) {
            int cfd = accept(srv->listen_fd, nullptr, nullptr);
            if (cfd < 0) {
                if (srv->stop.load()) return;
                continue;
            }
            {
                std::lock_guard<std::mutex> lk(srv->conns_mu);
                srv->conns.push_back(cfd);
            }
            srv->active_conns.fetch_add(1);
            std::thread(serve_conn, srv, cfd).detach();
        }
    });
    g_server = srv;
    g_server_port.store(bound);
    return bound;
}

// Bound port of the process-wide native listener (0 = none).  In
// combined master+volume processes the registry is shared, so whichever
// daemon started the listener serves every command (incl. assigns).
int svn_server_port() { return g_server_port.load(); }

// out[0..6] = framed reads, ec reads, writes, deletes, http reads,
//             307 fallbacks, errors
int svn_server_stats(int64_t* out) {
    out[0] = g_stat_reads.load();
    out[1] = g_stat_ec_reads.load();
    out[2] = g_stat_writes.load();
    out[3] = g_stat_deletes.load();
    out[4] = g_stat_http_reads.load();
    out[5] = g_stat_fallbacks.load();
    out[6] = g_stat_errors.load();
    return 0;
}

int svn_server_stop() {
    std::lock_guard<std::mutex> lk(g_server_mu);
    if (!g_server) return 0;
    Server* srv = g_server;
    g_server = nullptr;
    g_server_port.store(0);
    srv->stop.store(true);
    shutdown(srv->listen_fd, SHUT_RDWR);
    close(srv->listen_fd);
    {
        std::lock_guard<std::mutex> clk(srv->conns_mu);
        for (int fd : srv->conns) shutdown(fd, SHUT_RDWR);
    }
    if (srv->accept_thread.joinable()) srv->accept_thread.join();
    // conn threads are detached: wait until every one has made its final
    // touch of srv (bounded; on timeout leak rather than use-after-free)
    for (int i = 0; i < 500 && srv->active_conns.load() > 0; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
    if (srv->active_conns.load() == 0) delete srv;
    return 0;
}

// ---------------------------------------------------------------------------
// Benchmark load generator (native-speed client, like the reference's
// compiled `weed benchmark` driver)
// ---------------------------------------------------------------------------

// op: 'W' writes fid[i] with a `payload_size` body; 'R' reads a random
// fid.  fids = '\n'-joined fid strings.  lat_us_out (length nreqs) gets
// per-request latency in microseconds.  Returns elapsed seconds; errors
// counted into *errors_out.
double svn_bench(const char* host, int port, int op, const char* fids,
                 int64_t nfids, int64_t nreqs, int payload_size,
                 int concurrency, float* lat_us_out, int64_t* errors_out) {
    std::vector<std::string> fid_list;
    fid_list.reserve((size_t)nfids);
    {
        const char* p = fids;
        for (int64_t i = 0; i < nfids; i++) {
            const char* e = strchr(p, '\n');
            if (!e) {
                fid_list.emplace_back(p);
                break;
            }
            fid_list.emplace_back(p, e - p);
            p = e + 1;
        }
    }
    if (fid_list.empty() || nreqs <= 0) return 0.0;
    std::string payload((size_t)payload_size, 'x');
    for (size_t i = 0; i < payload.size(); i++)
        payload[i] = (char)('a' + (i * 131 + 7) % 26);
    std::atomic<int64_t> next{0};
    std::atomic<int64_t> errors{0};
    std::atomic<int64_t> completed{0};

    auto dial = [](const std::string& h, int p) -> int {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return -1;
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_port = htons((uint16_t)p);
        if (inet_pton(AF_INET, h.c_str(), &addr.sin_addr) != 1)
            addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            return -1;
        }
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return fd;
    };

    auto worker = [&](int widx) {
        int fd = dial(host, port);
        if (fd < 0) return;  // surviving workers drain the slots;
                             // unclaimed slots are charged as errors
        std::mt19937_64 rng(0x5EEDu + (unsigned)widx);
        std::string rxbuf;
        std::string req;

        // framed request/response on an arbitrary conn (F mode talks to
        // the master AND per-volume-server conns)
        auto framed = [&](int cfd, std::string& rbuf,
                          const std::string& frame, uint32_t* st,
                          std::string* payload) -> bool {
            size_t sent = 0;
            while (sent < frame.size()) {
                ssize_t r = send(cfd, frame.data() + sent,
                                 frame.size() - sent, 0);
                if (r <= 0) return false;
                sent += (size_t)r;
            }
            while (rbuf.size() < 8)
                if (!recv_some(cfd, rbuf)) return false;
            *st = get_be32((const uint8_t*)rbuf.data());
            uint32_t plen = get_be32((const uint8_t*)rbuf.data() + 4);
            while (rbuf.size() < 8 + (size_t)plen)
                if (!recv_some(cfd, rbuf)) return false;
            if (payload) *payload = rbuf.substr(8, plen);
            rbuf.erase(0, 8 + (size_t)plen);
            return true;
        };
        std::unordered_map<std::string, int> vol_conns;
        std::unordered_map<std::string, std::string> vol_bufs;

        auto json_field = [](const std::string& j,
                             const char* key) -> std::string {
            std::string pat = std::string("\"") + key + "\": \"";
            size_t p = j.find(pat);
            if (p == std::string::npos) return "";
            p += pat.size();
            size_t e = j.find('"', p);
            return e == std::string::npos ? "" : j.substr(p, e - p);
        };

        while (true) {
            int64_t slot = next.fetch_add(1);
            if (slot >= nreqs) break;
            if (op == 'F') {
                // full per-file cycle: native assign -> native write
                // (the reference benchmark's per-file flow,
                // command/benchmark.go writeFiles)
                auto t0 = std::chrono::steady_clock::now();
                uint32_t st = 500;
                std::string assign;
                bool master_ok = framed(fd, rxbuf, "A\n", &st, &assign);
                // a 503 is a transient lease drought (refill ticks every
                // 0.2 s): wait briefly like a real client would fall
                // back, instead of charging an instant error
                for (int retry = 0; master_ok && st == 503 && retry < 50;
                     retry++) {
                    std::this_thread::sleep_for(
                        std::chrono::milliseconds(5));
                    master_ok = framed(fd, rxbuf, "A\n", &st, &assign);
                }
                bool ok = master_ok && st == 0;
                if (ok) {
                    std::string fid = json_field(assign, "fid");
                    std::string url = json_field(assign, "url");
                    size_t colon = url.rfind(':');
                    if (fid.empty() || colon == std::string::npos) {
                        ok = false;
                    } else {
                        auto it = vol_conns.find(url);
                        if (it == vol_conns.end()) {
                            int vport =
                                atoi(url.c_str() + colon + 1) + 20000;
                            int vfd =
                                dial(url.substr(0, colon), vport);
                            if (vfd >= 0) {
                                it = vol_conns.emplace(url, vfd).first;
                                vol_bufs.emplace(url, std::string());
                            }
                            // a failed dial is NOT cached: the server
                            // may just not be listening yet
                        }
                        if (it == vol_conns.end()) {
                            ok = false;
                        } else {
                            std::string auth = json_field(assign, "auth");
                            std::string wreq =
                                "W " + fid + " " +
                                std::to_string(payload.size());
                            if (!auth.empty()) wreq += " " + auth;
                            wreq += "\n";
                            wreq += payload;
                            if (!framed(it->second, vol_bufs[url], wreq,
                                        &st, nullptr)) {
                                // dead volume conn: drop it so the next
                                // slot re-dials
                                close(it->second);
                                vol_conns.erase(it);
                                vol_bufs.erase(url);
                                ok = false;
                            } else {
                                ok = st == 0;
                            }
                        }
                    }
                }
                auto t1 = std::chrono::steady_clock::now();
                if (lat_us_out)
                    lat_us_out[slot] =
                        (float)std::chrono::duration_cast<
                            std::chrono::nanoseconds>(t1 - t0)
                            .count() /
                        1000.0f;
                completed.fetch_add(1);
                if (!ok) errors.fetch_add(1);
                if (!master_ok) break;  // master conn dead: surviving
                                        // workers drain the slots
                continue;
            }
            const std::string& entry =
                (op == 'W') ? fid_list[(size_t)(slot % nfids)]
                            : fid_list[rng() % fid_list.size()];
            // a list entry may carry a per-fid token: "fid jwt"
            // (JWT-secured clusters; the Python driver joins them)
            size_t sp = entry.find(' ');
            std::string fid = entry.substr(0, sp);
            std::string tok =
                sp == std::string::npos ? "" : entry.substr(sp + 1);
            req.clear();
            auto t0 = std::chrono::steady_clock::now();
            if (op == 'W') {
                req = "W " + fid + " " + std::to_string(payload.size());
                if (!tok.empty()) req += " " + tok;
                req += "\n";
                req += payload;
            } else if (op == 'D') {
                req = "D " + fid;
                if (!tok.empty()) req += " " + tok;
                req += "\n";
            } else if (op == 'H') {  // HTTP GET against the same port
                req = "GET /" + fid + " HTTP/1.1\r\nHost: bench\r\n\r\n";
            } else {
                req = "G " + fid;
                if (!tok.empty()) req += " " + tok;
                req += "\n";
            }
            size_t sent = 0;
            bool ok = true;
            while (sent < req.size()) {
                ssize_t r = send(fd, req.data() + sent, req.size() - sent, 0);
                if (r <= 0) {
                    ok = false;
                    break;
                }
                sent += (size_t)r;
            }
            uint32_t status = 500, plen = 0;
            if (ok && op == 'H') {
                // parse an HTTP/1.1 keep-alive response
                size_t hdr_end;
                while ((hdr_end = rxbuf.find("\r\n\r\n"))
                       == std::string::npos) {
                    if (!recv_some(fd, rxbuf)) {
                        ok = false;
                        break;
                    }
                }
                if (ok) {
                    status = (uint32_t)atoi(rxbuf.c_str() + 9);
                    if (status == 200) status = 0;
                    size_t clpos = rxbuf.find("Content-Length: ");
                    size_t body_len = 0;
                    if (clpos != std::string::npos && clpos < hdr_end)
                        body_len = (size_t)atoll(rxbuf.c_str() + clpos + 16);
                    size_t total = hdr_end + 4 + body_len;
                    while (rxbuf.size() < total) {
                        if (!recv_some(fd, rxbuf)) {
                            ok = false;
                            break;
                        }
                    }
                    if (ok) rxbuf.erase(0, total);
                }
            } else if (ok) {
                while (rxbuf.size() < 8) {
                    if (!recv_some(fd, rxbuf)) {
                        ok = false;
                        break;
                    }
                }
                if (ok) {
                    status = get_be32((const uint8_t*)rxbuf.data());
                    plen = get_be32((const uint8_t*)rxbuf.data() + 4);
                    while (rxbuf.size() < 8 + (size_t)plen) {
                        if (!recv_some(fd, rxbuf)) {
                            ok = false;
                            break;
                        }
                    }
                    if (ok) rxbuf.erase(0, 8 + (size_t)plen);
                }
            }
            auto t1 = std::chrono::steady_clock::now();
            if (lat_us_out)
                lat_us_out[slot] =
                    (float)std::chrono::duration_cast<
                        std::chrono::nanoseconds>(t1 - t0)
                        .count() /
                    1000.0f;
            completed.fetch_add(1);
            if (!ok || status != 0) errors.fetch_add(1);
            if (!ok) break;  // connection dead
        }
        for (auto& kv : vol_conns)
            if (kv.second >= 0) close(kv.second);
        close(fd);
    };

    auto start = std::chrono::steady_clock::now();
    std::vector<std::thread> threads;
    for (int i = 0; i < concurrency; i++) threads.emplace_back(worker, i);
    for (auto& t : threads) t.join();
    auto end = std::chrono::steady_clock::now();
    if (errors_out)
        *errors_out = errors.load() + (nreqs - completed.load());
    return std::chrono::duration<double>(end - start).count();
}

}  // extern "C"
