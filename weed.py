#!/usr/bin/env python3
"""weed — CLI entrypoint for the TPU-native SeaweedFS-capability store.

Subcommand surface modelled on the reference's weed/command registry
(weed/weed.go:37-84, command/command.go): master, volume, filer, s3,
server (combined), shell, benchmark, upload, download, version.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from seaweedfs_tpu.rpc.http_rpc import RpcError, call  # noqa: E402

VERSION = "seaweedfs_tpu 0.1 (RS(10,4) EC on TPU via JAX/Pallas)"


def _completion_script(subcommands) -> str:
    """Bash completion for the weed CLI (command/autocomplete.go)."""
    words = " ".join(subcommands)
    return f"""# bash completion for weed — `source <(weed autocomplete)`
_weed_complete() {{
    local cur="${{COMP_WORDS[COMP_CWORD]}}"
    if [ "$COMP_CWORD" -eq 1 ]; then
        COMPREPLY=( $(compgen -W "{words}" -- "$cur") )
    else
        COMPREPLY=( $(compgen -f -- "$cur") )
    fi
}}
complete -F _weed_complete weed weed.py"""


def _wait_forever(stoppables):
    from seaweedfs_tpu.util import grace

    # graceful shutdown via the grace hooks (also dumps any active
    # -cpuprofile/-memprofile on the way out)
    grace.on_interrupt(lambda: _stop_all(stoppables))
    signal.pause()


def _stop_all(stoppables):
    for s in reversed(stoppables):
        try:
            s.stop()
        except Exception:
            pass


def _load_guard():
    """Build a security Guard from security.toml (weed/security/guard.go)."""
    from seaweedfs_tpu.security import Guard
    from seaweedfs_tpu.util.config import load_configuration

    conf = load_configuration("security")
    return Guard(
        white_list=[w for w in
                    str(conf.get("access.ui", "") or "").split(",") if w],
        signing_key=str(conf.get("jwt.signing.key", "") or ""),
        expires_after_seconds=conf.get_int(
            "jwt.signing.expires_after_seconds", 10),
        read_signing_key=str(conf.get("jwt.signing.read.key", "") or ""),
        read_expires_after_seconds=conf.get_int(
            "jwt.signing.read.expires_after_seconds", 60))


def cmd_master(args):
    from seaweedfs_tpu.master.server import MasterServer

    # -peers wins; WEED_MASTER_PEERS covers fleet-managed deployments
    # where every master gets the same env
    peer_spec = args.peers or os.environ.get("WEED_MASTER_PEERS", "")
    peers = [p for p in peer_spec.split(",") if p]
    m = MasterServer(host=args.ip, port=args.port,
                     volume_size_limit_mb=args.volumeSizeLimitMB,
                     default_replication=args.defaultReplication,
                     pulse_seconds=args.pulseSeconds,
                     guard=_load_guard(),
                     peers=peers, raft_dir=args.mdir,
                     enable_native_assign=args.tcp,
                     join=args.join)
    m.start()
    mode = " (joining as learner)" if args.join else ""
    print(f"master listening on {m.address}{mode}" +
          (f", raft peers {m.raft.peers}" if peers else ""))
    _wait_forever([m])


def cmd_master_follower(args):
    from seaweedfs_tpu.master.follower import MasterFollower

    f = MasterFollower(args.masters.split(","), host=args.ip, port=args.port)
    f.start()
    print(f"master follower on {f.address} tracking {args.masters}")
    _wait_forever([f])


def _parse_tier_backends(specs):
    """-tier name=local:/dir or name=s3:endpoint[,accessKey,secretKey]"""
    from seaweedfs_tpu.remote_storage import RemoteConf

    confs = []
    for spec in specs or []:
        name, _, rest = spec.partition("=")
        kind, _, params = rest.partition(":")
        if kind == "local":
            confs.append(RemoteConf(name=name, type="local",
                                    directory=params))
        elif kind == "s3":
            parts = params.split(",")
            confs.append(RemoteConf(
                name=name, type="s3", endpoint=parts[0],
                access_key=parts[1] if len(parts) > 1 else "",
                secret_key=parts[2] if len(parts) > 2 else ""))
        else:
            raise ValueError(f"bad tier spec {spec!r}")
    return confs


def cmd_volume(args):
    from seaweedfs_tpu.volume_server.server import VolumeServer

    dirs = args.dir.split(",")
    maxes = [int(x) for x in args.max.split(",")] if args.max else None
    if maxes and len(maxes) == 1:
        maxes = maxes * len(dirs)
    vs = VolumeServer(dirs, args.mserver, host=args.ip, port=args.port,
                      rack=args.rack, data_center=args.dataCenter,
                      max_volume_counts=maxes,
                      pulse_seconds=args.pulseSeconds,
                      guard=_load_guard(),
                      tier_backends=_parse_tier_backends(args.tier),
                      enable_tcp=args.tcp, read_mode=args.readMode,
                      fsync=args.fsync, needle_map_kind=args.index,
                      ec_encoder_backend=args.ecBackend or None,
                      upload_limit_mb=args.concurrentUploadLimitMB,
                      download_limit_mb=args.concurrentDownloadLimitMB)
    vs.start()
    print(f"volume server listening on {vs.address}, dirs={dirs}")
    _wait_forever([vs])


def _make_filer_store(kind: str, path: str, store_address: str = "",
                      masters: str = ""):
    from seaweedfs_tpu.filer.filer_store import (PerBucketStoreRouter,
                                                 ShardedSqliteStore,
                                                 SqliteStore)

    if kind == "remote":
        # stateless filer against a shared `weed filer.store` service
        # (the redis-family HA mode, universal_redis_store.go)
        from seaweedfs_tpu.filer.store_server import RemoteStore

        if not store_address:
            raise SystemExit("-store remote needs -storeAddress host:port")
        return RemoteStore(store_address)
    if kind == "cluster":
        # stateless filer routing by the master-replicated shard map to
        # a fleet of `weed filer.store -master ...` slot holders
        from seaweedfs_tpu.filer.cluster_store import ClusterStore

        if not masters:
            raise SystemExit("-store cluster needs -master host:port")
        return ClusterStore(masters.split(","))
    if kind not in ("sqlite", "sharded", "perbucket"):
        raise SystemExit(f"unknown filer store kind {kind!r} "
                         "(sqlite | sharded | perbucket | remote | "
                         "cluster)")
    if not path:
        if kind != "sqlite":
            raise SystemExit(
                f"-store {kind} is persistent and needs -db <path>")
        return None  # in-memory store
    if kind == "sqlite":
        return SqliteStore(path)
    if kind == "sharded":
        return ShardedSqliteStore(path)
    return PerBucketStoreRouter(path)


def cmd_filer_store(args):
    """`weed filer.store`: host one shared metadata store for many
    stateless filers (-store remote)."""
    from seaweedfs_tpu.filer.store_server import (FilerStoreServer,
                                                  make_store)

    store = make_store(args.db_kind, args.dir)
    masters = [m for m in (args.master or "").split(",") if m]
    s = FilerStoreServer(host=args.ip, port=args.port, store=store,
                         masters=masters)
    s.start()
    print(f"filer.store ({args.db_kind}) listening on {s.address}" +
          (f", leasing shards from {masters}" if masters else ""))
    _wait_forever([s])


def cmd_filer(args):
    from seaweedfs_tpu.filer.server import FilerServer

    store = _make_filer_store(args.store, args.db,
                              getattr(args, "storeAddress", ""),
                              masters=args.master)
    f = FilerServer(args.master, host=args.ip, port=args.port, store=store,
                    chunk_size=args.maxMB * 1024 * 1024,
                    replication=args.replication,
                    collection=args.collection, guard=_load_guard(),
                    peers=args.peers.split(",") if args.peers else None,
                    persist_meta_log=args.metaLog,
                    cipher=args.encryptVolumeData,
                    cache_dir=args.cacheDir,
                    cache_disk_bytes=args.cacheCapacityMB << 20)
    _wire_notification(f)
    f.start()
    stoppables = [f]
    if args.metricsPort:
        from seaweedfs_tpu.stats.metrics import start_metrics_server

        m = start_metrics_server(args.ip, args.metricsPort)
        stoppables.append(m)
        print(f"metrics on {m.address}/metrics")
    print(f"filer listening on {f.address}")
    _wait_forever(stoppables)


def _wire_notification(filer_server):
    """Attach the notification.toml sink, if configured."""
    from seaweedfs_tpu.notification import load_notification_queue
    from seaweedfs_tpu.util.config import load_configuration

    try:
        queue = load_notification_queue(load_configuration("notification"))
    except RuntimeError as e:
        print(f"notification sink disabled: {e}")
        return
    if queue is not None:
        filer_server.filer.notification_queue = queue
        print(f"notification sink: {queue.name}")


def _load_identities(path):
    from seaweedfs_tpu.s3api.auth import Identity

    if not path:
        return None
    with open(path) as f:
        config = json.load(f)
    return [Identity(name=i["name"], access_key=i["access_key"],
                     secret_key=i["secret_key"],
                     actions=i.get("actions", ["Admin"]))
            for i in config.get("identities", [])]


def cmd_s3(args):
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.s3api.server import S3ApiServer

    store = SqliteStore(args.db) if args.db else None
    filer = FilerServer(args.master, port=0, store=store,
                        guard=_load_guard(),
                        cipher=args.encryptVolumeData)
    filer.start()
    s3 = S3ApiServer(filer, host=args.ip, port=args.port,
                     identities=_load_identities(args.config))
    s3.start()
    stoppables = [s3, filer]
    if args.metricsPort:
        from seaweedfs_tpu.stats.metrics import start_metrics_server

        m = start_metrics_server(args.ip, args.metricsPort)
        stoppables.append(m)
        print(f"metrics on {m.address}/metrics")
    print(f"s3 gateway on {s3.address} (filer {filer.address})")
    _wait_forever(stoppables)


def cmd_iam(args):
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.iamapi.server import IamApiServer
    from seaweedfs_tpu.s3api.server import S3ApiServer

    store = SqliteStore(args.db) if args.db else None
    filer = FilerServer(args.master, port=0, store=store,
                        guard=_load_guard(),
                        cipher=args.encryptVolumeData)
    filer.start()
    s3 = S3ApiServer(filer, port=args.s3Port,
                     identities=_load_identities(args.config))
    s3.start()
    iam = IamApiServer(filer, host=args.ip, port=args.port, s3_server=s3)
    iam.start()
    print(f"iam api on {iam.address} (s3 {s3.address})")
    _wait_forever([iam, s3, filer])


def cmd_server(args):
    """Combined master + volume + filer (+ s3) in one process
    (weed/command/server.go)."""
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    stoppables = []
    guard = _load_guard()
    master = MasterServer(host=args.ip, port=args.masterPort,
                          volume_size_limit_mb=args.volumeSizeLimitMB,
                          pulse_seconds=args.pulseSeconds, guard=guard,
                          enable_native_assign=args.tcp)
    master.start()
    stoppables.append(master)
    print(f"master on {master.address}")

    dirs = args.dir.split(",")
    vs = VolumeServer(dirs, master.address, host=args.ip,
                      port=args.volumePort, rack=args.rack,
                      pulse_seconds=args.pulseSeconds, guard=guard,
                      enable_tcp=args.tcp)
    vs.start()
    vs.heartbeat_once()
    stoppables.append(vs)
    print(f"volume server on {vs.address}")

    if args.filer or args.s3 or args.iam:
        store = _make_filer_store(args.store, args.db,
                                  getattr(args, "storeAddress", ""),
                                  masters=master.address)
        filer = FilerServer(master.address, host=args.ip,
                            port=args.filerPort, store=store, guard=guard,
                            cipher=args.encryptVolumeData)
        _wire_notification(filer)
        filer.start()
        stoppables.append(filer)
        print(f"filer on {filer.address}")
        if args.s3 or args.iam:
            s3 = S3ApiServer(filer, host=args.ip, port=args.s3Port,
                             identities=_load_identities(args.config))
            s3.start()
            stoppables.append(s3)
            print(f"s3 gateway on {s3.address}")
            if args.iam:
                from seaweedfs_tpu.iamapi.server import IamApiServer

                iam = IamApiServer(filer, host=args.ip,
                                   port=args.iamPort, s3_server=s3)
                iam.start()
                stoppables.append(iam)
                print(f"iam api on {iam.address}")
    _wait_forever(stoppables)


def _shell_handlers(env):
    """The full admin command registry (weed/shell/commands.go)."""
    from seaweedfs_tpu.shell import commands as sh
    from seaweedfs_tpu.shell import commands_fs as fs
    from seaweedfs_tpu.shell import commands_maintenance as mnt
    from seaweedfs_tpu.shell import commands_qos as qos_cmds
    from seaweedfs_tpu.shell import commands_remote as rem
    from seaweedfs_tpu.shell import commands_scale as scale
    from seaweedfs_tpu.shell import commands_volume as vol

    def show(value):
        print(json.dumps(value, indent=2, default=str))

    def flag(a, name, default=None):
        for item in a:
            if item.startswith(f"-{name}="):
                return item.split("=", 1)[1]
        return default

    plan = lambda a: "-plan" in a or "-n" in a
    ap = lambda p: fs.resolve_path(env, p)  # fs.* paths obey fs.cd
    return {
        # volume family
        "volume.list": lambda a: show(sh.volume_list(env)),
        "volume.vacuum": lambda a: show(sh.volume_vacuum(
            env, float(a[0]) if a else None)),
        "volume.balance": lambda a: show(vol.volume_balance(
            env, collection=flag(a, "collection", "ALL"),
            plan_only=plan(a))),
        "volume.move": lambda a: show(vol.volume_move(
            env, int(a[0]), a[1], a[2], plan_only=plan(a))),
        "volume.copy": lambda a: show(vol.volume_copy(
            env, int(a[0]), a[1], a[2])),
        "volume.delete": lambda a: show(vol.volume_delete(
            env, int(a[0]), a[1])),
        "volume.delete_empty": lambda a: show(vol.volume_delete_empty(
            env, plan_only=plan(a))),
        "volume.mount": lambda a: show(vol.volume_mount(
            env, int(a[0]), a[1])),
        "volume.unmount": lambda a: show(vol.volume_unmount(
            env, int(a[0]), a[1])),
        "volume.mark": lambda a: show(vol.volume_mark(
            env, int(a[0]), a[1], writable="-writable" in a)),
        "volume.fix.replication": lambda a: show(
            vol.volume_fix_replication(env, plan_only=plan(a))),
        "volume.check.disk": lambda a: show(vol.volume_check_disk(
            env, plan_only=plan(a))),
        "volume.fsck": lambda a: show(vol.volume_fsck(
            env, filer_address=flag(a, "filer", ""),
            verbose="-v" in a)),
        "volume.configure.replication": lambda a: show(
            vol.volume_configure_replication(
                env, int(a[0]), flag(a, "replication", "000"))),
        "volume.server.evacuate": lambda a: show(
            vol.volume_server_evacuate(env, a[0], plan_only=plan(a))),
        "volume.server.leave": lambda a: show(
            vol.volume_server_leave(env, a[0])),
        "volume.tier.upload": lambda a: show(vol.volume_tier_upload(
            env, int(a[0]), a[1], flag(a, "backend", "default"),
            bucket=flag(a, "bucket", "volumes"),
            keep_local="-keepLocal" in a)),
        "volume.tier.download": lambda a: show(vol.volume_tier_download(
            env, int(a[0]), a[1])),
        "volume.tier.move": lambda a: show(vol.volume_tier_move(
            env, int(a[0]), flag(a, "backend", "default"),
            bucket=flag(a, "bucket", "volumes"), plan_only=plan(a))),
        "volume.query": lambda a: show(sh.volume_query(
            env, [a[0]],
            selections=(flag(a, "select", "") or "").split(",")
            if flag(a, "select") else None,
            field=flag(a, "field", ""), op=flag(a, "op", ""),
            value=flag(a, "value", ""), csv="-csv" in a)),
        # ec family — ec.encode takes an explicit volume id, or selects
        # full+quiet volumes with -fullPercent/-quietFor (seconds), the
        # reference's auto-EC trigger (command_ec_encode.go:271-302)
        "ec.encode": lambda a: show(
            (lambda vids: sh.ec_encode(
                env, int(vids[0]), collection=flag(a, "collection", ""),
                plan_only=plan(a))
             if vids else
             sh.ec_encode_auto(
                env, collection=flag(a, "collection", ""),
                full_percent=float(flag(a, "fullPercent", "95")),
                quiet_seconds=float(flag(a, "quietFor", "3600")),
                plan_only=plan(a)))(
            [x for x in a if not x.startswith("-")])),
        "ec.decode": lambda a: show(sh.ec_decode(
            env, int(a[0]), plan_only=plan(a))),
        "ec.rebuild": lambda a: show(sh.ec_rebuild(
            env, int(a[0]), plan_only=plan(a))),
        "ec.balance": lambda a: show(sh.ec_balance(
            env, plan_only=plan(a))),
        "ec.scrub": lambda a: show(sh.ec_scrub(
            env,
            vid=(lambda v: int(v[0]) if v else None)(
                [x for x in a if not x.startswith("-")]),
            repair="-repair" in a, plan_only=plan(a))),
        # coding-tier inventory: registered code families plus the family
        # each mounted EC volume was encoded with
        "ec.codes": lambda a: show(sh.ec_codes(
            env,
            vid=(lambda v: int(v[0]) if v else None)(
                [x for x in a if not x.startswith("-")]))),
        # maintenance family — curator status/queue on the master
        "maintenance.status": lambda a: show(mnt.maintenance_status(env)),
        "maintenance.queue": lambda a: show(mnt.maintenance_queue(env)),
        "maintenance.pause": lambda a: show(mnt.maintenance_pause(
            env, paused="-resume" not in a)),
        "maintenance.run": lambda a: show(mnt.maintenance_run(
            env, job_type=flag(a, "type"),
            volume=int(flag(a, "volume", "0") or 0),
            collection=flag(a, "collection", ""))),
        # qos — cluster-wide /debug/qos rollup
        "qos.status": lambda a: show(qos_cmds.qos_status(env)),
        # collection / cluster
        "collection.list": lambda a: show(vol.collection_list(env)),
        "collection.delete": lambda a: show(vol.collection_delete(
            env, a[0], plan_only=plan(a))),
        # elasticity — autoscaler status + manual scale.up / scale.drain
        "cluster.scale": lambda a: show(
            scale.scale_up(env) if "-up" in a
            else scale.scale_drain(env, flag(a, "drain", ""))
            if flag(a, "drain") else scale.scale_status(env)),
        "cluster.ps": lambda a: show(vol.cluster_ps(env)),
        "cluster.check": lambda a: show(vol.cluster_check(env)),
        "cluster.health": lambda a: show(vol.cluster_health(env)),
        "cluster.raft.ps": lambda a: show(vol.cluster_raft_ps(env)),
        "raft.status": lambda a: show(vol.cluster_raft_ps(env)),
        "cluster.raft.add": lambda a: show(vol.cluster_raft_add(
            env, a[0])),
        "cluster.raft.remove": lambda a: show(vol.cluster_raft_remove(
            env, a[0])),
        "filer.shards": lambda a: show(vol.filer_shards_status(env)),
        "filer.shards.split": lambda a: show(vol.filer_shards_split(
            env, int(a[0]))),
        "filer.shards.merge": lambda a: show(vol.filer_shards_merge(
            env, int(a[0]))),
        "lock": lambda a: show(vol.shell_lock(env)),
        "unlock": lambda a: show(vol.shell_unlock(env)),
        # fs family
        "fs.ls": lambda a: show(fs.fs_ls(
            env, ap(a[-1] if a and not a[-1].startswith("-") else ""),
            long_format="-l" in a)),
        "fs.cat": lambda a: sys.stdout.buffer.write(
            fs.fs_cat(env, ap(a[0]))),
        "fs.mkdir": lambda a: show(fs.fs_mkdir(env, ap(a[0]))),
        "fs.rm": lambda a: fs.fs_rm(
            env, ap(a[-1]), recursive="-r" in a),
        "fs.mv": lambda a: show(fs.fs_mv(env, ap(a[0]), ap(a[1]))),
        "fs.du": lambda a: show(fs.fs_du(env, ap(a[0] if a else ""))),
        "fs.tree": lambda a: print("\n".join(fs.fs_tree(
            env, ap(a[0] if a else "")))),
        "fs.cd": lambda a: show(fs.fs_cd(env, a[0] if a else "/")),
        "fs.pwd": lambda a: show(fs.fs_pwd(env)),
        "fs.meta.cat": lambda a: show(fs.fs_meta_cat(env, ap(a[0]))),
        "fs.meta.save": lambda a: show({"saved": len(fs.fs_meta_save(
            env, ap(a[-1] if a and not a[-1].startswith("-") else ""),
            output=flag(a, "o", "")))}),
        "fs.meta.load": lambda a: show(
            {"loaded": fs.fs_meta_load(env, a[0])}),
        "fs.meta.notify": lambda a: show(fs.fs_meta_notify(
            env, ap(a[0] if a else ""))),
        "fs.configure": lambda a: show(fs.fs_configure(
            env, flag(a, "locationPrefix", a[0] if a else "/"),
            collection=flag(a, "collection", ""),
            replication=flag(a, "replication", ""),
            ttl=flag(a, "ttl", ""),
            read_only=True if "-readOnly" in a else None,
            ec_code=flag(a, "ecCode", ""),
            delete="-delete" in a)),
        # remote storage family
        "remote.configure": lambda a: show(rem.remote_configure(
            env, name=flag(a, "name", ""), type=flag(a, "type", "s3"),
            endpoint=flag(a, "endpoint", ""),
            access_key=flag(a, "access_key", ""),
            secret_key=flag(a, "secret_key", ""),
            directory=flag(a, "dir", ""), delete="-delete" in a)),
        "remote.mount": lambda a: show(rem.remote_mount(
            env, directory=flag(a, "dir", ""),
            remote=flag(a, "remote", ""))),
        "remote.unmount": lambda a: show(rem.remote_unmount(
            env, flag(a, "dir", ""))),
        "remote.meta.sync": lambda a: show(rem.remote_meta_sync(
            env, flag(a, "dir", ""))),
        "remote.cache": lambda a: show(rem.remote_cache(
            env, flag(a, "dir", ""))),
        "remote.uncache": lambda a: show(rem.remote_uncache(
            env, flag(a, "dir", ""))),
        "remote.mount.buckets": lambda a: show(rem.remote_mount_buckets(
            env, flag(a, "remote", ""))),
        # s3 family
        "s3.bucket.list": lambda a: show(fs.s3_bucket_list(env)),
        "s3.bucket.create": lambda a: show(fs.s3_bucket_create(
            env, flag(a, "name", a[0] if a else ""))),
        "s3.bucket.delete": lambda a: fs.s3_bucket_delete(
            env, flag(a, "name", a[0] if a else "")),
        "s3.clean.uploads": lambda a: show(fs.s3_clean_uploads(
            env, float(flag(a, "timeAgo", 24 * 3600)))),
        "s3.configure": lambda a: show(fs.s3_configure(
            env, flag(a, "user", "admin"),
            flag(a, "access_key", ""), flag(a, "secret_key", ""),
            actions=(flag(a, "actions", "Admin") or "").split(","))),
        "s3.bucket.quota": lambda a: show(fs.s3_bucket_quota(
            env, flag(a, "name", ""), op=flag(a, "op", "set"),
            size_mb=int(flag(a, "sizeMB", "0")))),
        "s3.bucket.quota.enforce": lambda a: show(
            fs.s3_bucket_quota_enforce(env, apply="-apply" in a)),
        "s3.circuitbreaker": lambda a: show(fs.s3_circuitbreaker(
            env, actions=flag(a, "actions", ""),
            values=flag(a, "values", ""),
            buckets=flag(a, "buckets", ""),
            enable=(True if "-enable" in a
                    else False if "-disable" in a else None),
            delete="-delete" in a)),
    }


def cmd_shell(args):
    from seaweedfs_tpu.shell import commands as sh

    env = sh.CommandEnv(args.master, filer_address=args.filer)
    handlers = _shell_handlers(env)

    def run_line(line: str) -> bool:
        if line in (".exit", "exit", "quit"):
            return False
        if line in (".help", "help"):
            print("commands:", ", ".join(sorted(handlers)))
            return True
        name, *rest = line.split()
        fn = handlers.get(name)
        if fn is None:
            print(f"unknown command {name!r}; .help lists commands")
            return True
        try:
            fn(rest)
        except (RpcError, ValueError, IndexError) as e:
            print(f"error: {e}")
        return True

    if args.c:
        for line in args.c.split(";"):
            if line.strip() and not run_line(line.strip()):
                return
        return
    print(f"connected to master {args.master}; .help for commands")
    while True:
        try:
            line = input("> ").strip()
        except EOFError:
            return
        if line and not run_line(line):
            return


def cmd_benchmark(args):
    from seaweedfs_tpu.benchmark import run_benchmark

    run_benchmark(args.master, num_files=args.n, file_size=args.size,
                  concurrency=args.c, delete_percent=args.deletePercent,
                  replication=args.replication, use_tcp=args.useTcp,
                  use_native=args.useNative, assign_batch=args.assignBatch,
                  per_file_assign=args.perFileAssign)


def cmd_upload(args):
    with open(args.file, "rb") as f:
        body = f.read()
    a = call(args.master, f"/dir/assign?replication={args.replication}")
    headers = {"X-File-Name": os.path.basename(args.file)}
    if a.get("auth"):
        headers["Authorization"] = "BEARER " + a["auth"]
    resp = call(a["url"], f"/{a['fid']}", raw=body, method="POST",
                headers=headers)
    print(json.dumps({"fid": a["fid"], "url": a["url"],
                      "size": resp.get("size")}))


def cmd_download(args):
    vid = args.fid.split(",")[0]
    found = call(args.master, f"/dir/lookup?volumeId={vid}")
    data = call(found["locations"][0]["url"], f"/{args.fid}")
    out = args.output or args.fid.replace(",", "_")
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {len(data)} bytes to {out}")


def _sync_state_path(tag: str) -> str:
    import hashlib

    digest = hashlib.md5(tag.encode()).hexdigest()[:12]
    return os.path.expanduser(f"~/.weed_sync_{digest}.json")


def _load_offsets(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_offsets(path: str, offsets: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(offsets, f)
    os.replace(tmp, path)


def cmd_filer_sync(args):
    """Continuous one- or two-way sync between filers
    (weed/command/filer_sync.go)."""
    import time as _time

    from seaweedfs_tpu.replication import FilerSink, FilerSource, Replicator

    import hashlib as _hashlib

    # key includes the paths: different path pairs between the same
    # endpoints must not share cursors
    state = args.state or _sync_state_path(
        f"{args.a}{args.a_path}|{args.b}{args.b_path}")
    offsets = _load_offsets(state)

    def _sig(tag: str) -> int:
        # stable across restarts (unlike hash()), never 0
        return (int.from_bytes(_hashlib.md5(tag.encode()).digest()[:4],
                               "big") & 0x7FFFFFFF) or 1

    sig_ab, sig_ba = _sig(f"{args.a}->{args.b}"), _sig(f"{args.b}->{args.a}")
    # each direction stamps its own signature on sink writes and SKIPS
    # events stamped by the opposite direction (they are its echoes)
    pairs = [("a->b", FilerSource(args.a, args.a_path),
              FilerSink(args.b, args.b_path, signature=sig_ab), sig_ba)]
    if not args.isActivePassive:
        pairs.append(("b->a", FilerSource(args.b, args.b_path),
                      FilerSink(args.a, args.a_path, signature=sig_ba),
                      sig_ab))
    reps = [(name, Replicator(src, snk, signature=skip_sig))
            for name, src, snk, skip_sig in pairs]
    print(f"filer.sync {args.a}{args.a_path} <-> {args.b}{args.b_path} "
          f"({'active-passive' if args.isActivePassive else 'two-way'})")
    while True:
        moved = 0
        for name, rep in reps:
            applied, cursor = rep.run_once(offsets.get(name, 0),
                                           concurrency=args.concurrency)
            if cursor != offsets.get(name, 0):
                offsets[name] = cursor
                _save_offsets(state, offsets)
            moved += applied
        if args.once and moved == 0:
            break
        if not moved:
            _time.sleep(args.interval)


def cmd_filer_backup(args):
    """Incremental content backup of a filer path to a local/s3 sink
    (weed/command/filer_backup.go)."""
    import time as _time

    from seaweedfs_tpu.replication import FilerSource, Replicator, make_sink

    sink = make_sink(args.sink, access_key=args.accessKey,
                     secret_key=args.secretKey,
                     is_incremental=args.incremental)
    source = FilerSource(args.filer, args.filerPath)
    rep = Replicator(source, sink,
                     exclude_dirs=[d for d in args.exclude.split(",") if d])
    state = args.state or _sync_state_path(
        f"backup{args.filer}{args.filerPath}|{args.sink}")
    offsets = _load_offsets(state)
    while True:
        applied, cursor = rep.run_once(offsets.get("backup", 0))
        if cursor != offsets.get("backup", 0):
            offsets["backup"] = cursor
            _save_offsets(state, offsets)
        if args.once and applied == 0:
            break
        if not applied:
            _time.sleep(args.interval)


def cmd_filer_replicate(args):
    """MQ-driven replication consumer (weed/command/filer_replication.go):
    events arrive from the notification queue configured in
    notification.toml, not from a live filer subscription."""
    import time as _time

    from seaweedfs_tpu.notification import load_notification_input
    from seaweedfs_tpu.replication import FilerSource, Replicator, make_sink
    from seaweedfs_tpu.replication.replicator import run_from_queue
    from seaweedfs_tpu.util.config import load_configuration

    queue_input = load_notification_input(load_configuration("notification"))
    if queue_input is None:
        raise SystemExit(
            "no notification input defined in notification.toml "
            "(enable notification.file or notification.kafka)")
    sink = make_sink(args.sink, access_key=args.accessKey,
                     secret_key=args.secretKey,
                     is_incremental=args.incremental)
    source = FilerSource(args.filer, args.filerPath)
    rep = Replicator(source, sink,
                     exclude_dirs=[d for d in args.exclude.split(",") if d])
    print(f"filer.replicate: {queue_input.name} queue -> {args.sink}")
    applied = run_from_queue(queue_input, rep, once=args.once,
                             idle_sleep=args.interval)
    if args.once:
        print(f"applied {applied} events")


def cmd_filer_meta_backup(args):
    """Metadata-only backup into a local sqlite store
    (weed/command/filer_meta_backup.go)."""
    import time as _time

    from seaweedfs_tpu.replication.meta_backup import (MetaBackup,
                                                       restore_listing)

    if args.restore:
        for entry in restore_listing(args.store, args.filerPath):
            print(json.dumps(entry))
        return
    backup = MetaBackup(args.filer, args.filerPath, args.store)
    try:
        while True:
            applied = backup.run_once()
            if args.once and applied == 0:
                break
            if not applied:
                _time.sleep(args.interval)
    finally:
        backup.close()


def cmd_filer_meta_tail(args):
    """Print the filer metadata change feed
    (weed/command/filer_meta_tail.go)."""
    import time as _time

    from seaweedfs_tpu.replication import FilerSource

    source = FilerSource(args.filer, args.pathPrefix)
    since = int((_time.time() - args.timeAgo) * 1e9) if args.timeAgo else 0
    while True:
        events = source.subscribe(since)
        for event in events:
            print(json.dumps(event))
            since = max(since, event["ts_ns"])
        if args.once:
            break
        if not events:
            _time.sleep(args.interval)


def cmd_filer_copy(args):
    """Copy local files/directories into the filer
    (weed/command/filer_copy.go)."""
    dest = args.path.rstrip("/")  # "" for root: targets join as /name
    copied = 0
    for src in args.files:
        src = src.rstrip("/")
        if os.path.isdir(src):
            base = os.path.basename(src)
            for dirpath, _, files in os.walk(src):
                rel_dir = os.path.relpath(dirpath, src)
                for name in sorted(files):
                    rel = name if rel_dir == "." \
                        else f"{rel_dir}/{name}"
                    target = f"{dest}/{base}/{rel}"
                    _copy_one(args.filer, os.path.join(dirpath, name),
                              target)
                    copied += 1
        else:
            _copy_one(args.filer, src,
                      f"{dest}/{os.path.basename(src)}")
            copied += 1
    print(f"copied {copied} files to {args.filer}{dest}")


def _copy_one(filer: str, local_path: str, target: str):
    import mimetypes
    import urllib.parse

    with open(local_path, "rb") as f:
        body = f.read()
    mime = mimetypes.guess_type(local_path)[0] or \
        "application/octet-stream"
    call(filer, urllib.parse.quote(target), raw=body, method="POST",
         headers={"Content-Type": mime}, timeout=600)


def cmd_filer_cat(args):
    """Stream one filer file to stdout (weed/command/filer_cat.go)."""
    import urllib.parse

    # the raw GET can't distinguish a stored .json file from a
    # directory listing, so check the entry type via the parent listing
    path = "/" + args.path.strip("/")
    parent, _, name = path.rpartition("/")
    listing = call(args.filer,
                   urllib.parse.quote(parent or "/") + "/?limit=10000",
                   timeout=60)
    entry = next((e for e in listing.get("Entries", [])
                  if e.get("FullPath", "").rsplit("/", 1)[-1] == name),
                 None)
    if entry is None:
        print(f"error: {path} not found", file=sys.stderr)
        sys.exit(1)
    if entry.get("IsDirectory"):
        print(f"error: {path} is a directory", file=sys.stderr)
        sys.exit(1)
    data = call(args.filer, urllib.parse.quote(path), parse=False,
                timeout=600)
    sys.stdout.buffer.write(data)


def cmd_backup(args):
    """Keep a local, incrementally-updated copy of one volume
    (weed/command/backup.go): first run fetches .dat/.idx wholesale,
    later runs tail only the new appends."""
    from seaweedfs_tpu.storage import volume_backup
    from seaweedfs_tpu.storage.volume import Volume

    found = call(args.master, f"/dir/lookup?volumeId={args.volumeId}")
    locations = found.get("locations", [])
    if not locations:
        print(f"error: volume {args.volumeId} not found")
        sys.exit(1)
    source = locations[0]["url"]
    os.makedirs(args.dir, exist_ok=True)
    name = (f"{args.collection}_{args.volumeId}" if args.collection
            else str(args.volumeId))
    dat_path = os.path.join(args.dir, name + ".dat")
    if not os.path.exists(dat_path):
        for ext in (".idx", ".dat"):
            blob = call(source,
                        f"/admin/ec/shard_file?volume={args.volumeId}"
                        f"&collection={args.collection}&ext={ext}",
                        timeout=3600)
            with open(os.path.join(args.dir, name + ext), "wb") as f:
                f.write(blob if isinstance(blob, bytes) else b"")
        print(f"full copy of volume {args.volumeId} from {source}")
        return
    v = Volume(args.dir, args.collection, args.volumeId)
    try:
        applied = volume_backup.incremental_backup(
            v, lambda since: _fetch_tail(source, args.volumeId, since))
        print(f"applied {applied} new records from {source}")
    finally:
        v.close()


def _fetch_tail(source: str, vid: int, since_ns: int) -> bytes:
    data = call(source,
                f"/admin/volume/tail?volume={vid}&since_ns={since_ns}",
                timeout=600)
    return data if isinstance(data, (bytes, bytearray)) else b""


def cmd_compact(args):
    """Offline vacuum of a volume directory (weed/command/compact.go)."""
    from seaweedfs_tpu.storage.tools import compact_offline

    print(json.dumps(compact_offline(args.dir, args.collection,
                                     args.volumeId)))


def cmd_fix(args):
    """Rebuild the .idx from the .dat (weed/command/fix.go)."""
    from seaweedfs_tpu.storage.tools import rebuild_index

    count = rebuild_index(args.dir, args.collection, args.volumeId)
    print(f"rebuilt index from {count} records")


def cmd_scrub(args):
    """Verify local EC shards against the fused-CRC record in .vif; with
    -repair, regenerate corrupt/missing shards from survivors."""
    import json as _json

    from seaweedfs_tpu.storage.tools import scrub_ec_volume

    report = scrub_ec_volume(args.dir, args.collection, args.volumeId,
                             repair=args.repair)
    print(_json.dumps(report, indent=2))
    if (report["corrupt"] or report["missing"]) and not args.repair:
        raise SystemExit(1)  # degraded redundancy is not healthy


def cmd_export(args):
    """Export a volume's live needles (weed/command/export.go)."""
    from seaweedfs_tpu.storage.tools import export_volume

    records = export_volume(args.dir, args.collection, args.volumeId,
                            output_tar=args.o,
                            newer_than_ts=args.newer or 0.0)
    for r in records:
        print(json.dumps(r))
    if args.o:
        print(f"wrote {len(records)} files to {args.o}",
              file=sys.stderr)


def cmd_filer_remote_sync(args):
    """Push local changes under a remote mount back to the remote
    storage (weed/command/filer_remote_sync.go; filer.remote.gateway is
    the same loop pointed at /buckets)."""
    import time as _time

    from seaweedfs_tpu.remote_storage import (RemoteConf, RemoteLocation,
                                              make_remote_client)
    from seaweedfs_tpu.replication import FilerSource

    directory = args.dir.rstrip("/") or "/"
    listing = call(args.filer, "/remote/list")
    mappings = listing.get("mappings", {})
    if directory not in mappings:
        print(f"error: {directory} is not a remote mount "
              f"(mounted: {sorted(mappings) or 'none'})")
        sys.exit(1)
    root = RemoteLocation.parse(mappings[directory])
    conf = next((c for c in listing.get("storages", [])
                 if c["name"] == root.name), None)
    if conf is None:
        print(f"error: remote storage {root.name!r} not configured")
        sys.exit(1)
    client = make_remote_client(RemoteConf.from_dict(conf))
    source = FilerSource(args.filer, directory + "/")
    state = args.state or _sync_state_path(
        f"remote{args.filer}{directory}")
    offsets = _load_offsets(state)
    print(f"filer.remote.sync {args.filer}{directory} -> {root}")

    def loc_of(full_path: str) -> "RemoteLocation":
        rel = full_path[len(directory):].lstrip("/")
        return RemoteLocation(root.name, root.bucket,
                              root.path.rstrip("/") + "/" + rel)

    while True:
        cursor = offsets.get("sync", 0)
        moved = 0
        for event in source.subscribe(cursor):
            old, new = event.get("old_entry"), event.get("new_entry")

            def in_mount(e):
                return e and e["full_path"].startswith(directory + "/")

            def entry_is_dir(e):
                return bool(e.get("attr", {}).get("mode", 0) & 0o40000)

            try:
                # drop the old remote object on delete AND on rename
                if in_mount(old) and (
                        new is None
                        or old["full_path"] != new["full_path"]):
                    if entry_is_dir(old):
                        client.delete_prefix(loc_of(old["full_path"]))
                    else:
                        client.delete_file(loc_of(old["full_path"]))
                    moved += 1
                if in_mount(new) and not entry_is_dir(new) \
                        and not new.get("remote_entry"):
                    # a genuinely local change (mount syncs carry
                    # remote_entry and must not echo back)
                    path = new["full_path"]
                    data = source.read_entry_bytes(path)
                    client.write_file(loc_of(path), data)
                    moved += 1
            except RpcError as e:
                print(f"push {(new or old)['full_path']}: {e} "
                      "(will retry)")
                break
            cursor = max(cursor, event["ts_ns"])
        if cursor != offsets.get("sync", 0):
            offsets["sync"] = cursor
            _save_offsets(state, offsets)
        if args.once and moved == 0:
            break
        if not moved:
            _time.sleep(args.interval)


def cmd_profile(args):
    """Cluster flamegraph: fan /debug/pprof/profile out to every live
    daemon (master topology + cluster membership discovery), merge the
    folded stacks under per-daemon root frames, print/write collapsed-
    stack text ready for flamegraph.pl or speedscope."""
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu import profiling
    from seaweedfs_tpu.rpc.http_rpc import RpcError, call

    master = args.master
    targets: dict[str, str] = {f"master {master}": master}
    try:
        topo = call(master, "/dir/status")
    except (RpcError, OSError) as e:
        print(f"error: master {master} unreachable: {e}")
        sys.exit(1)
    for dc in topo.get("datacenters", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                targets[f"volume {n['url']}"] = n["url"]
    for kind in ("filer", "s3"):
        try:
            nodes = call(master, f"/cluster/nodes?type={kind}")
        except (RpcError, OSError):
            continue
        for n in nodes.get("cluster_nodes", []):
            targets[f"{kind} {n['address']}"] = n["address"]

    seconds, hz = args.seconds, args.hz
    path = f"/debug/pprof/profile?seconds={seconds}&hz={hz}"

    def fetch(addr: str):
        return call(addr, path, parse=False, timeout=seconds + 30.0)

    profiles: dict[str, str] = {}
    failed: list[str] = []
    with ThreadPoolExecutor(max_workers=max(4, len(targets))) as pool:
        futures = {name: pool.submit(fetch, addr)
                   for name, addr in targets.items()}
        for name, fut in futures.items():
            try:
                profiles[name.replace(";", ":")] = \
                    fut.result().decode("utf-8", "replace")
            except (RpcError, OSError) as e:
                failed.append(f"{name}: {e}")

    merged = profiling.merge_folded(profiles)
    header = (f"# cluster cpu profile: {len(profiles)}/{len(targets)} "
              f"daemons, {seconds}s @ {hz}Hz\n")
    for f in failed:
        header += f"# unreachable: {f}\n"
    if args.o:
        with open(args.o, "w") as f:
            f.write(header + merged)
        print(f"wrote {args.o} ({len(merged.splitlines())} stacks from "
              f"{len(profiles)} daemons)")
    else:
        print(header + merged, end="")
    if not profiles:
        sys.exit(1)


def cmd_maintenance(args):
    """One-shot curator control from the command line: status/queue
    dumps, pause/resume, or force a detector pass / explicit job —
    the same /maintenance/* surface the shell commands use."""
    from seaweedfs_tpu.rpc.http_rpc import RpcError
    from seaweedfs_tpu.shell import commands_maintenance as mnt
    from seaweedfs_tpu.shell.commands import CommandEnv

    env = CommandEnv(args.master)
    try:
        if args.action == "status":
            out = mnt.maintenance_status(env)
        elif args.action == "queue":
            out = mnt.maintenance_queue(env)
        elif args.action == "pause":
            out = mnt.maintenance_pause(env, paused=True)
        elif args.action == "resume":
            out = mnt.maintenance_pause(env, paused=False)
        else:  # run
            out = mnt.maintenance_run(
                env, job_type=args.type or None, volume=args.volume,
                collection=args.collection)
    except (RpcError, OSError) as e:
        print(f"error: master {args.master} unreachable: {e}")
        sys.exit(1)
    print(json.dumps(out, indent=2, default=str))


def _render_top(h, master):
    """One frame of `weed top` from the /cluster/health rollup."""
    lines = [f"cluster {h.get('status', '?').upper():10s}  "
             f"leader {h.get('leader') or '?'}  "
             f"(via {master}, scrape "
             f"{h.get('scrape', {}).get('interval_ms', 0):.0f}ms, "
             f"duty {h.get('scrape', {}).get('duty', 0):.4f})", ""]
    lines.append(f"{'NODE':28s} {'KIND':8s} {'UP':3s} READY")
    for addr, n in sorted(h.get("nodes", {}).items()):
        ready = "-"
        if n.get("up"):
            try:
                call(addr, "/readyz", timeout=2)
                ready = "yes"
            except (RpcError, OSError):
                ready = "NO"
        lines.append(f"{addr:28s} {n.get('kind', '?'):8s} "
                     f"{'up' if n.get('up') else 'DOWN':3s} {ready}")
    lines.append("")
    lines.append(f"{'SLO RULE':20s} {'BURN 5m':>8s} {'BURN 1h':>8s} "
                 f"{'P99 ms':>8s} STATE")
    for name, a in sorted(h.get("slo", {}).items()):
        p99 = a.get("detail", {}).get("p99_ms")
        lines.append(
            f"{name:20s} {a.get('burn_fast', 0):8.2f} "
            f"{a.get('burn_slow', 0):8.2f} "
            f"{p99 if p99 is not None else '-':>8} "
            f"{'FIRING' if a.get('firing') else 'ok'}")
    events = h.get("events", [])[-8:]
    if events:
        lines.append("")
        lines.append("RECENT EVENTS")
        for e in events:
            lines.append(f"  {e['ts']:.1f} {e['kind']:16s} "
                         f"{e.get('service', ''):8s} {e.get('node', '')}")
    return lines


def _render_usage(u):
    """Workload-analytics frame of `weed top`: the hot-key / tenant
    rollup from GET /cluster/usage (decayed sketch merge, so the
    numbers are recent-traffic weighted, not lifetime totals)."""
    lines = []
    t = u.get("totals", {})
    lines.append(
        f"workload (last epochs, decayed): "
        f"{t.get('reads', 0):.0f} reads / {t.get('writes', 0):.0f} writes, "
        f"{t.get('bytes_read', 0) / 1e6:.1f}MB out / "
        f"{t.get('bytes_written', 0) / 1e6:.1f}MB in, "
        f"~{t.get('distinct_keys', 0)} distinct keys "
        f"({len(u.get('nodes', []))} reporting daemons)")
    top = u.get("top_keys", [])
    if top:
        lines.append("")
        lines.append(f"{'HOT KEY':40s} {'READS':>9s} {'SHARE':>7s}")
        for e in top[:10]:
            lines.append(f"{e.get('fid', '?'):40s} "
                         f"{e.get('reads', 0):9.0f} "
                         f"{e.get('share', 0) * 100:6.1f}%")
    tenants = u.get("tenants", {})
    if tenants:
        # ops/bytes come per-op from the usage view; the terminal view
        # wants one scalar per tenant
        def total(e, field):
            return sum((e.get(field) or {}).values())

        lines.append("")
        lines.append(f"{'TENANT':24s} {'OPS':>9s} {'BYTES':>12s} "
                     f"{'~KEYS':>7s}")
        ranked = sorted(tenants.items(),
                        key=lambda kv: (-total(kv[1], "bytes"), kv[0]))
        for name, e in ranked[:10]:
            lines.append(f"{name or '(none)':24s} "
                         f"{total(e, 'ops'):9.0f} "
                         f"{total(e, 'bytes'):12.0f} "
                         f"{e.get('distinct_keys', 0):7d}")
    return lines


def cmd_top(args):
    """Live terminal view over GET /cluster/health (+ per-node readyz
    probes) — the cluster-wide answer to `kubectl get nodes`."""
    import time as _time

    frames = 0
    while True:
        try:
            h = call(args.master, "/cluster/health", timeout=5)
        except (RpcError, OSError) as e:
            print(f"error: master {args.master} unreachable: {e}")
            sys.exit(1)
        lines = _render_top(h, args.master)
        try:
            u = call(args.master, "/cluster/usage", timeout=5)
        except (RpcError, OSError):
            u = None
        if u and u.get("nodes"):
            lines.append("")
            lines.extend(_render_usage(u))
        if not args.once and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        print("\n".join(lines), flush=True)
        frames += 1
        if args.once or (args.n and frames >= args.n):
            return
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return


def cmd_lint_dashboards(args):
    """Grafana-vs-registry + SLO-rule lint; non-zero exit on any
    dangling metric reference (wired into the perf_smoke tests)."""
    from seaweedfs_tpu.stats import lint

    problems = lint.run(args.path or None)
    for prob in problems:
        print(f"lint: {prob}")
    if problems:
        sys.exit(1)
    print("dashboards + SLO rules reference only registered families")


def cmd_scaffold(args):
    from seaweedfs_tpu.util.config import scaffold

    text = scaffold(args.config)
    if args.output:
        path = os.path.join(args.output, args.config + ".toml")
        with open(path, "w") as f:
            f.write(text)
        print(f"wrote {path}")
    else:
        print(text, end="")


def _workers_flag(p):
    p.add_argument("-workers", type=int, default=0,
                   help="prefork this many gateway worker processes per "
                        "HTTP listener via SO_REUSEPORT (sets "
                        "WEED_HTTP_WORKERS; 0/1 = single process)")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="weed", description=__doc__)
    parser.add_argument("-v", type=int, default=0,
                        help="glog verbosity level")
    parser.add_argument("-cpuprofile", default="",
                        help="dump a cProfile trace here on shutdown")
    parser.add_argument("-memprofile", default="",
                        help="dump a heap snapshot here on shutdown")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("master", help="start a master server")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-volumeSizeLimitMB", type=int, default=1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-peers", default="",
                   help="comma-separated other master addresses (raft)")
    p.add_argument("-join", action="store_true",
                   help="join the -peers cluster as a non-voting "
                        "learner (promoted to voter after catch-up) "
                        "instead of bootstrapping as a voter")
    p.add_argument("-mdir", default="", help="raft state directory")
    p.add_argument("-tcp", action="store_true",
                   help="serve per-file assigns on the native fast-path "
                        "port (port+20000) via leased fid ranges")
    _workers_flag(p)
    p.set_defaults(fn=cmd_master)

    p = sub.add_parser("master.follower",
                       help="read-only lookup/assign cache master")
    p.add_argument("-masters", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=9334)
    p.set_defaults(fn=cmd_master_follower)

    p = sub.add_parser("volume", help="start a volume server")
    p.add_argument("-dir", default="./data")
    p.add_argument("-max", default="8")
    p.add_argument("-mserver", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8080)
    p.add_argument("-rack", default="")
    p.add_argument("-dataCenter", default="")
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-tier", action="append", default=[],
                   help="tier backend: name=local:/dir or "
                        "name=s3:endpoint[,ak,sk] (repeatable)")
    p.add_argument("-tcp", action="store_true",
                   help="serve the TCP read fast path on port+20000")
    p.add_argument("-readMode", default="proxy",
                   choices=["local", "proxy", "redirect"],
                   help="how to serve reads of non-local volumes")
    p.add_argument("-fsync", action="store_true",
                   help="group-commit fsync before acknowledging writes")
    p.add_argument("-ecBackend", default="",
                   choices=["", "tpu", "cpu", "jax", "numpy"],
                   help="EC codec: tpu (batched device pipeline, default) "
                        "| cpu (AVX2) | jax (portable XLA) | numpy")
    p.add_argument("-index", default="memory",
                   choices=["memory", "compact", "sqlite"],
                   help="needle index kind (compact: 16 B/needle numpy "
                        "arrays; sqlite: disk-backed)")
    p.add_argument("-concurrentUploadLimitMB", type=int, default=0,
                   help="in-flight upload byte throttle (0 = unlimited)")
    p.add_argument("-concurrentDownloadLimitMB", type=int, default=0,
                   help="in-flight download byte throttle (0 = unlimited)")
    _workers_flag(p)
    p.set_defaults(fn=cmd_volume)

    p = sub.add_parser("filer", help="start a filer server")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-metricsPort", type=int, default=0,
                   help="serve /metrics on a dedicated port")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8888)
    p.add_argument("-maxMB", type=int, default=4)
    p.add_argument("-db", default="", help="sqlite path (default: memory)")
    p.add_argument("-store", default="sqlite",
                   help="store kind: sqlite | sharded | perbucket | "
                        "remote | cluster")
    p.add_argument("-storeAddress", default="",
                   help="shared `weed filer.store` address (-store remote)")
    p.add_argument("-replication", default="")
    p.add_argument("-collection", default="")
    p.add_argument("-peers", default="",
                   help="comma-separated peer filers to aggregate")
    p.add_argument("-metaLog", action="store_true",
                   help="persist the metadata change log")
    p.add_argument("-encryptVolumeData", action="store_true",
                   help="encrypt chunk data at rest (per-chunk AES keys "
                        "in filer metadata)")
    p.add_argument("-cacheDir", default="",
                   help="directory for the tiered on-disk chunk cache")
    p.add_argument("-cacheCapacityMB", type=int, default=1024,
                   help="on-disk chunk cache budget (with -cacheDir)")
    _workers_flag(p)
    p.set_defaults(fn=cmd_filer)

    p = sub.add_parser("filer.store",
                       help="host one shared metadata store for many "
                            "stateless filers (-store remote)")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8889)
    p.add_argument("-dir", default="",
                   help="persistence directory (default: memory)")
    p.add_argument("-db_kind", default="memory",
                   help="embedded kind: memory | sqlite | sharded | "
                        "perbucket")
    p.add_argument("-master", default="",
                   help="comma-separated masters: lease directory shards "
                        "from the replicated map (cluster mode)")
    p.set_defaults(fn=cmd_filer_store)

    p = sub.add_parser("s3", help="start an s3 gateway (+embedded filer)")
    p.add_argument("-metricsPort", type=int, default=0,
                   help="serve /metrics on a dedicated port")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8333)
    p.add_argument("-db", default="")
    p.add_argument("-config", default="", help="identities json")
    p.add_argument("-encryptVolumeData", action="store_true",
                   help="encrypt chunk data at rest")
    _workers_flag(p)
    p.set_defaults(fn=cmd_s3)

    p = sub.add_parser("iam", help="start an IAM management API (+s3+filer)")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8111)
    p.add_argument("-s3Port", type=int, default=8333)
    p.add_argument("-db", default="", help="sqlite path (default: memory)")
    p.add_argument("-config", default="", help="s3 identities json")
    p.add_argument("-encryptVolumeData", action="store_true",
                   help="encrypt chunk data at rest")
    p.set_defaults(fn=cmd_iam)

    p = sub.add_parser("server", help="combined master+volume(+filer)(+s3)")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-dir", default="./data")
    p.add_argument("-masterPort", type=int, default=9333)
    p.add_argument("-volumePort", type=int, default=8080)
    p.add_argument("-filerPort", type=int, default=8888)
    p.add_argument("-s3Port", type=int, default=8333)
    p.add_argument("-volumeSizeLimitMB", type=int, default=1024)
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-filer", action="store_true")
    p.add_argument("-s3", action="store_true")
    p.add_argument("-iam", action="store_true",
                   help="also start the IAM management API")
    p.add_argument("-iamPort", type=int, default=8111)
    p.add_argument("-db", default="")
    p.add_argument("-store", default="sqlite",
                   help="filer store kind: sqlite | sharded | perbucket | "
                        "remote")
    p.add_argument("-storeAddress", default="",
                   help="shared `weed filer.store` address (-store remote)")
    p.add_argument("-config", default="")
    p.add_argument("-rack", default="")
    p.add_argument("-tcp", action="store_true",
                   help="enable the volume TCP read fast path")
    p.add_argument("-encryptVolumeData", action="store_true",
                   help="encrypt chunk data at rest (per-chunk AES keys "
                        "in filer metadata)")
    _workers_flag(p)
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("shell", help="interactive admin shell")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-filer", default="",
                   help="filer for fs.*/s3.* (default: discover via master)")
    p.add_argument("-c", default="",
                   help="run ;-separated commands and exit")
    p.set_defaults(fn=cmd_shell)

    p = sub.add_parser("profile",
                       help="cluster-wide CPU flamegraph: burst-profile "
                            "every live daemon and merge the stacks")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-seconds", type=float, default=5.0,
                   help="burst duration per daemon")
    p.add_argument("-hz", type=float, default=99.0,
                   help="sampling rate during the burst")
    p.add_argument("-o", default="",
                   help="write collapsed stacks here (default: stdout)")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("maintenance",
                       help="curator control: status, queue, pause/"
                            "resume, or force a scan/job")
    p.add_argument("action",
                   choices=["status", "queue", "pause", "resume", "run"])
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-type", default="",
                   help="run: enqueue one explicit job of this type "
                        "(ec.rebuild / fix.replication / vacuum / "
                        "deep.scrub / balance) instead of a full scan")
    p.add_argument("-volume", type=int, default=0,
                   help="run: volume id for the explicit job")
    p.add_argument("-collection", default="",
                   help="run: collection for the explicit job")
    p.set_defaults(fn=cmd_maintenance)

    p = sub.add_parser("top", help="live cluster health view "
                                   "(/cluster/health + readyz probes)")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-interval", type=float, default=2.0,
                   help="seconds between redraws")
    p.add_argument("-n", type=int, default=0,
                   help="frames to render (0 = until interrupted)")
    p.add_argument("-once", action="store_true",
                   help="print one frame and exit (scripting)")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("lint-dashboards",
                       help="check grafana panels and SLO rules against "
                            "the metrics registry")
    p.add_argument("-path", default="",
                   help="dashboard json (default: bundled dashboard)")
    p.set_defaults(fn=cmd_lint_dashboards)

    p = sub.add_parser("benchmark", help="write/read load benchmark")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000)
    p.add_argument("-size", type=int, default=1024)
    p.add_argument("-c", type=int, default=16)
    p.add_argument("-deletePercent", type=int, default=0)
    p.add_argument("-replication", default="000")
    p.add_argument("-useTcp", action="store_true",
                   help="read over the TCP fast path")
    p.add_argument("-useNative", action="store_true",
                   help="drive the native engine's fast-path port with "
                        "the C++ load generator (batched assigns)")
    p.add_argument("-assignBatch", type=int, default=256,
                   help="fids per /dir/assign?count= call in -useNative "
                        "mode")
    p.add_argument("-perFileAssign", action="store_true",
                   help="per-file native assigns (master -tcp lease "
                        "service) + native writes; write phase only")
    p.set_defaults(fn=cmd_benchmark)

    p = sub.add_parser("upload", help="upload one file")
    p.add_argument("file")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-replication", default="000")
    p.set_defaults(fn=cmd_upload)

    p = sub.add_parser("download", help="download by fid")
    p.add_argument("fid")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-output", default="")
    p.set_defaults(fn=cmd_download)

    p = sub.add_parser("filer.copy",
                       help="copy local files/dirs into the filer")
    p.add_argument("files", nargs="+")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-path", default="/", help="destination directory")
    p.set_defaults(fn=cmd_filer_copy)

    p = sub.add_parser("filer.cat", help="stream a filer file to stdout")
    p.add_argument("path")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.set_defaults(fn=cmd_filer_cat)

    p = sub.add_parser("backup",
                       help="local incremental copy of one volume")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-dir", default=".")
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser("compact", help="offline vacuum of a volume")
    p.add_argument("-dir", default=".")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.set_defaults(fn=cmd_compact)

    p = sub.add_parser("fix", help="rebuild a volume .idx from its .dat")
    p.add_argument("-dir", default=".")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.set_defaults(fn=cmd_fix)

    p = sub.add_parser("scrub", help="verify EC shards against the CRCs "
                       "recorded by the device-fused encode (.vif)")
    p.add_argument("-dir", default=".")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-repair", action="store_true",
                   help="rebuild corrupt/missing shards from survivors")
    p.set_defaults(fn=cmd_scrub)

    p = sub.add_parser("export", help="export a volume's live needles")
    p.add_argument("-dir", default=".")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-o", default="", help="write a tar archive here")
    p.add_argument("-newer", type=float, default=0,
                   help="only needles modified after this unix time")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("filer.sync", help="sync two filers continuously")
    p.add_argument("-a", required=True, help="source filer host:port")
    p.add_argument("-b", required=True, help="target filer host:port")
    p.add_argument("-a.path", dest="a_path", default="/")
    p.add_argument("-b.path", dest="b_path", default="/")
    p.add_argument("-isActivePassive", action="store_true",
                   help="one-way a->b only")
    p.add_argument("-state", default="", help="offset state file")
    p.add_argument("-concurrency", type=int, default=1,
                   help="parallel sync lanes partitioned by path hash")
    p.add_argument("-interval", type=float, default=2.0)
    p.add_argument("-once", action="store_true",
                   help="exit when caught up (for scripting/tests)")
    p.set_defaults(fn=cmd_filer_sync)

    p = sub.add_parser("filer.backup",
                       help="replicate filer data to local/s3 sink")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-filerPath", default="/")
    p.add_argument("-sink", required=True,
                   help="local:///dir | s3://bucket/dir?endpoint=host:port"
                        " | filer://host:port/dir")
    p.add_argument("-accessKey", default="")
    p.add_argument("-secretKey", default="")
    p.add_argument("-incremental", action="store_true",
                   help="file changes under yyyy-mm-dd dirs")
    p.add_argument("-exclude", default="",
                   help="comma-separated directories to skip")
    p.add_argument("-state", default="")
    p.add_argument("-interval", type=float, default=2.0)
    p.add_argument("-once", action="store_true")
    p.set_defaults(fn=cmd_filer_backup)

    p = sub.add_parser("filer.replicate",
                       help="consume notification-queue events into a "
                            "replication sink (MQ-driven mode)")
    p.add_argument("-filer", default="127.0.0.1:8888",
                   help="source filer (chunk data reads)")
    p.add_argument("-filerPath", default="/")
    p.add_argument("-sink", required=True,
                   help="local:///dir | s3://bucket/dir?endpoint=host:port"
                        " | filer://host:port/dir")
    p.add_argument("-accessKey", default="")
    p.add_argument("-secretKey", default="")
    p.add_argument("-incremental", action="store_true")
    p.add_argument("-exclude", default="")
    p.add_argument("-interval", type=float, default=1.0)
    p.add_argument("-once", action="store_true",
                   help="drain the queue and exit")
    p.set_defaults(fn=cmd_filer_replicate)

    p = sub.add_parser("filer.meta.backup",
                       help="continuously back up filer metadata to sqlite")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-filerPath", default="/")
    p.add_argument("-store", required=True, help="sqlite backup file")
    p.add_argument("-restore", action="store_true",
                   help="print entries from the backup store and exit")
    p.add_argument("-interval", type=float, default=2.0)
    p.add_argument("-once", action="store_true")
    p.set_defaults(fn=cmd_filer_meta_backup)

    p = sub.add_parser("filer.remote.sync",
                       help="push local changes under a mount to remote")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-dir", required=True, help="mounted directory")
    p.add_argument("-state", default="")
    p.add_argument("-interval", type=float, default=2.0)
    p.add_argument("-once", action="store_true")
    p.set_defaults(fn=cmd_filer_remote_sync)

    p = sub.add_parser("filer.remote.gateway",
                       help="push bucket changes under /buckets to remote")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-dir", default="/buckets")
    p.add_argument("-state", default="")
    p.add_argument("-interval", type=float, default=2.0)
    p.add_argument("-once", action="store_true")
    p.set_defaults(fn=cmd_filer_remote_sync)

    p = sub.add_parser("filer.meta.tail",
                       help="print filer metadata change events")
    p.add_argument("-filer", default="127.0.0.1:8888")
    p.add_argument("-pathPrefix", default="/")
    p.add_argument("-timeAgo", type=float, default=0,
                   help="start this many seconds in the past")
    p.add_argument("-interval", type=float, default=1.0)
    p.add_argument("-once", action="store_true")
    p.set_defaults(fn=cmd_filer_meta_tail)

    p = sub.add_parser("scaffold", help="print a config template")
    p.add_argument("-config", default="security",
                   help="security|master|filer|replication|notification")
    p.add_argument("-output", default="", help="write <name>.toml to dir")
    p.set_defaults(fn=cmd_scaffold)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=lambda a: print(VERSION))

    p = sub.add_parser("autocomplete",
                       help="print a bash completion script "
                            "(source it or install under "
                            "/etc/bash_completion.d)")
    p.set_defaults(fn=lambda a: print(_completion_script(
        sorted(sub.choices))))

    args = parser.parse_args(argv)
    if getattr(args, "workers", 0):
        # flag wins over env; RpcServer reads WEED_HTTP_WORKERS at bind
        os.environ["WEED_HTTP_WORKERS"] = str(args.workers)
    if args.v:
        from seaweedfs_tpu.util import glog

        glog.set_verbosity(args.v)
    if args.cpuprofile or args.memprofile:
        from seaweedfs_tpu.util import grace

        grace.setup_profiling(args.cpuprofile, args.memprofile)
    try:
        args.fn(args)
    except BrokenPipeError:  # e.g. `weed filer.meta.tail | head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)


if __name__ == "__main__":
    main()
