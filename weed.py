#!/usr/bin/env python3
"""weed — CLI entrypoint for the TPU-native SeaweedFS-capability store.

Subcommand surface modelled on the reference's weed/command registry
(weed/weed.go:37-84, command/command.go): master, volume, filer, s3,
server (combined), shell, benchmark, upload, download, version.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from seaweedfs_tpu.rpc.http_rpc import RpcError, call  # noqa: E402

VERSION = "seaweedfs_tpu 0.1 (RS(10,4) EC on TPU via JAX/Pallas)"


def _wait_forever(stoppables):
    stop = lambda *a: (_stop_all(stoppables), sys.exit(0))
    signal.signal(signal.SIGINT, stop)
    signal.signal(signal.SIGTERM, stop)
    signal.pause()


def _stop_all(stoppables):
    for s in reversed(stoppables):
        try:
            s.stop()
        except Exception:
            pass


def _load_guard():
    """Build a security Guard from security.toml (weed/security/guard.go)."""
    from seaweedfs_tpu.security import Guard
    from seaweedfs_tpu.util.config import load_configuration

    conf = load_configuration("security")
    return Guard(
        white_list=[w for w in
                    str(conf.get("access.ui", "") or "").split(",") if w],
        signing_key=str(conf.get("jwt.signing.key", "") or ""),
        expires_after_seconds=conf.get_int(
            "jwt.signing.expires_after_seconds", 10),
        read_signing_key=str(conf.get("jwt.signing.read.key", "") or ""),
        read_expires_after_seconds=conf.get_int(
            "jwt.signing.read.expires_after_seconds", 60))


def cmd_master(args):
    from seaweedfs_tpu.master.server import MasterServer

    peers = [p for p in args.peers.split(",") if p]
    m = MasterServer(host=args.ip, port=args.port,
                     volume_size_limit_mb=args.volumeSizeLimitMB,
                     default_replication=args.defaultReplication,
                     pulse_seconds=args.pulseSeconds,
                     guard=_load_guard(),
                     peers=peers, raft_dir=args.mdir)
    m.start()
    print(f"master listening on {m.address}" +
          (f", raft peers {m.raft.peers}" if peers else ""))
    _wait_forever([m])


def cmd_master_follower(args):
    from seaweedfs_tpu.master.follower import MasterFollower

    f = MasterFollower(args.masters.split(","), host=args.ip, port=args.port)
    f.start()
    print(f"master follower on {f.address} tracking {args.masters}")
    _wait_forever([f])


def cmd_volume(args):
    from seaweedfs_tpu.volume_server.server import VolumeServer

    dirs = args.dir.split(",")
    maxes = [int(x) for x in args.max.split(",")] if args.max else None
    if maxes and len(maxes) == 1:
        maxes = maxes * len(dirs)
    vs = VolumeServer(dirs, args.mserver, host=args.ip, port=args.port,
                      rack=args.rack, data_center=args.dataCenter,
                      max_volume_counts=maxes,
                      pulse_seconds=args.pulseSeconds,
                      guard=_load_guard())
    vs.start()
    print(f"volume server listening on {vs.address}, dirs={dirs}")
    _wait_forever([vs])


def cmd_filer(args):
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer

    store = SqliteStore(args.db) if args.db else None
    f = FilerServer(args.master, host=args.ip, port=args.port, store=store,
                    chunk_size=args.maxMB * 1024 * 1024,
                    replication=args.replication,
                    collection=args.collection, guard=_load_guard(),
                    peers=args.peers.split(",") if args.peers else None,
                    persist_meta_log=args.metaLog)
    f.start()
    print(f"filer listening on {f.address}")
    _wait_forever([f])


def _load_identities(path):
    from seaweedfs_tpu.s3api.auth import Identity

    if not path:
        return None
    with open(path) as f:
        config = json.load(f)
    return [Identity(name=i["name"], access_key=i["access_key"],
                     secret_key=i["secret_key"],
                     actions=i.get("actions", ["Admin"]))
            for i in config.get("identities", [])]


def cmd_s3(args):
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.s3api.server import S3ApiServer

    store = SqliteStore(args.db) if args.db else None
    filer = FilerServer(args.master, port=0, store=store,
                        guard=_load_guard())
    filer.start()
    s3 = S3ApiServer(filer, host=args.ip, port=args.port,
                     identities=_load_identities(args.config))
    s3.start()
    print(f"s3 gateway on {s3.address} (filer {filer.address})")
    _wait_forever([s3, filer])


def cmd_iam(args):
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.iamapi.server import IamApiServer
    from seaweedfs_tpu.s3api.server import S3ApiServer

    store = SqliteStore(args.db) if args.db else None
    filer = FilerServer(args.master, port=0, store=store,
                        guard=_load_guard())
    filer.start()
    s3 = S3ApiServer(filer, port=args.s3Port,
                     identities=_load_identities(args.config))
    s3.start()
    iam = IamApiServer(filer, host=args.ip, port=args.port, s3_server=s3)
    iam.start()
    print(f"iam api on {iam.address} (s3 {s3.address})")
    _wait_forever([iam, s3, filer])


def cmd_server(args):
    """Combined master + volume + filer (+ s3) in one process
    (weed/command/server.go)."""
    from seaweedfs_tpu.filer.filer_store import SqliteStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    stoppables = []
    guard = _load_guard()
    master = MasterServer(host=args.ip, port=args.masterPort,
                          volume_size_limit_mb=args.volumeSizeLimitMB,
                          pulse_seconds=args.pulseSeconds, guard=guard)
    master.start()
    stoppables.append(master)
    print(f"master on {master.address}")

    dirs = args.dir.split(",")
    vs = VolumeServer(dirs, master.address, host=args.ip,
                      port=args.volumePort, rack=args.rack,
                      pulse_seconds=args.pulseSeconds, guard=guard)
    vs.start()
    vs.heartbeat_once()
    stoppables.append(vs)
    print(f"volume server on {vs.address}")

    if args.filer or args.s3:
        store = SqliteStore(args.db) if args.db else None
        filer = FilerServer(master.address, host=args.ip,
                            port=args.filerPort, store=store, guard=guard)
        filer.start()
        stoppables.append(filer)
        print(f"filer on {filer.address}")
        if args.s3:
            s3 = S3ApiServer(filer, host=args.ip, port=args.s3Port,
                             identities=_load_identities(args.config))
            s3.start()
            stoppables.append(s3)
            print(f"s3 gateway on {s3.address}")
    _wait_forever(stoppables)


def cmd_shell(args):
    from seaweedfs_tpu.shell import commands as sh

    env = sh.CommandEnv(args.master)
    print(f"connected to master {args.master}; .help for commands")
    handlers = {
        "volume.list": lambda a: print(json.dumps(sh.volume_list(env),
                                                  indent=2)),
        "volume.vacuum": lambda a: print(sh.volume_vacuum(
            env, float(a[0]) if a else None)),
        "ec.encode": lambda a: print(sh.ec_encode(
            env, int(a[0]), plan_only="-plan" in a)),
        "ec.decode": lambda a: print(sh.ec_decode(
            env, int(a[0]), plan_only="-plan" in a)),
        "ec.rebuild": lambda a: print(sh.ec_rebuild(
            env, int(a[0]), plan_only="-plan" in a)),
        "ec.balance": lambda a: print(sh.ec_balance(
            env, plan_only="-plan" in a)),
    }
    while True:
        try:
            line = input("> ").strip()
        except EOFError:
            return
        if not line:
            continue
        if line in (".exit", "exit", "quit"):
            return
        if line == ".help":
            print("commands:", ", ".join(sorted(handlers)))
            continue
        name, *rest = line.split()
        fn = handlers.get(name)
        if fn is None:
            print(f"unknown command {name!r}; .help lists commands")
            continue
        try:
            fn(rest)
        except (RpcError, ValueError) as e:
            print(f"error: {e}")


def cmd_benchmark(args):
    from seaweedfs_tpu.benchmark import run_benchmark

    run_benchmark(args.master, num_files=args.n, file_size=args.size,
                  concurrency=args.c, delete_percent=args.deletePercent,
                  replication=args.replication)


def cmd_upload(args):
    with open(args.file, "rb") as f:
        body = f.read()
    a = call(args.master, f"/dir/assign?replication={args.replication}")
    headers = {"X-File-Name": os.path.basename(args.file)}
    if a.get("auth"):
        headers["Authorization"] = "BEARER " + a["auth"]
    resp = call(a["url"], f"/{a['fid']}", raw=body, method="POST",
                headers=headers)
    print(json.dumps({"fid": a["fid"], "url": a["url"],
                      "size": resp.get("size")}))


def cmd_download(args):
    vid = args.fid.split(",")[0]
    found = call(args.master, f"/dir/lookup?volumeId={vid}")
    data = call(found["locations"][0]["url"], f"/{args.fid}")
    out = args.output or args.fid.replace(",", "_")
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {len(data)} bytes to {out}")


def cmd_scaffold(args):
    from seaweedfs_tpu.util.config import scaffold

    text = scaffold(args.config)
    if args.output:
        path = os.path.join(args.output, args.config + ".toml")
        with open(path, "w") as f:
            f.write(text)
        print(f"wrote {path}")
    else:
        print(text, end="")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="weed", description=__doc__)
    parser.add_argument("-v", type=int, default=0,
                        help="glog verbosity level")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("master", help="start a master server")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-volumeSizeLimitMB", type=int, default=1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-peers", default="",
                   help="comma-separated other master addresses (raft)")
    p.add_argument("-mdir", default="", help="raft state directory")
    p.set_defaults(fn=cmd_master)

    p = sub.add_parser("master.follower",
                       help="read-only lookup/assign cache master")
    p.add_argument("-masters", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=9334)
    p.set_defaults(fn=cmd_master_follower)

    p = sub.add_parser("volume", help="start a volume server")
    p.add_argument("-dir", default="./data")
    p.add_argument("-max", default="8")
    p.add_argument("-mserver", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8080)
    p.add_argument("-rack", default="")
    p.add_argument("-dataCenter", default="")
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.set_defaults(fn=cmd_volume)

    p = sub.add_parser("filer", help="start a filer server")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8888)
    p.add_argument("-maxMB", type=int, default=4)
    p.add_argument("-db", default="", help="sqlite path (default: memory)")
    p.add_argument("-replication", default="")
    p.add_argument("-collection", default="")
    p.add_argument("-peers", default="",
                   help="comma-separated peer filers to aggregate")
    p.add_argument("-metaLog", action="store_true",
                   help="persist the metadata change log")
    p.set_defaults(fn=cmd_filer)

    p = sub.add_parser("s3", help="start an s3 gateway (+embedded filer)")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8333)
    p.add_argument("-db", default="")
    p.add_argument("-config", default="", help="identities json")
    p.set_defaults(fn=cmd_s3)

    p = sub.add_parser("iam", help="start an IAM management API (+s3+filer)")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-port", type=int, default=8111)
    p.add_argument("-s3Port", type=int, default=8333)
    p.add_argument("-db", default="", help="sqlite path (default: memory)")
    p.add_argument("-config", default="", help="s3 identities json")
    p.set_defaults(fn=cmd_iam)

    p = sub.add_parser("server", help="combined master+volume(+filer)(+s3)")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-dir", default="./data")
    p.add_argument("-masterPort", type=int, default=9333)
    p.add_argument("-volumePort", type=int, default=8080)
    p.add_argument("-filerPort", type=int, default=8888)
    p.add_argument("-s3Port", type=int, default=8333)
    p.add_argument("-volumeSizeLimitMB", type=int, default=1024)
    p.add_argument("-pulseSeconds", type=float, default=5.0)
    p.add_argument("-filer", action="store_true")
    p.add_argument("-s3", action="store_true")
    p.add_argument("-db", default="")
    p.add_argument("-config", default="")
    p.add_argument("-rack", default="")
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("shell", help="interactive admin shell")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.set_defaults(fn=cmd_shell)

    p = sub.add_parser("benchmark", help="write/read load benchmark")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000)
    p.add_argument("-size", type=int, default=1024)
    p.add_argument("-c", type=int, default=16)
    p.add_argument("-deletePercent", type=int, default=0)
    p.add_argument("-replication", default="000")
    p.set_defaults(fn=cmd_benchmark)

    p = sub.add_parser("upload", help="upload one file")
    p.add_argument("file")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-replication", default="000")
    p.set_defaults(fn=cmd_upload)

    p = sub.add_parser("download", help="download by fid")
    p.add_argument("fid")
    p.add_argument("-master", default="127.0.0.1:9333")
    p.add_argument("-output", default="")
    p.set_defaults(fn=cmd_download)

    p = sub.add_parser("scaffold", help="print a config template")
    p.add_argument("-config", default="security",
                   help="security|master|filer|replication|notification")
    p.add_argument("-output", default="", help="write <name>.toml to dir")
    p.set_defaults(fn=cmd_scaffold)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=lambda a: print(VERSION))

    args = parser.parse_args(argv)
    if args.v:
        from seaweedfs_tpu.util import glog

        glog.set_verbosity(args.v)
    args.fn(args)


if __name__ == "__main__":
    main()
