"""Remote storage providers (weed/remote_storage).

A RemoteStorageClient abstracts an external object store that filer
directories can be mounted onto: traverse its namespace, read/write/
delete objects, and stat them.  The reference ships an S3 provider
(remote_storage/s3/s3_storage_client.go) built on the AWS SDK; here the
S3 provider speaks SigV4 through the framework's own client (works
against any S3-compatible endpoint, including this framework's gateway),
and a `local` directory-tree provider exists for tests and air-gapped
use.

A remote location string is `name/bucket/path` where `name` identifies
a configured storage (remote_pb.RemoteStorageLocation).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class RemoteConf:
    """One configured remote storage (remote.conf entry)."""

    name: str
    type: str = "s3"  # s3 | local
    endpoint: str = ""
    access_key: str = ""
    secret_key: str = ""
    directory: str = ""  # local provider root

    def to_dict(self) -> dict:
        return {"name": self.name, "type": self.type,
                "endpoint": self.endpoint, "access_key": self.access_key,
                "secret_key": self.secret_key,
                "directory": self.directory}

    @classmethod
    def from_dict(cls, d: dict) -> "RemoteConf":
        return cls(**{k: d[k] for k in
                      ("name", "type", "endpoint", "access_key",
                       "secret_key", "directory") if k in d})


@dataclass
class RemoteLocation:
    """Parsed `name/bucket/path` location."""

    name: str
    bucket: str = ""
    path: str = "/"

    @classmethod
    def parse(cls, s: str) -> "RemoteLocation":
        parts = s.strip("/").split("/", 2)
        return cls(name=parts[0],
                   bucket=parts[1] if len(parts) > 1 else "",
                   path="/" + (parts[2] if len(parts) > 2 else ""))

    def __str__(self) -> str:
        return f"{self.name}/{self.bucket}{self.path}"

    def child(self, name: str) -> "RemoteLocation":
        base = self.path.rstrip("/")
        return RemoteLocation(self.name, self.bucket, f"{base}/{name}")


@dataclass
class RemoteObject:
    """One remote object's metadata (remote_pb.RemoteEntry)."""

    key: str  # path relative to the traversal root, no leading /
    size: int = 0
    mtime: float = 0.0
    etag: str = ""

    def to_remote_entry(self, storage_name: str) -> dict:
        return {"storage_name": storage_name, "remote_size": self.size,
                "remote_mtime": self.mtime, "remote_e_tag": self.etag,
                "last_local_sync_ts_ns": time.time_ns()}


class RemoteStorageClient:
    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        raise NotImplementedError

    def read_file(self, loc: RemoteLocation) -> bytes:
        raise NotImplementedError

    def write_file(self, loc: RemoteLocation, data: bytes) -> RemoteObject:
        raise NotImplementedError

    def delete_file(self, loc: RemoteLocation):
        raise NotImplementedError

    def delete_prefix(self, loc: RemoteLocation):
        """Delete every object under a prefix (directory delete)."""
        for obj in list(self.traverse(loc)):
            self.delete_file(loc.child(obj.key))

    def read_range(self, loc: RemoteLocation, offset: int,
                   size: int) -> bytes:
        """Ranged read; default slices a whole-object fetch."""
        return self.read_file(loc)[offset:offset + size]

    def write_file_from(self, loc: RemoteLocation, read_chunk,
                        total_size: int) -> "RemoteObject":
        """Streaming write from a chunk reader.  The default accumulates
        (single-PUT stores); file-backed providers override to stream."""
        parts = []
        while True:
            chunk = read_chunk()
            if not chunk:
                break
            parts.append(chunk)
        return self.write_file(loc, b"".join(parts))

    def stat(self, loc: RemoteLocation) -> Optional[RemoteObject]:
        """Metadata of one object, or None when absent."""
        raise NotImplementedError


class LocalRemoteStorage(RemoteStorageClient):
    """A directory tree as a 'remote' (tests, NFS mounts, air-gap)."""

    def __init__(self, conf: RemoteConf):
        self.root = conf.directory

    def _abs(self, loc: RemoteLocation) -> str:
        return os.path.join(self.root, loc.bucket,
                            loc.path.lstrip("/"))

    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        base = self._abs(loc)
        for dirpath, _, files in os.walk(base):
            for f in sorted(files):
                full = os.path.join(dirpath, f)
                st = os.stat(full)
                yield RemoteObject(
                    key=os.path.relpath(full, base),
                    size=st.st_size, mtime=st.st_mtime,
                    etag=f"{st.st_mtime_ns:x}-{st.st_size:x}")

    def read_file(self, loc: RemoteLocation) -> bytes:
        with open(self._abs(loc), "rb") as f:
            return f.read()

    def write_file(self, loc: RemoteLocation, data: bytes) -> RemoteObject:
        path = self._abs(loc)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        st = os.stat(path)
        return RemoteObject(key=loc.path.lstrip("/"), size=len(data),
                            mtime=st.st_mtime,
                            etag=f"{st.st_mtime_ns:x}-{st.st_size:x}")

    def delete_file(self, loc: RemoteLocation):
        try:
            os.remove(self._abs(loc))
        except FileNotFoundError:
            pass

    def read_range(self, loc: RemoteLocation, offset: int,
                   size: int) -> bytes:
        with open(self._abs(loc), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def write_file_from(self, loc: RemoteLocation, read_chunk,
                        total_size: int) -> RemoteObject:
        path = self._abs(loc)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".part"
        with open(tmp, "wb") as f:
            while True:
                chunk = read_chunk()
                if not chunk:
                    break
                f.write(chunk)
        os.replace(tmp, path)
        st = os.stat(path)
        return RemoteObject(key=loc.path.lstrip("/"), size=st.st_size,
                            mtime=st.st_mtime,
                            etag=f"{st.st_mtime_ns:x}-{st.st_size:x}")

    def stat(self, loc: RemoteLocation) -> Optional[RemoteObject]:
        try:
            st = os.stat(self._abs(loc))
        except FileNotFoundError:
            return None
        return RemoteObject(key=loc.path.lstrip("/"), size=st.st_size,
                            mtime=st.st_mtime,
                            etag=f"{st.st_mtime_ns:x}-{st.st_size:x}")


class S3RemoteStorage(RemoteStorageClient):
    """Any S3-compatible endpoint via the SigV4 client
    (remote_storage/s3/s3_storage_client.go)."""

    def __init__(self, conf: RemoteConf):
        from ..wdclient.s3_client import S3Client

        self.client = S3Client(conf.endpoint, conf.access_key,
                               conf.secret_key)

    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        import calendar

        prefix = loc.path.lstrip("/")
        for obj in self.client.list_objects(loc.bucket, prefix):
            key = obj["key"]
            data_key = key[len(prefix):].lstrip("/") if prefix else key
            mtime = 0.0
            if obj.get("last_modified"):
                try:
                    mtime = calendar.timegm(time.strptime(
                        obj["last_modified"], "%Y-%m-%dT%H:%M:%S.000Z"))
                except ValueError:
                    pass
            yield RemoteObject(key=data_key or key, size=obj["size"],
                              mtime=mtime, etag=obj.get("etag", ""))

    def read_file(self, loc: RemoteLocation) -> bytes:
        return self.client.get_object(loc.bucket, loc.path.lstrip("/"))

    def write_file(self, loc: RemoteLocation, data: bytes) -> RemoteObject:
        self.client.put_object(loc.bucket, loc.path.lstrip("/"), data)
        return RemoteObject(key=loc.path.lstrip("/"), size=len(data),
                            mtime=time.time())

    def delete_file(self, loc: RemoteLocation):
        self.client.delete_object(loc.bucket, loc.path.lstrip("/"))

    def read_range(self, loc: RemoteLocation, offset: int,
                   size: int) -> bytes:
        return self.client.get_object_range(
            loc.bucket, loc.path.lstrip("/"), offset, size)

    def stat(self, loc: RemoteLocation) -> Optional[RemoteObject]:
        import calendar

        key = loc.path.lstrip("/")
        # exact-key prefix listing: the full key is the prefix, so the
        # page holds the object itself plus at most same-prefix siblings
        for obj in self.client.list_objects(loc.bucket, key):
            if obj["key"] == key:
                mtime = 0.0
                if obj.get("last_modified"):
                    try:
                        mtime = calendar.timegm(time.strptime(
                            obj["last_modified"],
                            "%Y-%m-%dT%H:%M:%S.000Z"))
                    except ValueError:
                        pass
                return RemoteObject(key=key, size=obj["size"],
                                    mtime=mtime,
                                    etag=obj.get("etag", ""))
        return None


def make_remote_client(conf: RemoteConf) -> RemoteStorageClient:
    if conf.type == "local":
        return LocalRemoteStorage(conf)
    if conf.type == "s3":
        return S3RemoteStorage(conf)
    raise ValueError(f"unknown remote storage type {conf.type!r}")
