"""Volume server daemon: public object HTTP API + admin/EC RPC + heartbeat.

Parity with weed/server/volume_server*.go:
  * GET/HEAD/POST/DELETE /{fid} with replication fan-out guarded by
    type=replicate (volume_server_handlers_write.go:18-137,
    topology/store_replicate.go:24-141)
  * admin RPCs: allocate/delete/mount/readonly/vacuum/status
    (volume_grpc_admin.go, volume_grpc_vacuum.go)
  * the 9 EC handlers: generate/rebuild/copy/delete/mount/unmount/
    shard-read/blob-delete/to-volume (volume_grpc_erasure_coding.go:38-438)
  * heartbeat client loop (volume_grpc_client_to_master.go:46-120)

EC reads use the local -> remote -> reconstruct ladder; remote shard spans
are fetched over HTTP from peers found via the master's EC lookup, cached
with a freshness window (store_ec.go:227-268).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

import numpy as np

from .. import profiling, qos, tracing
from ..rpc import policy
from ..rpc import prefork as _prefork
from ..rpc.http_rpc import (FileSlice, Request, Response, RpcError,
                            RpcServer, call, call_stream, sendfile_enabled,
                            stream_file)
from ..util import faults
from ..security import Guard, gen_write_jwt, token_from_request
from ..stats import access
from ..stats import events as events_mod
from ..stats import healthz
from ..stats import metrics as stats
from ..storage import types as t
from ..storage.erasure_coding import TOTAL_SHARDS_COUNT, to_ext
from ..storage.erasure_coding import codes as ec_codes
from ..storage.erasure_coding import decoder as ec_decoder
from ..storage.erasure_coding.encoder import load_volume_info
from ..storage.erasure_coding.ec_volume import (EcDeletedError,
                                                EcNotFoundError,
                                                rebuild_ecx_file)
from ..storage import volume_backup
from ..storage.needle import Needle
from ..storage.store import Store
from ..storage.volume import (CookieMismatchError, DeletedError,
                              NotFoundError, VolumeError)

# EC shard-location cache freshness tiers (store_ec.go:227-268): a lookup
# that errored or found too few shards to reconstruct stays fresh only
# briefly; an incomplete-but-usable set refreshes at a medium cadence; a
# full set is trusted for a long window.
EC_SHARD_CACHE_TTL_ERROR = 11.0
EC_SHARD_CACHE_TTL_INCOMPLETE = 7 * 60.0
EC_SHARD_CACHE_TTL_HEALTHY = 37 * 60.0


def _resp_len(resp) -> int:
    """Bytes a handler reply carries (access accounting): buffered
    bodies directly, streamed/sendfile bodies via Content-Length."""
    body = getattr(resp, "body", resp)
    if isinstance(body, (bytes, bytearray, memoryview)):
        return len(body)
    headers = getattr(resp, "headers", None) or {}
    try:
        return int(headers.get("Content-Length", 0) or 0)
    except (TypeError, ValueError):
        return 0


class _EcBindingEntry:
    """One EC volume's native serving state (binding + the EcVolume
    instance it was built from, to detect remounts)."""

    __slots__ = ("ev", "binding")

    def __init__(self, ev, binding):
        self.ev = ev
        self.binding = binding


class _InflightGate:
    """In-flight byte throttle (volume_server.go:21-50 cond-var limits).

    Bounds the bytes concurrently being PROCESSED by upload/download
    handlers; the HTTP substrate has already buffered the request body by
    routing time, so this caps needle assembly + replication fan-out
    concurrency rather than socket buffering.  Zero limit = unlimited."""

    def __init__(self, limit_bytes: int, timeout: float = 30.0):
        self.limit = limit_bytes
        self.timeout = timeout
        self._current = 0
        self._cond = threading.Condition()

    def acquire(self, n: int, timeout: float = None) -> bool:
        if self.limit <= 0:
            return True
        deadline = time.monotonic() + (
            self.timeout if timeout is None else timeout)
        with self._cond:
            # a single oversized request may exceed the limit when alone
            while self._current > 0 and self._current + n > self.limit:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
            self._current += n
            return True

    def release(self, n: int):
        if self.limit <= 0:
            return
        with self._cond:
            self._current -= n
            self._cond.notify_all()


class _RequestShedder:
    """Bounded-inflight load shedding for the object API: unlike the
    byte gates above (which QUEUE callers), excess requests are shed
    immediately with 503 + Retry-After so clients back off instead of
    piling onto a saturated server.  Zero limit = off; the limit is
    re-read per request (WEED_VS_MAX_INFLIGHT) so it can be flipped
    live."""

    def __init__(self, limit: int = 0):
        self.limit = limit
        self._current = 0
        self._lock = threading.Lock()

    def _effective_limit(self) -> int:
        env = os.environ.get("WEED_VS_MAX_INFLIGHT", "")
        return int(env) if env else self.limit

    def try_acquire(self) -> bool:
        limit = self._effective_limit()
        with self._lock:
            if limit > 0 and self._current >= limit:
                return False
            self._current += 1
            return True

    def release(self):
        with self._lock:
            self._current -= 1

    @property
    def current(self) -> int:
        with self._lock:
            return self._current


def _remove_quiet(*paths: str):
    """Best-effort unlink for rollback paths."""
    for path in paths:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass


def _parse_range(header: str, total: int):
    """Parse a Range header against an entity of `total` bytes
    (volume_server_handlers_read.go:238 processRangeRequest).

    -> (start, end_exclusive) for a single satisfiable range, None when
    unsatisfiable (caller replies 416), Ellipsis to ignore the header and
    serve the full entity (malformed or multi-range)."""
    if not header.startswith("bytes="):
        return ...
    spec = header[len("bytes="):]
    if "," in spec:  # multi-range: legal to ignore and serve 200
        return ...
    start_s, _, end_s = spec.partition("-")
    try:
        if start_s == "":
            n = int(end_s)  # suffix form: last n bytes
            if n <= 0:
                return None
            return max(0, total - n), total
        start = int(start_s)
        end = int(end_s) + 1 if end_s else total
    except ValueError:
        return ...
    if start >= total or start < 0 or end <= start:
        return None
    return start, min(end, total)


_GZIPPABLE_MIME = ("text/", "application/json", "application/javascript",
                   "application/xml", "application/xhtml", "image/svg")
_GZIPPABLE_EXT = (".txt", ".htm", ".html", ".css", ".js", ".json", ".xml",
                  ".csv", ".svg", ".md", ".log", ".conf", ".yaml", ".yml")


def _is_gzippable(name: bytes, mime: bytes) -> bool:
    """Compressibility heuristic (util/http/compression.go IsGzippable):
    by mime family first, by filename extension otherwise."""
    m = mime.decode(errors="replace").lower()
    if m:
        if any(m.startswith(p) for p in _GZIPPABLE_MIME):
            return True
        if m == "application/octet-stream":
            pass  # fall through to the extension check
        else:
            return False
    n = name.decode(errors="replace").lower()
    return any(n.endswith(e) for e in _GZIPPABLE_EXT)


class VolumeServer:
    def __init__(self, directories: list[str], master_address: str,
                 host: str = "127.0.0.1", port: int = 0,
                 public_url: str = "", data_center: str = "",
                 rack: str = "", max_volume_counts: Optional[list[int]] = None,
                 pulse_seconds: float = 5.0, ec_encoder_backend=None,
                 guard: Optional[Guard] = None, tier_backends=None,
                 enable_tcp: bool = False, read_mode: str = "proxy",
                 needle_map_kind: str = "memory", fsync: bool = False,
                 upload_limit_mb: int = 0, download_limit_mb: int = 0,
                 max_inflight_requests: int = 0):
        if read_mode not in ("local", "proxy", "redirect"):
            raise ValueError(f"unknown readMode {read_mode!r}")
        self.read_mode = read_mode
        self.upload_gate = _InflightGate(upload_limit_mb << 20)
        self.download_gate = _InflightGate(download_limit_mb << 20)
        self.request_shedder = _RequestShedder(max_inflight_requests)
        # weighted-fair admission over the same limit; WEED_QOS=0 falls
        # back to the flat shedder above (WEED_VS_MAX_INFLIGHT is the
        # deprecated alias for WEED_QOS_VS_LIMIT)
        self.qos_gate = qos.AdmissionGate(
            "volume", limit_env="WEED_QOS_VS_LIMIT",
            fallback_env="WEED_VS_MAX_INFLIGHT",
            default_limit=max_inflight_requests)
        # workload analytics sketches for this daemon's needle traffic
        self.access_recorder = access.AccessRecorder(node="volume")
        self.enable_tcp = enable_tcp
        self._tcp_sock = None
        # tier backends must be registered before Store discovery so
        # .vif-only (tiered) volumes load (storage/tier.py registry)
        if tier_backends:
            from ..storage import tier

            for conf in tier_backends:
                tier.register_tier_backend(conf)
        self.server = RpcServer(host, port, service_name="volume")
        if self.server._prefork_workers > 1:
            # workers serve reads from their fork-time needle-map
            # snapshot and tail the .idx for needles the parent wrote
            # after the fork — which requires unbuffered idx appends
            from ..storage import needle_map as _needle_map

            _needle_map.FLUSH_APPENDS = True
        # drain/leave must reach every prefork worker, not just the
        # parent that executed them
        self.server.fanout_prefixes.update({"/admin/drain",
                                            "/admin/leave"})
        # the configured seed list survives leader redirects so a dead
        # leader never strands the heartbeat loop
        self._seed_masters = [m for m in master_address.split(",") if m]
        self.master_address = self._seed_masters[0]
        self.pulse_seconds = pulse_seconds
        self.guard = guard or Guard()
        self.store = Store(
            directories, max_volume_counts, ip=host,
            port=self.server.port, public_url=public_url,
            data_center=data_center, rack=rack,
            ec_encoder_backend=ec_encoder_backend,
            needle_map_kind=needle_map_kind, fsync=fsync)
        # a disk-failure demotion must reach the master NOW, not at the
        # next pulse: assigns in the gap would keep landing on the
        # demoted volume (the heartbeat reports read_only per volume)
        self.store.on_demote = self._on_demote
        # unified read cache over the needle-read path: parsed needles
        # keyed by fid, validated against the live needle map on every
        # hit (RAM + optional HBM tier; no disk tier — the needles are
        # already on local disk)
        from ..cache import TieredReadCache

        self.read_cache = TieredReadCache()
        self._stop = threading.Event()
        # elasticity state: `draining` marks this server read-only while
        # the curator evacuates it; the request counters feed the rps /
        # byte-rate telemetry piggybacked on every heartbeat; children
        # spawned by scale.up jobs are reaped in stop()
        self.draining = False
        self._tele_lock = threading.Lock()
        self._req_counts = {"read": 0, "write": 0, "bytes": 0}
        self._tele_prev = (time.monotonic(), 0, 0, 0)
        self._occ_peak = 0.0
        self.scale_children: list = []
        # in-process spawn seam: tests and bench phases install a
        # callable(job) -> url here so scale.up never forks on the
        # 1-core CI harness; None means subprocess `weed.py volume`
        self.spawn_volume_server = None
        # per-volume-id copy locks: concurrent copies of the SAME vid must
        # not race each other's temp files / exists-checks, but a slow copy
        # of one volume must not serialize copies of unrelated volumes
        self._copy_locks: dict[int, threading.Lock] = {}
        self._copy_locks_mu = threading.Lock()
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._ec_locations: dict[int, tuple[float, dict[int, list[str]]]] = {}
        self._register_routes()
        # EC volumes discovered on disk at startup need the remote-fetch
        # ladder too, not just ones mounted via RPC
        for loc in self.store.locations:
            for vid, ev in loc.ec_volumes.items():
                ev.remote_reader = self._make_remote_reader(vid)
        # maintenance worker: pulls curator jobs from the master and
        # executes them under the foreground-load-aware byte pacer
        from ..maintenance.worker import MaintenanceWorker

        self.maintenance_worker = MaintenanceWorker(self)

    @property
    def address(self) -> str:
        return self.server.address

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self.server.start()
        if self.enable_tcp:
            self._start_tcp()
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True)
        self._heartbeat_thread.start()
        self.maintenance_worker.start()

    def stop(self):
        self._stop.set()
        self.maintenance_worker.stop()
        for child in self.scale_children:
            try:  # subprocess volume servers spawned by scale.up jobs
                child.terminate()
                child.wait(timeout=10)
            except Exception:
                pass
        self.scale_children = []
        if getattr(self, "_native_owner", False) or \
                getattr(self, "_native_jwt_owner", False) or \
                getattr(self, "_native_listener_owner", False):
            from ..storage import native_engine

            if getattr(self, "_native_owner", False):
                for vid in getattr(self, "_native_bound", set()):
                    native_engine.unserve_volume(vid)
                for vid, entry in getattr(self, "_native_ec", {}).items():
                    native_engine.unserve_ec_volume(vid)
                    entry.binding.close()
                native_engine.release_serving()
                self._native_owner = False
            if getattr(self, "_native_jwt_owner", False):
                native_engine.server_set_jwt("", "", 10)
                self._native_jwt_owner = False
            if getattr(self, "_native_listener_owner", False):
                native_engine.server_stop()
                self._native_listener_owner = False
        if self._tcp_sock is not None:
            try:
                self._tcp_sock.close()
            except OSError:
                pass
        self.server.stop()
        self.read_cache.close()
        self.store.close()

    # -- native fast-path serving registry ------------------------------------
    def _sync_native_serving(self):
        """Keep the native TCP server's vid->volume bindings in step with
        the store (only the server instance that owns the process-wide
        native listener binds; others leave the registry alone)."""
        if not getattr(self, "_native_owner", False):
            return
        from ..storage import native_engine

        current = {}
        ec_current = {}
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                # TTL volumes serve natively too: the engine 404s
                # expired needles itself (svn_set_ttl, set at map
                # creation — volume_read.go:27-35 semantics)
                if isinstance(v.nm, native_engine.NativeNeedleMap):
                    current[vid] = v.nm
            for vid, ev in list(loc.ec_volumes.items()):
                ec_current[vid] = ev
        bound = getattr(self, "_native_bound", set())
        for vid in bound - current.keys():
            native_engine.unserve_volume(vid)
        for vid, nm in current.items():
            native_engine.serve_volume(vid, nm)
        self._native_bound = set(current)
        # EC volumes: bind local-shard read serving; rebind when the
        # EcVolume instance or its shard set changed (mount/copy/rebuild)
        ec_bound = getattr(self, "_native_ec", {})
        for vid in set(ec_bound) - ec_current.keys():
            native_engine.unserve_ec_volume(vid)
            ec_bound.pop(vid).binding.close()
        for vid, ev in ec_current.items():
            entry = ec_bound.get(vid)
            if entry is not None and entry.ev is not ev:
                native_engine.unserve_ec_volume(vid)
                entry.binding.close()
                entry = None
            if entry is None:
                try:
                    binding = native_engine.NativeEcBinding(ev)
                except (OSError, RuntimeError):
                    continue  # e.g. .ecx missing mid-copy: retry next sync
                entry = _EcBindingEntry(ev, binding)
                ec_bound[vid] = entry
            else:
                entry.binding.sync_shards(ev)
            native_engine.serve_ec_volume(vid, entry.binding)
        self._native_ec = ec_bound
        self._sync_native_replicas()

    def _sync_native_replicas(self):
        """Publish each replicated volume's peer fast-path addresses to
        the engine so native writes fan out without a 307 round-trip
        (store_replicate.go:24-141's location set, refreshed from the
        master's lookup on the heartbeat cadence; resolution failures
        just leave the vid unpublished — writes fall back to the Python
        handler's fan-out)."""
        from ..storage import native_engine
        from ..wdclient.volume_tcp_client import VolumeTcpClient

        now = time.monotonic()
        cache = getattr(self, "_replica_sync", None)
        if cache is None:
            cache = self._replica_sync = {"at": 0.0, "vids": {},
                                          "fresh": {}}
        if now - cache["at"] < max(self.pulse_seconds * 4, 4.0):
            return
        cache["at"] = now
        client = getattr(self, "_replica_tcp", None)
        if client is None:
            client = self._replica_tcp = VolumeTcpClient()
        # bound the heartbeat-path work: unpublished vids first, then
        # round-robin refresh of published ones every REFRESH seconds,
        # at most BUDGET lookups per tick (each is a blocking master
        # round-trip — hundreds of replicated volumes must not stall
        # the heartbeat thread for seconds)
        BUDGET, REFRESH = 16, 30.0
        candidates = []
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                extra = v.super_block.replica_placement.copy_count() - 1
                if extra <= 0 or not isinstance(
                        v.nm, native_engine.NativeNeedleMap):
                    continue
                age = now - cache["fresh"].get(vid, 0.0)
                if vid not in cache["vids"]:
                    candidates.append((0.0, vid))  # never resolved
                elif age >= REFRESH:
                    candidates.append((-age, vid))  # stalest first
        candidates.sort()
        for _, vid in candidates[:BUDGET]:
            try:
                lookup = call(self.master_address,
                              f"/dir/lookup?volumeId={vid}", timeout=5)
                others = [l["url"] for l in lookup.get("locations", [])
                          if l["url"] != self.store.url]
                addrs = [client.tcp_address(u) for u in others]
            except Exception:
                continue  # unpublished: native writes 307 for now
            cache["fresh"][vid] = now
            if cache["vids"].get(vid) != addrs:
                native_engine.set_replicas(vid, addrs)
                cache["vids"][vid] = addrs

    # -- TCP fast path (volume_server_tcp, port+20000) -----------------------
    def _start_tcp(self):
        """Prefer the native engine's off-GIL server; fall back to the
        Python loop (reads only) when the library is missing, JWT signing
        requires the Python guard, or another in-process volume server
        already owns the native listener."""
        from ..storage import native_engine
        from ..wdclient.volume_tcp_client import TCP_PORT_OFFSET

        if native_engine.available():
            host, port = self.server.address.rsplit(":", 1)
            wanted = int(port) + TCP_PORT_OFFSET
            bound = native_engine.server_port()
            if bound <= 0:
                try:
                    bound = native_engine.server_start(
                        host, wanted if wanted <= 65535 else 0,
                        http_redirect=self.server.address)
                    self._native_listener_owner = True
                except OSError:
                    bound = 0
            # the listener may already exist (combined process: the
            # master starts it for assign leases); SERVING vids is a
            # separate, single-claim role per process
            if bound > 0 and native_engine.claim_serving():
                # JWT-secured clusters ride the fast path too: the
                # engine verifies fid-scoped HS256 tokens itself
                # (guard.go:18-50 semantics).  Keys are set only AFTER
                # the serving claim succeeds: a server that did not
                # engage must neither set nor (on stop) clear the
                # engine-global keys another in-process server relies
                # on — clearing them would fail open.
                if self.guard.signing or self.guard.read_signing:
                    native_engine.server_set_jwt(
                        self.guard.signing.key,
                        self.guard.read_signing.key,
                        self.guard.signing.expires_after_seconds)
                    self._native_jwt_owner = True
                # the listener may predate this volume server (combined
                # process: the master starts it for assign leases) —
                # the HTTP 302 fallback must point at OUR full handler
                native_engine.server_set_redirect(self.server.address)
                self.tcp_port = bound
                self._native_owner = True
                self._native_bound = set()
                self._sync_native_serving()
                return
        if not self.enable_tcp:
            return
        self._start_tcp_python()

    def _start_tcp_python(self):
        import socket
        import struct

        from ..wdclient.volume_tcp_client import TCP_PORT_OFFSET

        host, port = self.server.address.rsplit(":", 1)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        wanted = int(port) + TCP_PORT_OFFSET
        try:
            sock.bind((host, wanted if wanted <= 65535 else 0))
        except OSError:
            sock.bind((host, 0))  # convention port taken: ephemeral,
            # clients discover it via /admin/status tcp_port
        sock.listen(64)
        self._tcp_sock = sock
        self.tcp_port = sock.getsockname()[1]

        def reply(conn, status: int, payload: bytes):
            conn.sendall(struct.pack(">II", status, len(payload))
                         + payload)

        def serve_conn(conn):
            try:
                buf = b""
                while not self._stop.is_set():
                    while b"\n" not in buf:
                        chunk = conn.recv(4096)
                        if not chunk:
                            return
                        buf += chunk
                    line, _, buf = buf.partition(b"\n")
                    parts = line.decode(errors="replace").split()
                    if len(parts) not in (2, 3) or parts[0] != "G":
                        reply(conn, 400, b"bad request")
                        return
                    fid = parts[1]
                    # same read security as the HTTP path: an optional
                    # JWT rides as the third token
                    if self.guard.read_signing:
                        try:
                            self.guard.verify_read(
                                parts[2] if len(parts) == 3 else "",
                                fid)
                        except PermissionError as e:
                            reply(conn, 401, str(e).encode())
                            continue
                    try:
                        vid, nid, cookie = t.parse_file_id(fid)
                        n = self.store.read_needle(vid, nid,
                                                   cookie=cookie)
                        data = n.data
                        if n.is_compressed:
                            # fast path has no Accept-Encoding: agree
                            # with the HTTP handler and serve plain
                            import gzip as _gzip

                            data = _gzip.decompress(data)
                        reply(conn, 0, data)
                    except (NotFoundError, EcNotFoundError,
                            DeletedError, EcDeletedError,
                            CookieMismatchError):
                        reply(conn, 404, b"not found")
                    except Exception as e:
                        reply(conn, 500, str(e).encode())
            finally:
                conn.close()

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = sock.accept()
                except OSError:
                    return
                threading.Thread(target=serve_conn, args=(conn,),
                                 daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True).start()

    def _h_metrics(self, req: Request):
        """Prometheus exposition, with the native engine's off-GIL
        request counters folded in at scrape time."""
        if getattr(self, "_native_owner", False):
            from ..storage import native_engine

            for op, n in native_engine.server_stats().items():
                stats.VolumeServerNativeRequestCounter.labels(
                    op).set_cumulative(n)
        return stats.metrics_handler(req)

    def heartbeat_once(self):
        # keep native fast-path bindings fresh (handles change across
        # vacuum commits and volume add/delete)
        self._sync_native_serving()
        hb = self.store.collect_heartbeat()
        hb["telemetry"] = self._telemetry()
        if self.access_recorder.enabled:
            # access sketches ride the beat the node already sends —
            # the leader merges summaries, raw keys never leave here
            hb["access"] = self.access_recorder.summary()
        targets = [self.master_address] + [
            m for m in self._seed_masters if m != self.master_address]
        # shared failover policy: per-master breakers skip a dead seed,
        # full-jitter backoff separates rounds (was a hand-rolled loop)
        resp, winner = policy.failover_call(
            targets, "/api/heartbeat", payload=hb, timeout=10, rounds=1)
        self.master_address = winner
        self.store.volume_size_limit = resp.get("volume_size_limit", 0)
        # raft leader failover (volume_grpc_client_to_master.go:46-76):
        # keep heartbeating the leader so assigns see our volumes
        leader = resp.get("leader_address")
        if leader and not resp.get("leader", True):
            self.master_address = leader
        return resp

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            try:
                self.heartbeat_once()
            except RpcError:
                pass
            except Exception:
                # the heartbeat thread must never die: a missed beat is
                # recoverable, a dead loop gets the node reaped by the
                # master and strands every volume it holds
                import logging

                logging.getLogger(__name__).exception(
                    "heartbeat iteration failed")
            self._stop.wait(self.pulse_seconds)

    # -- routing -------------------------------------------------------------
    def _guarded(self, fn):
        """IP allow-list on admin routes (guard.go WhiteList wrapper)."""
        def wrapped(req: Request):
            peer = req.handler.client_address[0]
            if not self.guard.check_white_list(peer):
                raise RpcError(f"ip {peer} not allowed", 403)
            return fn(req)
        return wrapped

    def _register_routes(self):
        s = self.server
        g = self._guarded
        s.add("GET", "/admin/status",
              g(lambda r: {**self.store.status(),
                           "tcp_port": getattr(self, "tcp_port", 0)}))
        s.add("POST", "/admin/assign_volume", g(self._h_assign_volume))
        s.add("POST", "/admin/delete_volume", g(self._h_delete_volume))
        s.add("POST", "/admin/readonly", g(self._h_readonly))
        s.add("POST", "/admin/volume/mount", g(self._h_volume_mount))
        s.add("POST", "/admin/volume/unmount", g(self._h_volume_unmount))
        s.add("POST", "/admin/volume/copy", g(self._h_volume_copy))
        s.add("GET", "/admin/volume/status", g(self._h_volume_status))
        s.add("GET", "/admin/volume/tail", g(self._h_volume_tail))
        s.add("POST", "/admin/volume/sync", g(self._h_volume_sync))
        s.add("GET", "/admin/volume/read_all", g(self._h_volume_read_all))
        s.add("POST", "/admin/batch_delete", self._h_batch_delete)
        s.add("POST", "/admin/vacuum/check", g(self._h_vacuum_check))
        s.add("POST", "/admin/vacuum/compact", g(self._h_vacuum_compact))
        s.add("POST", "/admin/vacuum/commit", g(self._h_vacuum_commit))
        s.add("POST", "/admin/ec/generate", g(self._h_ec_generate))
        s.add("POST", "/admin/ec/rebuild", g(self._h_ec_rebuild))
        s.add("POST", "/admin/ec/mount", g(self._h_ec_mount))
        s.add("POST", "/admin/ec/unmount", g(self._h_ec_unmount))
        s.add("POST", "/admin/ec/copy", g(self._h_ec_copy))
        s.add("POST", "/admin/ec/delete_shards", g(self._h_ec_delete_shards))
        s.add("POST", "/admin/ec/to_volume", g(self._h_ec_to_volume))
        s.add("POST", "/admin/ec/scrub", g(self._h_ec_scrub))
        s.add("GET", "/admin/ec/recover_stats", g(self._h_ec_recover_stats))
        s.add("GET", "/admin/ec/codes", g(self._h_ec_codes))
        s.add("GET", "/admin/ec/inline_status", g(self._h_ec_inline_status))
        s.add("GET", "/admin/ec/shard_file", self._h_ec_shard_file)
        s.add("GET", "/admin/ec/shard_read", self._h_ec_shard_read)
        s.add("GET", "/admin/ec/shard_project", self._h_ec_shard_project)
        s.add("POST", "/admin/ec/rebuild_projected",
              g(self._h_ec_rebuild_projected))
        s.add("POST", "/admin/volume/configure_replication",
              g(self._h_configure_replication))
        s.add("POST", "/admin/volume/tier_upload", g(self._h_tier_upload))
        s.add("POST", "/admin/volume/tier_download",
              g(self._h_tier_download))
        s.add("POST", "/admin/remote/fetch_write",
              g(self._h_remote_fetch_write))
        s.add("POST", "/admin/drain", g(self._h_drain))
        s.add("POST", "/admin/leave", g(self._h_leave))
        s.add("POST", "/query", self._h_query)
        s.add("GET", "/metrics", self._h_metrics)
        s.add("GET", "/debug/traces", tracing.traces_handler)
        faults.mount(s)
        profiling.mount(s)
        qos.mount(s, gate=self.qos_gate)
        events_mod.mount(s)
        access.mount(s, self.access_recorder)
        healthz.mount_health(s, ready=self._ready_checks)
        s.add("GET", "/ui", self._h_ui)
        s.default_route = self._handle_object

    def _ready_checks(self):
        n_locations = len(self.store.locations)
        return [("store", n_locations > 0,
                 f"{n_locations} mounted location(s)"),
                ("master", bool(self.master_address),
                 f"master={self.master_address or 'unknown'}"),
                ("draining", not self.draining,
                 "draining" if self.draining else "serving"),
                healthz.gate_check(self.qos_gate)]

    def _on_demote(self, vid: int):
        events_mod.emit(events_mod.READONLY_DEMOTION, service="volume",
                        node=self.address, detail={"volume": vid})
        self._try_heartbeat()

    def _h_ui(self, req: Request):
        """Status page (server/volume_server_ui/volume.html)."""
        from ..util import ui

        rows = []
        ec_rows = []
        for loc in self.store.locations:
            with loc.lock:
                for vid, v in sorted(loc.volumes.items()):
                    dat_size, _ = v.file_stat()
                    rows.append((
                        vid, v.collection or "(default)", dat_size,
                        v.file_count(), v.deleted_count(),
                        str(v.super_block.replica_placement),
                        "readonly" if v.read_only else "writable"))
                for vid, ev in sorted(loc.ec_volumes.items()):
                    ec_rows.append((vid, ev.collection or "(default)",
                                    sorted(ev.shard_bits().shard_ids())))
        body = ui.page(
            f"SeaweedFS-TPU Volume Server {self.address}",
            ui.section("Server", ui.kv_table({
                "master": self.master_address,
                "directories": ", ".join(
                    loc.directory for loc in self.store.locations),
                "data center": self.store.data_center or "-",
                "rack": self.store.rack or "-",
                "tcp fast path": getattr(self, "tcp_port", 0) or "off",
            })),
            ui.section("Volumes", ui.table(
                ("id", "collection", "size", "files", "deleted",
                 "replication", "mode"), rows)),
            ui.section("EC shards", ui.table(
                ("volume", "collection", "shards"), ec_rows)),
        )
        return Response(body, content_type="text/html; charset=utf-8")

    def _h_configure_replication(self, req: Request):
        """VolumeConfigure (volume server side of
        command_volume_configure_replication.go): rewrite the
        replica-placement byte in the superblock on disk."""
        from ..storage.super_block import ReplicaPlacement

        p = req.json()
        v = self._volume_or_404(int(p["volume"]))
        rp = ReplicaPlacement.parse(p.get("replication", "000"))
        with v.lock:
            v.super_block.replica_placement = rp
            v.data.write_at(v.super_block.to_bytes(), 0)
            v.data.sync()
        self._try_heartbeat()
        return {"volume": v.id, "replication": str(rp)}

    def _h_tier_upload(self, req: Request):
        """VolumeTierMoveDatToRemote (volume_grpc_tier_upload.go): ship
        the .dat to a configured tier backend; volume turns readonly."""
        from ..storage import tier

        p = req.json()
        v = self._volume_or_404(int(p["volume"]))
        try:
            remote = tier.tier_upload(
                v, p["backend"], p.get("bucket", "volumes"),
                keep_local=bool(p.get("keep_local")))
        except ValueError as e:
            raise RpcError(str(e), 400)
        self._try_heartbeat()
        return {"volume": v.id, "key": remote.key,
                "size": remote.file_size}

    def _h_remote_fetch_write(self, req: Request):
        """FetchAndWriteNeedle (volume_grpc_remote.go:16-83): pull a
        remote object's byte range from the external store DIRECTLY into
        a local needle, so remote.cache of large objects never
        round-trips the bytes through the filer process.  Fans out to
        the volume's replicas like a normal write."""
        from ..remote_storage import (RemoteConf, RemoteLocation,
                                      make_remote_client)

        p = req.json()
        vid = int(p["volume"])
        nid = int(p["needle_id"])
        cookie = int(p["cookie"])
        self._volume_or_404(vid)
        client = make_remote_client(RemoteConf.from_dict(p["remote_conf"]))
        loc = RemoteLocation.parse(p["remote_location"])
        offset = int(p.get("offset", 0))
        size = int(p.get("size", -1))
        data = client.read_range(loc, offset, size) if size >= 0 \
            else client.read_file(loc)
        n = Needle.create(data)
        n.id, n.cookie = nid, cookie
        self.store.write_needle(vid, n)
        fid = f"{vid},{nid:x}{cookie:08x}"
        self._replicate(vid, fid, "POST", data,
                        {"Content-Type": "application/octet-stream"})
        return {"size": len(data), "eTag": n.etag()}

    def _h_tier_download(self, req: Request):
        """VolumeTierMoveDatFromRemote (volume_grpc_tier_download.go)."""
        from ..storage import tier

        v = self._volume_or_404(int(req.json()["volume"]))
        try:
            size = tier.tier_download(v)
        except ValueError as e:
            raise RpcError(str(e), 400)
        self._try_heartbeat()
        return {"volume": v.id, "size": size}

    def _h_drain(self, req: Request):
        """Graceful-drain step 1 (scale.drain): demote every local
        volume to read-only and flag the node as draining so assigns
        stop landing here while the curator paces the evacuation.
        ``{"draining": false}`` undoes an aborted drain."""
        p = req.json()
        draining = bool(p.get("draining", True))
        self.draining = draining
        demoted = []
        for loc in self.store.locations:
            with loc.lock:
                vids = list(loc.volumes)
            for vid in vids:
                try:
                    self.store.mark_volume_readonly(vid, draining)
                    demoted.append(vid)
                except NotFoundError:
                    pass  # deleted between listing and demotion
        stats.VolumeServerDrainingGauge.set(1.0 if draining else 0.0)
        events_mod.emit(events_mod.DRAIN, service="volume",
                        node=self.address,
                        detail={"draining": draining,
                                "demoted": len(demoted)})
        self._try_heartbeat()  # master must see read_only NOW
        return {"draining": draining, "volumes": sorted(demoted)}

    def _h_leave(self, req: Request):
        """VolumeServerLeave (volume_grpc_admin.go): stop heartbeating and
        unregister from the master so assigns stop landing here; the
        process keeps serving reads until stopped."""
        self._stop.set()  # ends the heartbeat loop only; server threads
        # are owned by RpcServer and keep running
        try:
            call(self.master_address, "/dir/leave",
                 {"ip": self.store.ip, "port": self.store.port}, timeout=5)
        except RpcError:
            pass  # master reaps on missed pulses anyway
        return {}

    # -- structured query (volume_grpc_query.go Query) -----------------------
    def _h_query(self, req: Request):
        """SELECT over JSON-lines/CSV needle content: body carries
        from_file_ids, filter {field, operand, value}, selections, and
        input_serialization (volume_server.proto QueryRequest)."""
        from ..query import Query, query_csv, query_json_lines

        spec = req.json()
        filt = spec.get("filter") or {}
        query = Query(field=filt.get("field", ""),
                      op=filt.get("operand", ""),
                      value=str(filt.get("value", "")))
        selections = spec.get("selections") or []
        input_ser = spec.get("input_serialization") or {"json": {}}
        records = []
        for fid in spec.get("from_file_ids", []):
            try:
                vid, nid, cookie = t.parse_file_id(fid)
            except ValueError as e:
                raise RpcError(f"bad fid {fid}: {e}", 400)
            try:
                n = self.store.read_needle(vid, nid, cookie=cookie)
            except (NotFoundError, EcNotFoundError, DeletedError,
                    EcDeletedError, CookieMismatchError):
                raise RpcError(f"{fid} not found", 404)
            if "csv" in input_ser:
                records.extend(query_csv(
                    n.data, selections, query,
                    input_ser["csv"].get("file_header_info", "USE")))
            else:
                records.extend(query_json_lines(n.data, selections, query))
        return {"records": records}

    # -- public object API ---------------------------------------------------
    def _handle_object(self, method: str, req: Request):
        if qos.enabled():
            # class/tenant installed by the dispatch loop from the
            # X-QoS-* headers; unclassified reads count as interactive
            # so foreground GETs outrank queued background work
            cls = qos.current_class()
            if qos.QOS_HEADER not in req.headers \
                    and method in ("GET", "HEAD"):
                cls = qos.INTERACTIVE
            try:
                release = self.qos_gate.admit(cls)
            except RpcError:
                stats.VolumeServerThrottleRejects.labels("inflight").inc()
                raise
            try:
                return self._handle_object_accounted(method, req)
            finally:
                release()
        if not self.request_shedder.try_acquire():
            stats.VolumeServerThrottleRejects.labels("inflight").inc()
            raise RpcError(
                "too many requests: inflight limit", 503,
                headers={"Retry-After": qos.retry_after(1, 3)})
        try:
            return self._handle_object_accounted(method, req)
        finally:
            self.request_shedder.release()

    def _handle_object_accounted(self, method: str, req: Request):
        out = self._handle_object_inner(method, req)
        body = getattr(out, "body", out)
        if isinstance(body, (bytes, bytearray)):
            n = len(body)
        elif isinstance(body, FileSlice):
            n = body.length
        else:
            n = 0
        if method in ("POST", "PUT"):
            n += len(req.body or b"")
        with self._tele_lock:
            key = "write" if method in ("POST", "PUT") else "read"
            self._req_counts[key] += 1
            self._req_counts["bytes"] += n
            sample = (self._req_counts["read"]
                      + self._req_counts["write"]) % 8 == 0
        if sample:
            # a heartbeat-instant occupancy read misses bursts entirely
            # (the gate is usually idle at the sampling moment); peak
            # occupancy observed from INSIDE requests — while this one
            # still holds its admission — is the congestion signal
            occ = self.qos_gate.occupancy()
            if occ > self._occ_peak:
                self._occ_peak = occ
        return out

    def _telemetry(self) -> dict:
        """Per-heartbeat load sample for the curator's autoscale
        detectors: admission-gate occupancy plus rps / byte-rate over
        the window since the previous heartbeat."""
        now = time.monotonic()
        with self._tele_lock:
            reads = self._req_counts["read"]
            writes = self._req_counts["write"]
            nbytes = self._req_counts["bytes"]
            t0, rw0, _, b0 = self._tele_prev
            self._tele_prev = (now, reads + writes, 0, nbytes)
            peak, self._occ_peak = self._occ_peak, 0.0
        dt = max(1e-6, now - t0)
        return {"occupancy": round(
                    max(peak, self.qos_gate.occupancy()), 4),
                "rps": round((reads + writes - rw0) / dt, 2),
                "mbps": round((nbytes - b0) / dt / float(1 << 20), 3),
                "draining": self.draining}

    def _handle_object_inner(self, method: str, req: Request):
        fid = req.path.lstrip("/").replace("/", ",", 1)
        if not fid or "," not in fid:
            raise RpcError(f"invalid fid path {req.path!r}", 400)
        try:
            vid, nid, cookie = t.parse_file_id(fid)
        except ValueError as e:
            raise RpcError(str(e), 400)
        if method in ("GET", "HEAD"):
            if self.guard.read_signing:
                try:
                    self.guard.verify_read(
                        token_from_request(req.headers, req.query), fid)
                except PermissionError as e:
                    raise RpcError(str(e), 401)
            stats.VolumeServerRequestCounter.labels("read").inc()
            t0 = time.monotonic()
            nbytes = 0
            try:
                with stats.VolumeServerRequestHistogram.labels(
                        "read").time():
                    with tracing.span("needle.read", tags={"fid": fid}):
                        resp = self._read_object(
                            vid, nid, cookie, method, req, fid)
                nbytes = _resp_len(resp)
                return resp
            finally:
                self._record_access("read", vid, fid, nbytes,
                                    time.monotonic() - t0)
        if method in ("POST", "PUT"):
            # JWT check before any byte is written
            # (volume_server_handlers_write.go:30-38)
            self._check_write_auth(req, fid)
            stats.VolumeServerRequestCounter.labels("write").inc()
            n_bytes = len(req.body)
            if not self.upload_gate.acquire(n_bytes):
                stats.VolumeServerThrottleRejects.labels("upload").inc()
                raise RpcError("too many requests: upload limit", 429)
            t0 = time.monotonic()
            try:
                with stats.VolumeServerRequestHistogram.labels(
                        "write").time():
                    with tracing.span(
                            "needle.write",
                            tags={"fid": fid, "bytes": n_bytes}):
                        return self._write_object(vid, nid, cookie, req)
            finally:
                self.upload_gate.release(n_bytes)
                self._record_access("write", vid, fid, n_bytes,
                                    time.monotonic() - t0)
        if method == "DELETE":
            self._check_write_auth(req, fid)
            stats.VolumeServerRequestCounter.labels("delete").inc()
            with tracing.span("needle.delete", tags={"fid": fid}):
                resp = self._delete_object(vid, nid, cookie, req)
            self._record_access("delete", vid, fid, 0, 0.0)
            return resp
        raise RpcError(f"unsupported method {method}", 405)

    def _record_access(self, op: str, vid: int, fid: str, nbytes: int,
                       latency_s: float):
        """Feed the workload analytics sketches (stats/access.py); the
        QoS class/tenant were set from the request headers by dispatch,
        so gateway-attributed tenants flow through unchanged."""
        v = self.store.find_volume(vid)
        coll = v.collection if v is not None else ""
        if not coll:
            ev = self.store.find_ec_volume(vid)
            coll = getattr(ev, "collection", "") if ev is not None else ""
        self.access_recorder.record(
            op, collection=coll, tenant=qos.current_tenant(),
            volume=vid, fid=fid, nbytes=nbytes,
            latency_s=latency_s, qos_class=qos.current_class())

    def _check_write_auth(self, req: Request, fid: str):
        try:
            self.guard.verify_write(
                token_from_request(req.headers, req.query), fid)
        except PermissionError as e:
            raise RpcError(str(e), 401)

    def _read_object(self, vid: int, nid: int, cookie: int, method: str,
                     req: Request, fid: str):
        v = self.store.find_volume(vid)
        if v is None and self.store.find_ec_volume(vid) is None:
            # volume not local: readMode local|proxy|redirect
            # (volume_server_handlers_read.go:30-70)
            return self._read_nonlocal(vid, method, req, fid)
        self._refresh_worker_view(v)
        n = self._cached_needle(v, vid, nid, cookie)
        if n is None:
            resp = self._sendfile_read(v, vid, nid, cookie, method, req)
            if resp is not None:
                return resp
        if n is None:
            nv_before = v.nm.get(nid) if v is not None else None
            try:
                n = self.store.read_needle(vid, nid, cookie=cookie)
            except (NotFoundError, EcNotFoundError):
                n = self._retry_after_idx_refresh(v, vid, nid, cookie)
                if n is None:
                    raise RpcError("not found", 404)
            except (DeletedError, EcDeletedError):
                raise RpcError("already deleted", 404)
            except (CookieMismatchError,) as e:
                raise RpcError(str(e), 404)
            self._fill_needle_cache(v, vid, nid, n, nv_before)
        if not self.download_gate.acquire(len(n.data)):
            stats.VolumeServerThrottleRejects.labels("download").inc()
            raise RpcError("too many requests: download limit", 429)
        try:
            return self._build_read_response(n, method, req)
        finally:
            self.download_gate.release(len(n.data))

    def _refresh_worker_view(self, v):
        """Prefork worker: tail the .idx BEFORE resolving a read, not
        only on a local miss — deletes and overwrites the parent
        applied after the fork still RESOLVE in this worker's stale
        snapshot (to the old offset/size), so a miss-only refresh would
        serve deleted or superseded bytes indefinitely
        (DELETE-then-GET returning 200 with the old data).  The
        no-news case is one fstat: refresh_from_idx compares the .idx
        size against the consumed tail and returns without reading."""
        if v is None or not _prefork.is_worker():
            return
        refresh = getattr(v.nm, "refresh_from_idx", None)
        if refresh is None:
            return  # native map: the HTTP-layer parent retry covers it
        with v.lock:
            try:
                refresh()
            except OSError:
                pass  # racing a vacuum's .idx swap: serve the snapshot

    def _retry_after_idx_refresh(self, v, vid: int, nid: int,
                                 cookie: int):
        """Prefork worker: a needle-map miss may be a needle the parent
        wrote after our fork.  Tail the (flush-per-append) .idx and
        retry once before 404ing; the HTTP layer additionally retries
        residual misses against the parent process."""
        if not _prefork.is_worker() or v is None:
            return None
        refresh = getattr(v.nm, "refresh_from_idx", None)
        if refresh is None:
            return None  # native map: the HTTP-layer retry covers it
        with v.lock:
            applied = refresh()
        if not applied:
            return None
        try:
            return self.store.read_needle(vid, nid, cookie=cookie)
        except (VolumeError, EcNotFoundError, EcDeletedError):
            return None

    def _sendfile_read(self, v, vid: int, nid: int, cookie: int,
                       method: str, req: Request):
        """Zero-copy GET: a big uncompressed needle goes straight from
        the .dat to the client socket via sendfile — the payload never
        enters Python.  Small needles (below WEED_SENDFILE_MIN) keep the
        buffered path so they still populate the RAM needle cache, which
        is faster for them than a syscall round trip.  Returns None to
        fall back to the buffered path (which also owns all error
        reporting: any storage error here falls through to it)."""
        if v is None or not sendfile_enabled():
            return None
        try:
            min_size = int(
                os.environ.get("WEED_SENDFILE_MIN", "") or 65536)
        except ValueError:
            min_size = 65536
        try:
            sliced = v.read_needle_slice(nid, cookie, min_size=min_size)
        except VolumeError:
            return None
        if sliced is None:
            return None
        n, data_off, data_len, fd = sliced
        fd_owned = True  # until closed here or handed to a FileSlice
        try:
            headers = {"Etag": f'"{n.etag()}"', "Accept-Ranges": "bytes"}
            if n.has_name:
                headers["X-File-Name"] = n.name.decode(errors="replace")
            if n.last_modified:
                headers["X-Last-Modified"] = str(n.last_modified)
            content_type = (n.mime.decode(errors="replace") if n.has_mime
                            else "application/octet-stream")
            status = 200
            offset, length = data_off, data_len
            range_header = req.headers.get("Range")
            if range_header:
                r = _parse_range(range_header, data_len)
                if r is None:
                    fd_owned = False
                    os.close(fd)
                    return Response(
                        b"", 416, content_type,
                        {"Content-Range": f"bytes */{data_len}"})
                if r is not ...:  # a single satisfiable range
                    start, end = r
                    headers["Content-Range"] = (
                        f"bytes {start}-{end - 1}/{data_len}")
                    offset, length = data_off + start, end - start
                    status = 206
            if not self.download_gate.acquire(length):
                fd_owned = False
                os.close(fd)
                stats.VolumeServerThrottleRejects.labels("download").inc()
                raise RpcError("too many requests: download limit", 429)
            gate = self.download_gate
            try:
                if method == "HEAD":
                    fd_owned = False
                    os.close(fd)
                    gate.release(length)
                    headers["Content-Length"] = str(length)
                    return Response(b"", status, content_type, headers)
                # the gate must be held for the TRANSFER's lifetime:
                # the bytes move in _reply_file AFTER this handler
                # returns, and -concurrentDownloadLimitMB exists to
                # bound in-flight bytes for exactly these large reads —
                # FileSlice.close() (the reply path's finally) releases
                body = FileSlice(fd, offset, length, close_fd=True,
                                 on_close=lambda: gate.release(length))
                fd_owned = False  # the reply path closes it
                return Response(body, status, content_type, headers)
            except BaseException:
                gate.release(length)
                raise
        except BaseException:
            if fd_owned:
                os.close(fd)
            raise

    def _cached_needle(self, v, vid: int, nid: int, cookie: int):
        """Serve a needle read out of the unified read cache when the
        live needle map still agrees with the cached (offset, size) —
        overwrites, deletes and vacuum offset shifts all change the
        map, so a stale entry self-invalidates even for writes that
        arrive on the native TCP path (defense in depth on top of the
        explicit invalidation hooks)."""
        if v is None or v.ttl:  # EC reads and TTL expiry go to the store
            return None
        key = f"{vid},{nid:x}"
        cached = self.read_cache.get(key)
        if cached is None:
            return None
        n, off, size = cached
        nv = v.nm.get(nid)
        if nv is None or nv.offset != off or nv.size != size:
            self.read_cache.invalidate(key, reason="stale")
            return None
        if cookie is not None and n.cookie != cookie:
            raise RpcError(f"cookie mismatch for needle {nid:x}", 404)
        return n

    def _fill_needle_cache(self, v, vid: int, nid: int, n: Needle,
                           nv_before):
        """Admit a freshly-read needle, pinned to the (offset, size) it
        was read at; a concurrent overwrite between the read and this
        fill shows up as a map probe mismatch and skips the fill."""
        if v is None or v.ttl:
            return
        nv = v.nm.get(nid)
        if nv is None or nv_before is None or \
                nv.offset != nv_before.offset or nv.size != nv_before.size:
            return
        self.read_cache.put(f"{vid},{nid:x}", (n, nv.offset, nv.size),
                            nbytes=len(n.data))

    def _build_read_response(self, n: Needle, method: str, req: Request):
        headers = {"Etag": f'"{n.etag()}"', "Accept-Ranges": "bytes"}
        if n.has_name:
            headers["X-File-Name"] = n.name.decode(errors="replace")
        if n.last_modified:
            headers["X-Last-Modified"] = str(n.last_modified)
        content_type = (n.mime.decode(errors="replace") if n.has_mime
                        else "application/octet-stream")

        data = n.data
        range_header = req.headers.get("Range")
        if n.is_compressed:
            accepts_gzip = "gzip" in (
                req.headers.get("Accept-Encoding") or "")
            if accepts_gzip and not range_header:
                # pass the stored gzip bytes through untouched
                # (volume_server_handlers_read.go:180-199 semantics)
                headers["Content-Encoding"] = "gzip"
            else:
                import gzip as _gzip

                data = _gzip.decompress(data)
        status = 200
        if range_header and "Content-Encoding" not in headers:
            sliced = _parse_range(range_header, len(data))
            if sliced is None:
                return Response(
                    b"", 416, content_type,
                    {"Content-Range": f"bytes */{len(data)}"})
            if sliced is not ...:  # a single satisfiable range
                start, end = sliced
                headers["Content-Range"] = (
                    f"bytes {start}-{end - 1}/{len(data)}")
                # zero-copy slice: the socket writes the view straight
                # out of the (possibly cached) needle bytes
                data = memoryview(data)[start:end]
                status = 206
        if method == "HEAD":
            # entity size, not body size (the handler sends no body)
            headers["Content-Length"] = str(len(data))
            return Response(b"", status, content_type, headers)
        return Response(data, status, content_type, headers)

    def _read_nonlocal(self, vid: int, method: str, req: Request,
                       fid: str):
        """Non-local read: 404 (local), 302 to a holder (redirect), or
        fetch-and-relay (proxy) — volume_server_handlers_read.go:30,303."""
        if self.read_mode == "local":
            raise RpcError(f"volume {vid} not found locally "
                           "(readMode=local)", 404)
        if req.headers.get("X-SW-Proxied"):
            # already one proxy hop away: never proxy a proxy (stale
            # master lookups could otherwise ping-pong two non-holders
            # until threads exhaust)
            raise RpcError(f"volume {vid} not found at proxy target", 404)
        try:
            lookup = policy.call_policy(
                self.master_address, f"/dir/lookup?volumeId={vid}",
                timeout=10)
        except RpcError:
            lookup = {}
        others = [loc for loc in lookup.get("locations", [])
                  if loc["url"] != self.store.url]
        if not others:
            raise RpcError(f"volume {vid} has no other locations", 404)
        target = others[0]
        stats.VolumeServerProxiedReadCounter.labels(self.read_mode).inc()
        if self.read_mode == "redirect":
            public = target.get("publicUrl") or target["url"]
            return Response(b"", 302, headers={
                "Location": f"http://{public}/{fid}"})
        # proxy: forward the read (with range/encoding negotiation) and
        # relay status + entity headers
        import urllib.error
        import urllib.request

        fwd = urllib.request.Request(
            f"http://{target['url']}/{fid}", method=method)
        fwd.add_header("X-SW-Proxied", "1")
        for h in ("Range", "Accept-Encoding", "Authorization"):
            if req.headers.get(h):
                fwd.add_header(h, req.headers[h])
        try:
            with urllib.request.urlopen(fwd, timeout=30) as resp:
                body = resp.read()
                relay = {k: v for k, v in resp.headers.items()
                         if k in ("Etag", "Content-Range",
                                  "Content-Encoding", "X-File-Name",
                                  "X-Last-Modified", "Accept-Ranges")}
                return Response(
                    body, resp.status,
                    resp.headers.get("Content-Type",
                                     "application/octet-stream"), relay)
        except urllib.error.HTTPError as e:
            raise RpcError(f"proxied read failed: {e}", e.code)
        except OSError as e:
            raise RpcError(f"proxied read failed: {e}", 502)

    def _write_object(self, vid: int, nid: int, cookie: int, req: Request):
        is_replicate = req.param("type") == "replicate"
        name = (req.headers.get("X-File-Name") or "").encode()
        mime = (req.headers.get("Content-Type") or "").encode()
        body = req.body
        is_compressed = (req.headers.get("Content-Encoding") or "") == "gzip"
        if not is_compressed and _is_gzippable(name, mime) \
                and len(body) > 128:
            # store-side gzip when it pays (CreateNeedleFromRequest,
            # needle.go:100; util.MaybeGzipData).  mtime=0 keeps the
            # bytes deterministic so replicas dedup identically.
            import gzip as _gzip

            packed = _gzip.compress(body, 6, mtime=0)
            if len(packed) < len(body) * 9 // 10:
                body = packed
                is_compressed = True
        n = Needle.create(
            body,
            name=name,
            mime=mime,
            last_modified=int(time.time()),
            is_compressed=is_compressed,
        )
        n.id, n.cookie = nid, cookie
        try:
            size, unchanged = self.store.write_needle(vid, n)
        except NotFoundError:
            raise RpcError(f"volume {vid} not found", 404)
        except CookieMismatchError as e:
            raise RpcError(str(e), 403)
        except VolumeError as e:
            raise RpcError(str(e), 500)
        self.read_cache.invalidate(f"{vid},{nid:x}", reason="overwrite")
        if not is_replicate:
            self._replicate(vid, f"{vid},{nid:x}{cookie:08x}", "POST",
                            req.body, dict(req.headers.items()))
        return {"name": (n.name or b"").decode(errors="replace"),
                "size": size, "eTag": n.etag()}

    def _delete_object(self, vid: int, nid: int, cookie: int, req: Request):
        is_replicate = req.param("type") == "replicate"
        n = Needle(id=nid, cookie=cookie)
        try:
            size = self.store.delete_needle(vid, n)
        except NotFoundError:
            raise RpcError(f"volume {vid} not found", 404)
        self.read_cache.invalidate(f"{vid},{nid:x}", reason="delete")
        if not is_replicate:
            self._replicate(vid, f"{vid},{nid:x}{cookie:08x}", "DELETE",
                            None, {})
        return {"size": size}

    def _replicate(self, vid: int, fid: str, method: str,
                   body: Optional[bytes], headers: dict):
        """Fan out to the other replicas (store_replicate.go:24-114);
        any replica failure fails the request, as in the reference."""
        try:
            lookup = policy.call_policy(
                self.master_address, f"/dir/lookup?volumeId={vid}",
                timeout=10)
        except RpcError:
            return  # master unreachable: single-copy write stands
        others = [loc["url"] for loc in lookup.get("locations", [])
                  if loc["url"] != self.store.url]
        # wire headers arrive with arbitrary capitalisation; match them
        # case-insensitively or replicas silently lose mime/filename
        lowered = {k.lower(): v for k, v in headers.items()}
        headers = {canonical: lowered[canonical.lower()]
                   for canonical in ("Content-Type", "X-File-Name",
                                     "Content-Encoding")
                   if canonical.lower() in lowered}
        if self.guard.signing:
            # replicas share security.toml; re-sign for the fan-out hop
            headers["Authorization"] = "BEARER " + gen_write_jwt(
                self.guard.signing, fid)
        if not others:
            return
        with tracing.span("needle.replicate",
                          tags={"fid": fid, "replicas": len(others)}), \
                qos.qos_scope(qos.BACKGROUND):
            # replication fan-out is auto-tagged background: replicas
            # admit it behind their own foreground traffic
            for url in others:
                # breaker-guarded, retried fan-out: type=replicate is
                # idempotent (unchanged-content writes dedup), so a
                # flaky replica gets jittered retries and a dead one
                # fails fast once its breaker opens
                policy.call_policy(
                    url, f"/{fid}?type=replicate", method=method,
                    raw=body, headers=headers, timeout=30,
                    idempotent=True)

    # -- admin ---------------------------------------------------------------
    def _h_assign_volume(self, req: Request):
        p = req.json()
        self.store.add_volume(int(p["volume"]), p.get("collection", ""),
                              p.get("replication", "000"),
                              p.get("ttl", ""))
        self._try_heartbeat()
        return {}

    def _h_delete_volume(self, req: Request):
        vid = int(req.json()["volume"])
        # share the copy lock: a delete landing between a copy's mount and
        # its status read must not turn the completed copy into a 500
        with self._vid_copy_lock(vid):
            self.store.delete_volume(vid)
        self._try_heartbeat()
        return {}

    def _h_readonly(self, req: Request):
        p = req.json()
        self.store.mark_volume_readonly(int(p["volume"]),
                                        bool(p.get("readonly", True)))
        return {}

    def _volume_or_404(self, vid: int):
        v = self.store.find_volume(vid)
        if v is None:
            raise RpcError(f"volume {vid} not found", 404)
        return v

    def _h_vacuum_check(self, req: Request):
        v = self._volume_or_404(int(req.json()["volume"]))
        return {"garbage_ratio": v.garbage_level()}

    def _h_vacuum_compact(self, req: Request):
        self._volume_or_404(int(req.json()["volume"])).compact()
        return {}

    def _h_vacuum_commit(self, req: Request):
        vid = int(req.json()["volume"])
        self._volume_or_404(vid).commit_compact()
        # compaction shifts needle offsets: cached (offset, size) pins
        # are stale en masse, drop the whole volume's entries
        self.read_cache.invalidate_volume(vid, reason="vacuum")
        return {}

    # -- volume copy/tail/backup (volume_grpc_copy.go, _tail.go, backup) -----
    def _h_volume_mount(self, req: Request):
        """VolumeMount: load an existing on-disk volume into the store."""
        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        for loc in self.store.locations:
            if os.path.exists(loc._base_name(collection, vid) + ".dat"):
                loc.add_volume(vid, collection)
                self._try_heartbeat()
                return {}
        raise RpcError(f"volume {vid} data file not found", 404)

    def _h_volume_unmount(self, req: Request):
        """VolumeUnmount: close + forget the volume, leave files on disk."""
        vid = int(req.json()["volume"])
        with self._vid_copy_lock(vid):
            loc = self.store.location_of(vid)
            if loc is None:
                raise RpcError(f"volume {vid} not found", 404)
            loc.unload_volume(vid)
        self._try_heartbeat()
        return {}

    def _h_volume_copy(self, req: Request):
        """VolumeCopy: pull .dat/.idx/.vif from a source server and mount
        (volume_grpc_copy.go doCopyFile over the CopyFile stream)."""
        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        source = p["source"]
        # serialize copies of this vid: two concurrent requests for the
        # same vid must not both pass the exists-checks (TOCTOU) and then
        # have one's rollback unlink the other's freshly-mounted files
        with self._vid_copy_lock(vid):
            if self.store.has_volume(vid):
                raise RpcError(f"volume {vid} already exists", 409)
            loc = self.store.locations[0]
            base = loc._base_name(collection, vid)
            if os.path.exists(base + ".dat"):
                raise RpcError(f"volume {vid} files already on disk", 409)
            # fetch to temp names; rename only once every file arrived, so
            # a mid-copy failure leaves no stray .dat/.idx behind.  .idx
            # first: writes that land between the two fetches then only
            # extend the .dat, and the integrity check truncates that
            # unreferenced tail on mount — the reverse order would leave
            # the .idx pointing past the copied .dat's EOF
            fetched: list[str] = []
            try:
                for ext in (".idx", ".dat", ".vif"):
                    try:
                        chunks = call_stream(
                            source,
                            f"/admin/ec/shard_file?volume={vid}"
                            f"&collection={collection}&ext={ext}",
                            timeout=600)
                    except RpcError as e:
                        if e.status == 404 and ext == ".vif":
                            continue
                        raise
                    with open(base + ext + ".cpy", "wb") as f:
                        for chunk in chunks:
                            f.write(chunk)
                    fetched.append(ext)
            except Exception:
                # RpcError before the first byte OR a mid-stream error
                _remove_quiet(*(base + ext + ".cpy"
                                for ext in (".idx", ".dat", ".vif")))
                raise
            for ext in fetched:
                os.replace(base + ext + ".cpy", base + ext)
            try:
                loc.add_volume(vid, collection)
            except Exception:
                # keep all-or-nothing: an unloadable copy (corrupt
                # source) must not squat on the volume id's file names —
                # but never touch files backing a volume that IS mounted
                if self.store.find_volume(vid) is None:
                    _remove_quiet(*(base + ext for ext in fetched))
                raise
            # read the cursor inside the lock: a concurrent delete after
            # release must not turn a completed copy into a 500
            last_ns = self.store.find_volume(vid).last_append_at_ns
        self._try_heartbeat()
        return {"last_append_at_ns": last_ns}

    def _vid_copy_lock(self, vid: int) -> threading.Lock:
        with self._copy_locks_mu:
            return self._copy_locks.setdefault(vid, threading.Lock())

    def _h_volume_status(self, req: Request):
        """VolumeStatus + ReadVolumeFileStatus."""
        v = self._volume_or_404(int(req.param("volume", "0")))
        with v.lock:
            v.nm.flush()
        return {
            "volume": v.id,
            "last_append_at_ns": v.last_append_at_ns,
            "compaction_revision": v.super_block.compaction_revision,
            "dat_size": v.data.size(),
            "idx_size": v.index_file_size(),
            "file_count": v.file_count(),
            "read_only": v.read_only,
        }

    def _h_volume_tail(self, req: Request):
        """VolumeTailSender: raw needle records appended after since_ns,
        streamed (volume_grpc_tail.go sends 64 KB frames); the resume
        cursor rides a header computed from a header-only walk before the
        body starts."""
        v = self._volume_or_404(int(req.param("volume", "0")))
        since_ns = int(req.param("since_ns", "0"))
        limit = int(req.param("limit", str(64 << 20)))
        chunks, length, last_ns = volume_backup.iter_appended_bytes(
            v, since_ns, limit)
        return Response(chunks, headers={
            "X-Last-Append-At-Ns": str(last_ns),
            "Content-Length": str(length)})

    def _h_volume_sync(self, req: Request):
        """VolumeIncrementalCopy client side: catch this replica up from a
        source replica (volume_backup.go IncrementalBackup)."""
        p = req.json()
        v = self._volume_or_404(int(p["volume"]))
        source = p["source"]

        def fetch(since_ns: int) -> bytes:
            data = call(source,
                        f"/admin/volume/tail?volume={v.id}"
                        f"&since_ns={since_ns}", timeout=600)
            return data if isinstance(data, (bytes, bytearray)) else b""

        applied = volume_backup.incremental_backup(v, fetch)
        return {"applied": applied,
                "last_append_at_ns": v.last_append_at_ns}

    def _h_volume_read_all(self, req: Request):
        """ReadAllNeedles: stream every live needle's metadata as NDJSON
        (volume_grpc_read_all.go; drives volume.fsck).  Chunked transfer:
        a billion-needle volume streams without server-side buffering."""
        v = self._volume_or_404(int(req.param("volume", "0")))
        include_deleted = req.param("deleted") == "true"

        def gen():
            batch: list[str] = []
            for n, offset in v.scan():
                if not include_deleted and not n.data and n.size == 0:
                    continue
                batch.append(json.dumps({
                    "id": n.id, "cookie": n.cookie, "size": len(n.data),
                    "offset": offset, "crc": n.checksum,
                    "append_at_ns": n.append_at_ns}))
                if len(batch) >= 512:
                    yield ("\n".join(batch) + "\n").encode()
                    batch.clear()
            if batch:
                yield ("\n".join(batch) + "\n").encode()

        return Response(gen(), content_type="application/x-ndjson")

    def _h_batch_delete(self, req: Request):
        """BatchDelete (volume_grpc_batch_delete.go): many fids, one call.
        On a jwt-secured cluster each fid needs write authorization."""
        fids = req.json().get("fids", [])
        token = token_from_request(req.headers, req.query)
        results = []
        for fid in fids:
            try:
                self.guard.verify_write(token, fid)
            except PermissionError as e:
                results.append({"fid": fid, "status": 401, "error": str(e)})
                continue
            try:
                vid, nid, cookie = t.parse_file_id(fid)
            except ValueError as e:
                results.append({"fid": fid, "status": 400, "error": str(e)})
                continue
            try:
                size = self.store.delete_needle(
                    vid, Needle(id=nid, cookie=cookie))
                self.read_cache.invalidate(f"{vid},{nid:x}",
                                           reason="delete")
                results.append({"fid": fid, "status": 200, "size": size})
            except NotFoundError:
                results.append({"fid": fid, "status": 404,
                                "error": "volume not found"})
            except VolumeError as e:
                results.append({"fid": fid, "status": 500, "error": str(e)})
        return {"results": results}

    # -- EC handlers (volume_grpc_erasure_coding.go) -------------------------
    def _h_ec_generate(self, req: Request):
        p = req.json()
        self.store.ec_generate(int(p["volume"]),
                               code_family=p.get("code_family") or None)
        return {}

    def _h_ec_rebuild(self, req: Request):
        p = req.json()
        vid = int(p["volume"])
        rebuilt = self.store.ec_rebuild(vid, p.get("collection", ""))
        self.read_cache.invalidate_volume(vid, reason="rebuild")
        return {"rebuilt_shard_ids": rebuilt}

    def _h_ec_mount(self, req: Request):
        p = req.json()
        vid = int(p["volume"])
        self.store.ec_mount(p.get("collection", ""), vid,
                            [int(s) for s in p["shard_ids"]])
        ev = self.store.find_ec_volume(vid)
        if ev is not None and ev.remote_reader is None:
            ev.remote_reader = self._make_remote_reader(vid)
        self._try_heartbeat()
        return {}

    def _h_ec_unmount(self, req: Request):
        p = req.json()
        self.store.ec_unmount(int(p["volume"]),
                              [int(s) for s in p["shard_ids"]])
        self._try_heartbeat()
        return {}

    def _h_ec_copy(self, req: Request):
        """VolumeEcShardsCopy: pull shard files from a source server."""
        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        source = p["source"]
        loc = self.store.locations[0]
        base = loc._base_name(collection, vid)
        exts = [to_ext(int(s)) for s in p.get("shard_ids", [])]
        if p.get("copy_ecx_file", True):
            exts += [".ecx", ".ecj", ".vif"]
        # same per-vid serialization as volume copy: a failing request's
        # rollback must not unlink a concurrent request's temp files
        with self._vid_copy_lock(vid):
            # stream to temp names, rename when complete: a mid-transfer
            # failure must never leave a truncated shard mounted later
            fetched: list[str] = []
            try:
                for ext in exts:
                    try:
                        chunks = call_stream(
                            source,
                            f"/admin/ec/shard_file?volume={vid}"
                            f"&collection={collection}&ext={ext}",
                            timeout=600)
                    except RpcError as e:
                        if e.status == 404 and ext in (".ecj", ".vif"):
                            continue  # optional sidecars
                        raise
                    with open(base + ext + ".cpy", "wb") as f:
                        for chunk in chunks:
                            f.write(chunk)
                    fetched.append(ext)
            except Exception:
                # RpcError before the first byte OR a mid-stream socket
                # error: remove every temp incl. the partial in-progress
                _remove_quiet(*(base + ext + ".cpy" for ext in exts))
                raise
            for ext in fetched:
                os.replace(base + ext + ".cpy", base + ext)
        return {}

    def _h_ec_scrub(self, req: Request):
        """Verify LOCAL shards of an EC volume against the .vif CRC
        record (the fused-encode checksums).  Report-only: repairing a
        corrupt shard needs >= 10 survivors, which one holder rarely
        has, so the shell's ec.scrub routes repairs through ec.rebuild
        after deleting the corrupt shard cluster-wide."""
        from ..storage.erasure_coding.encoder import load_volume_info
        from ..storage.tools import verify_shard_files

        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        loc = self.store.location_of(vid) or self.store.locations[0]
        base = loc._base_name(collection, vid)
        info = load_volume_info(base) or {}
        try:
            clean, corrupt, _ = verify_shard_files(
                base, info.get("shard_crc32c"))
        except ValueError as e:
            raise RpcError(str(e), 404)
        # 'absent' is normal here (shards spread over holders); the shell
        # derives cluster-wide missing from the union of holder reports
        return {"volume": vid, "clean": clean, "corrupt": corrupt}

    def _h_ec_delete_shards(self, req: Request):
        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        shard_ids = [int(s) for s in p["shard_ids"]]
        self.store.ec_unmount(vid, shard_ids)
        for loc in self.store.locations:
            base = loc._base_name(collection, vid)
            _remove_quiet(*(base + to_ext(sid) for sid in shard_ids))
            # when no shards remain, drop the index sidecars too
            if not any(os.path.exists(base + to_ext(i))
                       for i in range(TOTAL_SHARDS_COUNT)):
                _remove_quiet(base + ".ecx", base + ".ecj", base + ".vif")
        # push the shrunken ShardBits to the master NOW: callers chain
        # ec.rebuild right after a delete and plan from the master's view
        self._try_heartbeat()
        return {}

    def _h_ec_to_volume(self, req: Request):
        """VolumeEcShardsToVolume: decode local shards back to .dat/.idx."""
        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        loc = self.store.location_of(vid) or self.store.locations[0]
        base = loc._base_name(collection, vid)
        rebuild_ecx_file(base)
        dat_size = ec_decoder.find_dat_file_size(base, base)
        fam = ec_codes.get_family(
            (load_volume_info(base) or {}).get("code_family"))
        ec_decoder.write_dat_file(base, dat_size,
                                  data_shards=fam.data_shards)
        ec_decoder.write_idx_file_from_ec_index(base)
        # unmount EC runtime, load as a normal volume
        ev = self.store.find_ec_volume(vid)
        if ev is not None:
            self.store.ec_unmount(vid, list(ev.shards))
        loc.add_volume(vid, collection)
        self._try_heartbeat()
        return {}

    def _h_ec_recover_stats(self, req: Request):
        """Degraded-read telemetry: the process-wide stage/cache stats
        plus each mounted EC volume's recovered-block cache occupancy
        (same numbers the Prometheus ec_recover_* vectors export)."""
        from ..storage.erasure_coding.recover import STATS

        out = STATS.snapshot()
        volumes = {}
        for loc in self.store.locations:
            for vid, ev in loc.ec_volumes.items():
                volumes[str(vid)] = {
                    "cache_blocks": len(ev._recover_cache),
                    "cache_bytes": ev._recover_cache.size_bytes,
                }
        out["volumes"] = volumes
        return out

    def _h_ec_shard_file(self, req: Request):
        vid = int(req.param("volume", "0"))
        collection = req.param("collection", "") or ""
        ext = req.param("ext", "")
        if not ext.startswith(".ec") and ext not in (".ecx", ".ecj", ".vif",
                                                     ".dat", ".idx"):
            raise RpcError(f"disallowed ext {ext}", 400)
        if ext in (".dat", ".idx"):
            v = self.store.find_volume(vid)
            if v is not None:
                with v.lock:
                    v.nm.flush()
                    v.data.sync()
        for loc in self.store.locations:
            path = loc._base_name(collection, vid) + ext
            if os.path.exists(path):
                # stream with a fixed-size snapshot: a 30 GB volume moves
                # chunk by chunk (doCopyFile semantics, volume_grpc_copy.go)
                return stream_file(path)
        raise RpcError(f"{vid}{ext} not found", 404)

    def _h_ec_shard_read(self, req: Request):
        """VolumeEcShardRead: serve a span of a locally-mounted shard."""
        vid = int(req.param("volume", "0"))
        shard_id = int(req.param("shard", "0"))
        offset = int(req.param("offset", "0"))
        size = int(req.param("size", "0"))
        ev = self.store.find_ec_volume(vid)
        if ev is None or shard_id not in ev.shards:
            raise RpcError(f"shard {vid}.{shard_id} not found", 404)
        return ev.shards[shard_id].read_at(size, offset)

    def _h_ec_codes(self, req: Request):
        """Coding-tier introspection: registered families (geometry,
        repair read amp, decode-plan cache hit ratios), this process's
        rebuild read-amp counters, and each mounted EC volume's family.
        ?volume=N narrows to one volume."""
        want_vid = int(req.param("volume", "0"))
        volumes = {}
        for loc in self.store.locations:
            for vid, ev in loc.ec_volumes.items():
                if want_vid and vid != want_vid:
                    continue
                volumes[str(vid)] = {
                    "collection": ev.collection,
                    "family": ev.family.name,
                    "shards": sorted(ev.shards),
                }
        return {
            "default_family": ec_codes.DEFAULT_FAMILY,
            "families": ec_codes.describe_families(),
            "rebuild_read_amp": ec_codes.rebuild_read_amp_snapshot(),
            "volumes": volumes,
        }

    def _h_ec_inline_status(self, req: Request):
        """Inline-EC write-path introspection: every mounted volume that
        carries an inline stripe writer reports its commit watermark,
        tail occupancy and realised write amplification.  ?volume=N
        narrows to one volume."""
        want_vid = int(req.param("volume", "0"))
        volumes = {}
        for loc in self.store.locations:
            for vid, ev in loc.ec_volumes.items():
                writer = getattr(ev, "writer", None)
                if writer is None:
                    continue
                if want_vid and vid != want_vid:
                    continue
                st = writer.status()
                st["collection"] = ev.collection
                volumes[str(vid)] = st
        return {"inline_volumes": volumes, "count": len(volumes)}

    def _h_ec_shard_project(self, req: Request):
        """Sub-shard read RPC: stream GF(2^8) projection ``vec @ lanes``
        of a locally-mounted shard — the helper side of a regenerating-
        code repair.  The reply is 1/alpha the shard's size, which is
        the whole point: the rebuilder pulls d of these instead of k
        full shards."""
        vid = int(req.param("volume", "0"))
        shard_id = int(req.param("shard", "0"))
        vec = tuple(int(x) for x in req.param("vec", "").split(",") if x)
        ev = self.store.find_ec_volume(vid)
        if ev is None or shard_id not in ev.shards:
            raise RpcError(f"shard {vid}.{shard_id} not found", 404)
        fam = ev.family
        if fam.sub_shards <= 1:
            raise RpcError(
                f"volume {vid} family {fam.name} has no sub-shards", 400)
        if len(vec) != fam.sub_shards:
            raise RpcError(
                f"vec needs {fam.sub_shards} coefficients", 400)
        shard = ev.shards[shard_id]
        total = shard.ecd_file_size
        chunk = (4 << 20) // fam.sub_shards * fam.sub_shards

        def gen():
            pos = 0
            while pos < total:
                n = min(chunk, total - pos)
                buf = shard.read_at(n, pos)
                if len(buf) != n:
                    raise RpcError(
                        f"short read shard {vid}.{shard_id}", 500)
                yield fam.project(
                    np.frombuffer(buf, dtype=np.uint8), vec).tobytes()
                pos += n

        return Response(gen(), content_type="application/octet-stream")

    def _h_ec_rebuild_projected(self, req: Request):
        """Projection rebuild: pull d helper projections over the wire
        and combine them into the lost shard locally — the repair-optimal
        rebuild for regenerating families (moves shard_size * d / alpha
        bytes instead of shard_size * k).  Verifies the rebuilt CRC
        against the .vif record when one exists and feeds the
        maintenance_ec_rebuild_* read-amp metrics."""
        import concurrent.futures as cf

        from ..ops.crc32c import crc32c

        p = req.json()
        vid = int(p["volume"])
        collection = p.get("collection", "")
        lost = int(p["shard"])
        sources = {int(s["shard_id"]): s["url"] for s in p["sources"]}
        loc = self.store.location_of(vid) or self.store.locations[0]
        base = loc._base_name(collection, vid)
        info = load_volume_info(base) or {}
        fam = ec_codes.get_family(info.get("code_family"))
        plan = fam.repair_plan(lost, sources)
        if plan.kind != "projection":
            raise RpcError(
                f"family {fam.name} has no projection repair for shard "
                f"{lost} from {sorted(sources)}", 400)
        vec_param = ",".join(str(x) for x in plan.vector)

        def pull(h: int) -> str:
            path = f"{base}.proj{h:02d}"
            chunks = call_stream(
                sources[h],
                f"/admin/ec/shard_project?volume={vid}&shard={h}"
                f"&vec={vec_param}", timeout=600)
            with open(path, "wb") as f:
                for chunk in chunks:
                    f.write(chunk)
            return path

        proj_paths: dict[int, str] = {}
        with self._vid_copy_lock(vid):
            try:
                with cf.ThreadPoolExecutor(
                        max_workers=len(plan.helpers),
                        thread_name_prefix="ec-project") as pool:
                    futs = {h: pool.submit(pull, h) for h in plan.helpers}
                    for h, fut in futs.items():
                        proj_paths[h] = fut.result()
                widths = {os.path.getsize(path)
                          for path in proj_paths.values()}
                if len(widths) != 1:
                    raise RpcError(
                        f"helper projections disagree on size: {widths}",
                        502)
                width = widths.pop()
                crc = 0
                step = (1 << 20)
                files = [open(proj_paths[h], "rb") for h in plan.helpers]
                try:
                    with open(base + to_ext(lost) + ".cpy", "wb") as out:
                        pos = 0
                        while pos < width:
                            n = min(step, width - pos)
                            stack = np.stack([
                                np.frombuffer(f.read(n), dtype=np.uint8)
                                for f in files])
                            restored = np.ascontiguousarray(
                                fam.combine_projections(plan, stack)
                            ).tobytes()
                            out.write(restored)
                            crc = crc32c(restored, crc)
                            pos += n
                finally:
                    for f in files:
                        f.close()
                stored = info.get("shard_crc32c")
                if (isinstance(stored, list)
                        and len(stored) == TOTAL_SHARDS_COUNT
                        and crc != stored[lost]):
                    _remove_quiet(base + to_ext(lost) + ".cpy")
                    raise RpcError(
                        f"projected rebuild of shard {vid}.{lost} does "
                        "not match the recorded CRC — a helper shard is "
                        "corrupt", 502)
                os.replace(base + to_ext(lost) + ".cpy", base + to_ext(lost))
            finally:
                _remove_quiet(*proj_paths.values())
        read_bytes = width * len(plan.helpers)
        rebuilt_bytes = width * fam.sub_shards
        ec_codes.note_rebuild(fam.name, read_bytes, rebuilt_bytes)
        self.read_cache.invalidate_volume(vid, reason="rebuild")
        return {"rebuilt_shard_ids": [lost], "read_bytes": read_bytes,
                "rebuilt_bytes": rebuilt_bytes,
                "read_amp": round(read_bytes / rebuilt_bytes, 4),
                "crc32c": crc}

    # -- remote EC shard fetch (store_ec.go read ladder) ---------------------
    def _make_remote_reader(self, vid: int):
        def remote_reader(shard_id: int, offset: int,
                          size: int) -> Optional[bytes]:
            locations = self._ec_shard_locations(vid).get(shard_id, [])
            candidates = [u for u in locations if u != self.store.url]
            if not candidates:
                self._note_ec_lookup_error(vid)
                return None

            def fetch(url):
                def attempt():
                    data = call(
                        url,
                        f"/admin/ec/shard_read?volume={vid}"
                        f"&shard={shard_id}&offset={offset}&size={size}",
                        timeout=30)
                    if not isinstance(data, (bytes, bytearray)):
                        raise RpcError(
                            f"unexpected shard_read reply from {url}",
                            502, addr=url, transport=True)
                    return bytes(data)
                return attempt

            # hedged survivor fetch: a slow holder stops gating the
            # whole degraded read once the adaptive p95 delay elapses —
            # the next holder races it and the first answer wins
            try:
                return policy.hedged("/admin/ec/shard_read",
                                     [fetch(u) for u in candidates])
            except Exception:
                # all candidates failed: demote the cache entry to the
                # error tier so the next read re-resolves quickly
                self._note_ec_lookup_error(vid)
                return None
        return remote_reader

    def _note_ec_lookup_error(self, vid: int):
        cached = self._ec_locations.get(vid)
        if cached is not None:
            self._ec_locations[vid] = (cached[0], cached[1], True)

    def _ec_shard_locations(self, vid: int) -> dict[int, list[str]]:
        """Tiered-freshness shard location cache
        (cachedLookupEcShardLocations, store_ec.go:227-268)."""
        now = time.time()
        cached = self._ec_locations.get(vid)
        if cached is not None:
            fetched_at, locations, had_error = cached
            if had_error:
                ttl = EC_SHARD_CACHE_TTL_ERROR
            elif len(locations) < TOTAL_SHARDS_COUNT:
                ttl = EC_SHARD_CACHE_TTL_INCOMPLETE
            else:
                ttl = EC_SHARD_CACHE_TTL_HEALTHY
            if now - fetched_at < ttl:
                return locations
        try:
            resp = policy.call_policy(
                self.master_address, f"/ec/lookup?volumeId={vid}",
                timeout=10)
            locations = {
                e["shard_id"]: [loc["url"] for loc in e["locations"]]
                for e in resp.get("shard_id_locations", [])
            }
            had_error = False
        except RpcError:
            locations = cached[1] if cached else {}
            had_error = True
        self._ec_locations[vid] = (now, locations, had_error)
        return locations

    def _try_heartbeat(self):
        try:
            self.heartbeat_once()
        except RpcError:
            pass
