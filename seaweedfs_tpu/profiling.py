"""Continuous profiling: folded call stacks, device kernel timelines and
HBM occupancy telemetry.

Every perf round so far was steered by hand-rolled stage timers; the only
CPU profiler in the tree was the flat leaf-frame sampler in util/grace.py
(no call stacks, no on-demand access, no device visibility).  This module
is the always-on, queryable profiling layer:

  * host side — a sampling profiler over ``sys._current_frames()`` that
    keeps FULL folded call stacks (``frame.f_back`` walk, bounded
    stack-interning table), tagged with the sampled thread's name and
    the active RPC route from tracing's thread-local span context, so a
    profile slices per daemon, per thread pool and per route.  It runs
    always-on at a low ``WEED_PROF_HZ`` rate and serves on-demand bursts
    via ``GET /debug/pprof/profile?seconds=N&hz=M`` (collapsed-stack
    text — pipe straight into flamegraph.pl or speedscope) plus
    ``GET /debug/pprof/heap`` (tracemalloc allocation sites, armed on
    demand), mounted on every daemon exactly like ``/debug/traces``.
    The profiler measures its own duty cycle and exports it as the
    ``SeaweedFS_profiler_overhead_ratio`` gauge;
  * device side — host-timed dispatch->ready latency per batch from the
    EC device pipeline's completion FIFO, XLA cost analysis captured
    once per compiled geometry, and the device pool's HBM occupancy
    high-watermark, all queryable as a JSON timeline on
    ``GET /debug/pprof/device`` and exported as ``ec_kernel_*`` /
    ``device_pool_*`` metric families;
  * cluster side — ``merge_folded`` combines per-daemon profiles under
    per-daemon root frames into one cluster flamegraph (the engine
    behind ``weed.py profile``).

Knobs (env, read live like the WEED_TRACE_* family):
  WEED_PROF_HZ          always-on sampling rate (default 5; 0 disables)
  WEED_PROF_MAX_STACKS  interned-stack table cap (default 8192)
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Optional

from . import tracing
from .stats import metrics as _stats

_TRUNCATED = "(truncated)"
_MAX_DEPTH = 64


def prof_hz() -> float:
    return tracing._env_live(
        "WEED_PROF_HZ", b"WEED_PROF_HZ",
        lambda raw: max(0.0, float(raw)), 5.0)


def max_stacks() -> int:
    return tracing._env_live(
        "WEED_PROF_MAX_STACKS", b"WEED_PROF_MAX_STACKS", int, 8192)


# -- folded-stack engine ------------------------------------------------------

# frame labels are interned per (code object, line): the sampler walks
# the same hot frames thousands of times, so the format+basename cost is
# paid once per distinct frame, not per sample
_label_cache: dict = {}


def _frame_label(frame, leaf: bool) -> str:
    co = frame.f_code
    # leaf frames keep the sampled line (hot-line attribution, like the
    # old flat sampler); caller frames use the def line so one function
    # is ONE flamegraph frame no matter which call site is live.
    # f_lineno can be None when the target thread is mid-transition
    # (CPython computes it lazily from f_lasti) — fall back to the def
    # line rather than dropping the whole sample
    lineno = (frame.f_lineno if leaf else None) or co.co_firstlineno
    key = (co, lineno)
    label = _label_cache.get(key)
    if label is None:
        if len(_label_cache) > 4 * max_stacks():
            _label_cache.clear()
        label = "%s (%s:%d)" % (co.co_name,
                                os.path.basename(co.co_filename), lineno)
        label = label.replace(";", ":")  # ';' is the fold separator
        _label_cache[key] = label
    return label


def fold_stack(frame) -> str:
    """Root-first collapsed stack for one thread's current frame."""
    parts = []
    leaf = True
    while frame is not None and len(parts) < _MAX_DEPTH:
        parts.append(_frame_label(frame, leaf))
        leaf = False
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


class StackSampler:
    """All-thread folded-stack sampling profiler.

    Samples ``sys._current_frames()`` on a timer like Go's pprof CPU
    profile; each sample's key is ``thread[;route];frame;frame;...`` in
    flamegraph.pl collapsed form.  ``publish=True`` (the always-on
    instance) mirrors per-route sample counts into the Prometheus
    registry.  The sampler measures its own busy time, so its duty
    cycle (``overhead_ratio``) is observable, not guessed."""

    def __init__(self, hz: Optional[float] = None,
                 publish: bool = False, exclude=()):
        self.hz = hz  # None: follow WEED_PROF_HZ live
        self.samples: dict[str, int] = {}
        self.total = 0
        self.truncated = 0
        self.errors = 0
        self.route_samples: dict[str, int] = {}
        self.busy = 0.0
        self.started = 0.0
        self._publish = publish
        self._exclude = set(exclude)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._names: dict[int, str] = {}
        self._ticks = 0

    # -- lifecycle ----------------------------------------------------

    def start(self):
        self.started = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="weed-prof", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> bool:
        """Stop sampling; True when the sampler thread actually joined
        (False: it is still finishing one last tick — daemonized, so it
        cannot outlive the process, but the caller should say so)."""
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def _interval(self) -> float:
        hz = self.hz if self.hz is not None else prof_hz()
        return (1.0 / hz) if hz and hz > 0 else 0.0

    def _loop(self):
        me = threading.get_ident()
        while True:
            interval = self._interval()
            if interval <= 0:  # live-disabled: idle cheaply, stay alive
                if self._stop.wait(0.5):
                    return
                continue
            if self._stop.wait(interval):
                return
            t0 = time.perf_counter()
            try:
                self._sample_once(me)
            except Exception:
                # sampling races against every other thread's execution
                # state; one unreadable tick must not kill the always-on
                # sampler for the remaining process lifetime
                self.errors += 1
            self.busy += time.perf_counter() - t0

    # -- sampling -----------------------------------------------------

    def _sample_once(self, me: int):
        frames = sys._current_frames()
        names = self._names
        if any(tid not in names for tid in frames):
            names = self._names = {
                t.ident: t.name for t in threading.enumerate()}
        self._ticks += 1
        if self._ticks % 128 == 0:
            tracing.prune_thread_spans(frames.keys())
        cap = max_stacks()
        routes = []
        with self._lock:
            for tid, frame in frames.items():
                if tid == me or tid in self._exclude:
                    continue
                sp = tracing.span_for_thread(tid)
                route = (sp.route or "") if sp is not None else ""
                key = "%s;%s" % (names.get(tid) or "thread-%d" % tid,
                                 fold_stack(frame))
                if route:
                    thread, _, rest = key.partition(";")
                    key = "%s;%s;%s" % (thread, route, rest)
                    routes.append(route)
                    self.route_samples[route] = \
                        self.route_samples.get(route, 0) + 1
                if key not in self.samples and len(self.samples) >= cap:
                    self.truncated += 1
                    key = _TRUNCATED
                self.samples[key] = self.samples.get(key, 0) + 1
                self.total += 1
        if self._publish:
            for route in routes:
                _stats.ProfilerRouteSamplesCounter.labels(route).inc()

    # -- reporting ----------------------------------------------------

    def overhead_ratio(self) -> float:
        wall = time.perf_counter() - self.started if self.started else 0.0
        return (self.busy / wall) if wall > 0 else 0.0

    def folded(self, limit: int = 0) -> str:
        """Collapsed-stack text, hottest stacks first — feed directly to
        flamegraph.pl / speedscope."""
        with self._lock:
            items = sorted(self.samples.items(), key=lambda kv: -kv[1])
        if limit:
            items = items[:limit]
        return "".join("%s %d\n" % kv for kv in items)

    def top_frames(self, n: int = 12) -> list[dict]:
        """Self-time ranking by leaf frame (the bench JSON breakdown)."""
        agg: dict[str, int] = {}
        with self._lock:
            total = self.total or 1
            for stack, count in self.samples.items():
                leaf = stack.rsplit(";", 1)[-1]
                agg[leaf] = agg.get(leaf, 0) + count
        ranked = sorted(agg.items(), key=lambda kv: -kv[1])[:n]
        return [{"frame": frame, "samples": count,
                 "pct": round(100.0 * count / total, 1)}
                for frame, count in ranked]

    def snapshot(self) -> dict:
        with self._lock:
            return {"samples": self.total, "stacks": len(self.samples),
                    "truncated": self.truncated, "errors": self.errors,
                    "overhead_ratio": round(self.overhead_ratio(), 6)}


# -- always-on process profiler ----------------------------------------------

_PROFILER: Optional[StackSampler] = None
_prof_lock = threading.Lock()


def ensure_started() -> Optional[StackSampler]:
    """Start the process-wide always-on sampler (idempotent; called by
    every daemon mount).  WEED_PROF_HZ is read live inside the loop, so
    0 parks the thread rather than preventing creation."""
    global _PROFILER
    if _PROFILER is None:
        with _prof_lock:
            if _PROFILER is None:
                prof = StackSampler(hz=None, publish=True)
                prof.start()
                _PROFILER = prof
    return _PROFILER


def profiler() -> Optional[StackSampler]:
    return _PROFILER


def overhead_ratio() -> float:
    prof = _PROFILER
    return prof.overhead_ratio() if prof is not None else 0.0


def stack_count() -> float:
    prof = _PROFILER
    return float(len(prof.samples)) if prof is not None else 0.0


def profile_burst(seconds: float, hz: float, exclude=()) -> str:
    """On-demand burst: a dedicated sampler for `seconds` at `hz`,
    returning collapsed stacks.  Runs beside the always-on sampler
    without disturbing its counters."""
    sampler = StackSampler(hz=hz, publish=False, exclude=exclude)
    sampler.start()
    time.sleep(seconds)
    sampler.stop()
    return sampler.folded()


# -- device kernel timeline ---------------------------------------------------

_tl_lock = threading.Lock()
_DEVICE_TIMELINE: "deque[dict]" = deque(maxlen=512)
_KERNEL_COST: dict[str, dict] = {}


def record_device_batch(latency_s: float, units: int = 0, k: int = 0,
                        devices: int = 1):
    """One EC device batch completed: host-observed dispatch->ready
    latency (rides the WEED_EC_DEVICE_INFLIGHT completion FIFO).
    `devices` is the shard width of the dispatch — the histogram is
    labeled by it, so a stall that only appears at a given mesh width
    shows up as its own latency series."""
    _stats.EcKernelDispatchHistogram.labels(str(devices)).observe(latency_s)
    with _tl_lock:
        _DEVICE_TIMELINE.append({
            "ts": round(time.time(), 3),
            "dispatch_ready_ms": round(latency_s * 1e3, 3),
            "units": units, "k": k, "devices": devices})


def record_kernel_cost(geometry: str, flops: float, bytes_accessed: float,
                       extra: Optional[dict] = None):
    """XLA cost analysis for one compiled geometry (from mesh.py)."""
    entry = {"flops": float(flops), "bytes_accessed": float(bytes_accessed)}
    if extra:
        entry.update(extra)
    with _tl_lock:
        _KERNEL_COST[geometry] = entry
    _stats.EcKernelFlopsGauge.labels(geometry).set(float(flops))
    _stats.EcKernelBytesGauge.labels(geometry).set(float(bytes_accessed))


def device_timeline() -> dict:
    """The /debug/pprof/device payload: recent batch latencies, per-
    geometry kernel cost, and the device pool's occupancy snapshot."""
    from .ops import device_pool

    pool = device_pool._pool  # do NOT materialize a pool just to report
    with _tl_lock:
        timeline = list(_DEVICE_TIMELINE)
        cost = {k: dict(v) for k, v in _KERNEL_COST.items()}
    return {"timeline": timeline, "kernel_cost": cost,
            "pool": pool.snapshot() if pool is not None else {}}


def reset_device_telemetry():
    """Tests: drop the timeline + cost table."""
    with _tl_lock:
        _DEVICE_TIMELINE.clear()
        _KERNEL_COST.clear()


# -- cluster merge ------------------------------------------------------------

def merge_folded(profiles: dict[str, str]) -> str:
    """Merge per-daemon collapsed-stack texts into one cluster profile:
    each daemon becomes a root frame, identical stacks sum."""
    merged: dict[str, int] = {}
    for daemon in sorted(profiles):
        for line in profiles[daemon].splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            stack, _, count = line.rpartition(" ")
            try:
                n = int(count)
            except ValueError:
                continue
            if not stack:
                continue
            key = "%s;%s" % (daemon, stack)
            merged[key] = merged.get(key, 0) + n
    return "".join("%s %d\n" % kv for kv in
                   sorted(merged.items(), key=lambda kv: -kv[1]))


# -- HTTP surface -------------------------------------------------------------

def _heap_text(req) -> str:
    import tracemalloc

    if req.param("stop") == "1":
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        return "# tracemalloc disarmed\n"
    if not tracemalloc.is_tracing():
        # armed on demand: tracing allocations is too costly to leave on
        tracemalloc.start(12)
        return ("# tracemalloc armed (12 frames); re-fetch "
                "/debug/pprof/heap for allocation sites, "
                "?stop=1 to disarm\n")
    try:
        limit = int(req.param("limit") or 50)
    except ValueError:
        limit = 50
    snapshot = tracemalloc.take_snapshot()
    lines = ["# tracemalloc top allocation sites"]
    lines.extend(str(stat) for stat in
                 snapshot.statistics("lineno")[:limit])
    return "\n".join(lines) + "\n"


def pprof_handler(req):
    """RpcServer route for the /debug/pprof family.  Register with the
    bare prefix — longest-prefix matching routes profile/heap/device
    here, like traces_handler."""
    from .rpc.http_rpc import Response, RpcError

    rest = req.path[len("/debug/pprof"):].strip("/")
    if not rest:
        prof = _PROFILER
        return {
            "endpoints": ["/debug/pprof/profile?seconds=N&hz=M",
                          "/debug/pprof/heap", "/debug/pprof/device"],
            "always_on": prof.snapshot() if prof is not None else None,
            "hz": prof_hz(),
        }
    if rest == "profile":
        try:
            seconds = float(req.param("seconds") or 2.0)
        except ValueError:
            seconds = 2.0
        try:
            hz = float(req.param("hz") or 99.0)
        except ValueError:
            hz = 99.0
        seconds = max(0.0, min(seconds, 120.0))
        hz = max(1.0, min(hz, 1000.0))
        if seconds == 0:  # cumulative always-on profile, no wait
            prof = _PROFILER
            if prof is None:
                raise RpcError(
                    "always-on profiler not running; use ?seconds=N", 400)
            text = prof.folded()
        else:
            text = profile_burst(seconds, hz,
                                 exclude={threading.get_ident()})
        return Response(text.encode(),
                        content_type="text/plain; charset=utf-8")
    if rest == "heap":
        return Response(_heap_text(req).encode(),
                        content_type="text/plain; charset=utf-8")
    if rest == "device":
        return device_timeline()
    raise RpcError(f"unknown pprof endpoint {rest!r}", 404)


def mount(server):
    """Register /debug/pprof on an RpcServer and start the always-on
    sampler (every daemon front end calls this, like faults.mount)."""
    server.add("GET", "/debug/pprof", pprof_handler)
    ensure_started()
