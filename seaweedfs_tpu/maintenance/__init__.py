"""Continuous maintenance: curator scheduler, job queue, workers.

The master's leader runs a :class:`Curator` that scans heartbeat state
for anomalies (missing EC shards, under-replication, garbage, stale
scrubs, placement skew) and feeds a persistent deduped priority
:class:`JobQueue`.  Volume servers run a :class:`MaintenanceWorker`
that leases jobs, executes them under a :class:`BytePacer` that backs
off against foreground load, and reports outcomes.  Deep scrub
re-encodes data-shard spans through the persistent device parity step
and compares chained CRCs against the stored `.vif` records."""

from .curator import Curator
from .deep_scrub import ScrubTarget, deep_scrub, deep_scrub_host
from .jobs import (JOB_TYPES, TYPE_BALANCE, TYPE_DEEP_SCRUB,
                   TYPE_EC_REBUILD, TYPE_FIX_REPLICATION, TYPE_VACUUM,
                   Job)
from .pacer import BytePacer
from .queue import JobQueue
from .worker import MaintenanceWorker

__all__ = [
    "Curator", "MaintenanceWorker", "JobQueue", "Job", "BytePacer",
    "ScrubTarget", "deep_scrub", "deep_scrub_host", "JOB_TYPES",
    "TYPE_EC_REBUILD", "TYPE_FIX_REPLICATION", "TYPE_VACUUM",
    "TYPE_DEEP_SCRUB", "TYPE_BALANCE",
]
