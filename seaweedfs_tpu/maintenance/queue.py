"""Persistent, deduped, priority job queue for the curator.

Every mutation appends one JSON line to a journal file, so a restarted
(or newly elected) master replays the journal and resumes with the
same pending/leased set — jobs survive failover.  The journal is
compacted in place once it grows well past the live set.

Leases carry an expiry: a worker that stops renewing (crashed,
partitioned) loses the job, which silently returns to pending for the
next `lease()` call.  `self.now` is a monkeypatchable seam (like
rpc.policy.now) so lease-expiry tests run on a fake clock."""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from ..stats import metrics
from .jobs import DONE, LEASED, PENDING, PRIORITIES, Job


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class JobQueue:
    def __init__(self, journal_path: str = "",
                 lease_seconds: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 retry_backoff: float = 5.0):
        self.now = time.time  # fake-clock seam for tests
        self.journal_path = journal_path
        self._lease_seconds = lease_seconds
        self._max_attempts = max_attempts
        self.retry_backoff = retry_backoff
        self._lock = threading.RLock()
        self._jobs: dict[str, Job] = {}       # live (pending/leased)
        self._by_key: dict[tuple, str] = {}   # dedupe index
        self._seq = 0
        self._journal_lines = 0
        self.paused = False
        self.history: deque = deque(maxlen=256)  # finished job dicts
        if journal_path:
            self._replay()

    # -- knobs (re-read at use time, WEED_* convention) ----------------------
    @property
    def lease_seconds(self) -> float:
        if self._lease_seconds is not None:
            return self._lease_seconds
        return _env_float("WEED_MAINT_LEASE", 60.0)

    @property
    def max_attempts(self) -> int:
        if self._max_attempts is not None:
            return self._max_attempts
        return int(_env_float("WEED_MAINT_ATTEMPTS", 5))

    # -- journal -------------------------------------------------------------
    def _replay(self):
        if not os.path.exists(self.journal_path):
            return
        with open(self.journal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail write from a crash
                self._journal_lines += 1
                if rec.get("op") == "set":
                    job = Job.from_dict(rec["job"])
                    self._jobs[job.id] = job
                    self._by_key[job.key] = job.id
                    try:
                        self._seq = max(self._seq, int(job.id[1:]))
                    except ValueError:
                        pass
                elif rec.get("op") == "del":
                    job = self._jobs.pop(rec["id"], None)
                    if job is not None and \
                            self._by_key.get(job.key) == job.id:
                        del self._by_key[job.key]
        # a replayed lease belongs to a worker from before the restart;
        # let it expire naturally (the worker may still be running it)

    def _append(self, rec: dict):
        if not self.journal_path:
            return
        with open(self.journal_path, "a") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._journal_lines += 1
        if self._journal_lines > max(64, 8 * (len(self._jobs) + 1)):
            self._compact()

    def _compact(self):
        # crash-atomic: the live set is fully durable in the tmp file
        # BEFORE the rename swaps it in, so a kill at any instant leaves
        # either the complete old journal or the complete new one
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w") as f:
            for job in self._jobs.values():
                f.write(json.dumps({"op": "set", "job": job.to_dict()},
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.journal_path)
        try:
            dir_fd = os.open(os.path.dirname(self.journal_path) or ".",
                             os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except OSError:
            pass  # the rename itself is still atomic without the dir sync
        self._journal_lines = len(self._jobs)

    def _sync_metrics(self):
        counts = {PENDING: 0, LEASED: 0}
        for job in self._jobs.values():
            counts[job.state] = counts.get(job.state, 0) + 1
        for state, n in counts.items():
            metrics.MaintQueueJobsGauge.labels(state).set(n)

    # -- producer side -------------------------------------------------------
    def enqueue(self, type_: str, volume: int = 0, collection: str = "",
                params: Optional[dict] = None,
                priority: Optional[int] = None) -> Optional[str]:
        """Add a job unless one is already live for the same target.
        Returns the job id, or None when deduped."""
        with self._lock:
            key = (type_, volume, collection)
            if key in self._by_key:
                return None
            self._seq += 1
            job = Job(id=f"j{self._seq}", type=type_, volume=volume,
                      collection=collection, params=dict(params or {}),
                      priority=(PRIORITIES.get(type_, 9)
                                if priority is None else priority),
                      created_at=self.now())
            self._jobs[job.id] = job
            self._by_key[key] = job.id
            self._append({"op": "set", "job": job.to_dict()})
            self._sync_metrics()
            return job.id

    # -- worker side ---------------------------------------------------------
    def lease(self, worker: str, types: Optional[list] = None,
              limit: int = 1,
              ec_volumes: Optional[list] = None) -> list[dict]:
        """Hand out up to `limit` pending jobs, best priority first.
        `ec_volumes` (the worker's locally-held EC volumes) scopes
        deep-scrub jobs to holders — scrubbing needs the local .vif
        CRC record and most shard bytes on local disk; every other
        job type executes via RPC and goes to any worker."""
        with self._lock:
            if self.paused:
                return []
            now = self.now()
            held = set(ec_volumes) if ec_volumes is not None else None
            ready = [j for j in self._jobs.values()
                     if j.state == PENDING and j.not_before <= now
                     and (not types or j.type in types)
                     and (j.type != "deep.scrub" or held is None
                          or j.volume in held)]
            ready.sort(key=lambda j: (j.priority, j.created_at, j.id))
            out = []
            for job in ready[:max(0, limit)]:
                job.state = LEASED
                job.worker = worker
                job.attempts += 1
                job.lease_expires = now + self.lease_seconds
                self._append({"op": "set", "job": job.to_dict()})
                out.append(job.to_dict())
            if out:
                self._sync_metrics()
            return out

    def renew(self, job_id: str, worker: str) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state != LEASED or job.worker != worker:
                return False
            job.lease_expires = self.now() + self.lease_seconds
            # heartbeat only — not worth a journal line per renewal
            return True

    def complete(self, job_id: str, worker: str,
                 outcome: str = "ok") -> Optional[Job]:
        """Finish a job; returns the job (for completion hooks) or
        None when the lease was lost (stale worker)."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.worker != worker:
                return None
            return self._finish(job, outcome)

    def fail(self, job_id: str, worker: str, error: str) -> Optional[Job]:
        """Record a failure: requeue with backoff, or finish as
        'failed' once attempts are exhausted."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.worker != worker:
                return None
            job.last_error = str(error)[:500]
            if job.attempts >= self.max_attempts:
                return self._finish(job, "failed")
            job.state = PENDING
            job.worker = ""
            job.lease_expires = 0.0
            job.not_before = self.now() + self.retry_backoff
            self._append({"op": "set", "job": job.to_dict()})
            self._sync_metrics()
            return job

    def _finish(self, job: Job, outcome: str) -> Job:
        job.state = DONE
        job.outcome = outcome
        del self._jobs[job.id]
        if self._by_key.get(job.key) == job.id:
            del self._by_key[job.key]
        self._append({"op": "del", "id": job.id})
        self.history.append({**job.to_dict(), "finished_at": self.now()})
        metrics.MaintJobsCounter.labels(job.type, outcome).inc()
        self._sync_metrics()
        return job

    def expire_leases(self) -> list[str]:
        """Requeue jobs whose worker stopped renewing (dead/partitioned).
        Called from the curator tick."""
        with self._lock:
            now = self.now()
            expired = []
            for job in self._jobs.values():
                if job.state == LEASED and job.lease_expires < now:
                    job.state = PENDING
                    job.worker = ""
                    job.lease_expires = 0.0
                    job.last_error = job.last_error or "lease expired"
                    self._append({"op": "set", "job": job.to_dict()})
                    expired.append(job.id)
            if expired:
                self._sync_metrics()
            return expired

    # -- views ---------------------------------------------------------------
    def get(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def stats(self) -> dict:
        with self._lock:
            by_state: dict[str, int] = {}
            by_type: dict[str, int] = {}
            for job in self._jobs.values():
                by_state[job.state] = by_state.get(job.state, 0) + 1
                by_type[job.type] = by_type.get(job.type, 0) + 1
            return {"live": len(self._jobs), "by_state": by_state,
                    "by_type": by_type, "paused": self.paused,
                    "finished": len(self.history)}

    def jobs(self) -> list[dict]:
        with self._lock:
            live = sorted(self._jobs.values(),
                          key=lambda j: (j.priority, j.created_at, j.id))
            return [j.to_dict() for j in live]
