"""Device-batched deep scrub: re-encode and compare, not just re-hash.

The plain scrub (`/admin/ec/scrub`, storage.tools.verify_shard_files)
only re-hashes each local .ecNN file against the CRC the encode
pipeline recorded — it catches bitrot inside a file but cannot tell
whether the *parity still matches the data* (a stale or cross-wired
sidecar passes).  Deep scrub goes further:

 * every present shard file is streamed span-by-span (paced through
   the curator's BytePacer) and its rolling CRC32C is chained exactly
   like `shard_file_crc32c` — the basic bitrot check rides along for
   free on the same reads;
 * the ten data-shard spans are packed into `(10, B, W)` int32 batches
   — spans from *different volumes* share one compiled geometry — and
   pushed through the persistent `make_parity_step` SWAR kernel with
   the same DevicePool donated-output ring the encode path uses; the
   recomputed parity's chained CRCs are compared against the stored
   parity CRCs, proving data and parity agree end to end;
 * the host fallback (`deep_scrub_host`) walks the sorted .ecx and
   re-reads every live needle, verifying each needle's own CRC — the
   needle-level integrity walk for hosts without a device mesh.

Batching across volumes matters: scrub spans are small and plentiful,
and one fixed (k=10, B, W) shape means the kernel compiles once for
the whole sweep no matter how many volumes it covers."""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..ops import crc32c as crc_host
from ..qos import lanes as _lanes
from ..storage.erasure_coding import (DATA_SHARDS_COUNT,
                                      PARITY_SHARDS_COUNT,
                                      TOTAL_SHARDS_COUNT, to_ext)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def span_bytes_default() -> int:
    """WEED_MAINT_SPAN_KB: deep-scrub span (device chunk) size."""
    return max(4096, _env_int("WEED_MAINT_SPAN_KB", 1024) << 10)


def _inflight() -> int:
    return max(1, _env_int("WEED_EC_DEVICE_INFLIGHT", 3))


@dataclass
class ScrubTarget:
    """One EC volume to deep-scrub.  `reader(shard, offset, size)`
    returns up to `size` bytes of that shard (local file or a peer's
    /admin/ec/shard_read) — short returns mean EOF, exceptions mean
    the shard is unreachable."""

    volume: int
    collection: str
    stored: list            # 14 recorded CRC32Cs from the .vif
    sizes: list             # per-shard byte length; -1 when absent
    reader: Callable[[int, int, int], bytes]
    close: Optional[Callable[[], None]] = None
    # runtime state
    chains: list = field(default_factory=list)
    computed: list = field(default_factory=list)
    recompute: bool = True
    unreadable: set = field(default_factory=set)
    bytes_read: int = 0

    def __post_init__(self):
        self.chains = [0] * TOTAL_SHARDS_COUNT
        self.computed = [0] * PARITY_SHARDS_COUNT
        # recompute needs every data shard; file-CRC still covers the rest
        self.recompute = all(
            self.sizes[i] >= 0 for i in range(DATA_SHARDS_COUNT))

    @property
    def shard_len(self) -> int:
        return max([s for s in self.sizes if s >= 0] or [0])


def local_target(base: str, volume: int = 0,
                 collection: str = "") -> ScrubTarget:
    """Build a ScrubTarget over local .ecNN files (bench/offline path
    and the worker's local-shard reads)."""
    from ..storage.erasure_coding.encoder import load_volume_info

    info = load_volume_info(base) or {}
    stored = info.get("shard_crc32c")
    if not isinstance(stored, list) or len(stored) != TOTAL_SHARDS_COUNT:
        raise ValueError(f"{base}.vif has no shard_crc32c record")
    sizes = []
    for sid in range(TOTAL_SHARDS_COUNT):
        path = base + to_ext(sid)
        sizes.append(os.path.getsize(path)
                     if os.path.exists(path) else -1)
    fds: dict[int, int] = {}

    def reader(sid: int, offset: int, size: int) -> bytes:
        fd = fds.get(sid)
        if fd is None:
            fd = fds[sid] = os.open(base + to_ext(sid), os.O_RDONLY)
        return os.pread(fd, size, offset)

    def close():
        for fd in fds.values():
            os.close(fd)
        fds.clear()

    return ScrubTarget(volume=volume, collection=collection,
                       stored=list(stored), sizes=sizes,
                       reader=reader, close=close)


def _read_span(t: ScrubTarget, sid: int, off: int, chunk: int,
               throttle) -> bytes:
    """One paced span read, chained into the shard's rolling file CRC."""
    want = min(chunk, max(0, t.sizes[sid] - off))
    if want <= 0:
        return b""
    try:
        raw = t.reader(sid, off, want)
    except Exception:
        t.unreadable.add(sid)
        if sid < DATA_SHARDS_COUNT:
            t.recompute = False
        return b""
    if raw:
        if throttle is not None:
            throttle(len(raw))
        t.chains[sid] = crc_host.crc32c(raw, t.chains[sid])
        t.bytes_read += len(raw)
    return raw


def _verdict(t: ScrubTarget) -> dict:
    missing = [s for s in range(TOTAL_SHARDS_COUNT) if t.sizes[s] < 0]
    corrupt = [s for s in range(TOTAL_SHARDS_COUNT)
               if t.sizes[s] >= 0 and s not in t.unreadable
               and t.chains[s] != t.stored[s]]
    parity_mismatch = []
    if t.recompute and not any(s < DATA_SHARDS_COUNT for s in corrupt):
        # data is bit-identical to what was encoded, so a recompute
        # mismatch means the STORED parity record disagrees with the
        # data — the check the plain file CRC cannot make
        for j in range(PARITY_SHARDS_COUNT):
            sid = DATA_SHARDS_COUNT + j
            if t.computed[j] != t.stored[sid] and sid not in corrupt:
                parity_mismatch.append(sid)
    return {"volume": t.volume, "collection": t.collection,
            "corrupt": corrupt, "missing": missing,
            "unreadable": sorted(t.unreadable),
            "parity_mismatch": parity_mismatch,
            "recomputed": t.recompute,
            "bytes": t.bytes_read,
            "ok": not (corrupt or missing or t.unreadable
                       or parity_mismatch)}


def deep_scrub(targets: list, mesh=None,
               span_bytes: Optional[int] = None,
               batch_units: Optional[int] = None,
               throttle=None,
               stage_stats: Optional[dict] = None) -> dict:
    """Deep-scrub `targets`, batching recompute spans across volumes
    into one compiled device geometry.  Returns
    {"volumes": [per-target verdicts], "scrubbed_bytes", "corrupt"}."""
    import numpy as np

    wall0 = time.perf_counter()
    timers = {"read": 0.0, "dispatch": 0.0, "encode_crc": 0.0}

    chunk = span_bytes or span_bytes_default()
    max_len = max([t.shard_len for t in targets] or [0])
    # no point padding spans past the largest shard; keep words whole
    if max_len > 0:
        chunk = min(chunk, max_len + (-max_len) % 4)
    chunk = max(4096, chunk - chunk % 4)

    # units: (target_idx, offset) spans for recompute-capable targets;
    # file-CRC-only targets are streamed without device dispatch
    units: list[tuple[int, int]] = []
    for ti, t in enumerate(targets):
        if t.recompute and t.shard_len > 0:
            units.extend((ti, off)
                         for off in range(0, t.shard_len, chunk))

    backend = "host-crc32c"
    batches = 0
    b = 0
    depth = _inflight()
    pool_before = pool_after = None
    if units:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..ops.device_pool import get_pool
        from ..parallel.mesh import make_ec_mesh, make_parity_step

        if mesh is None:
            mesh = make_ec_mesh()
        n_data, n_block = mesh.devices.shape
        width = chunk // 4
        if width % n_block:
            mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
            n_data, n_block = mesh.devices.shape
        if batch_units is None:
            # ~32 MB of data spans per dispatch: at the default 1 MB
            # span this packs 3 volumes' spans into one geometry, the
            # cross-volume batching that amortizes the compiled step
            batch_units = max(1, (32 << 20) // (DATA_SHARDS_COUNT * chunk))
        b = min(batch_units, len(units))
        b = max(n_data, ((b + n_data - 1) // n_data) * n_data)
        step = make_parity_step(mesh)
        backend = "device-pooled-swar"
        pool = get_pool()
        single = mesh.devices.size == 1
        dev0 = mesh.devices.flat[0]
        dev_label = (str(dev0) if single
                     else f"sharded:{mesh.devices.size}")
        sharding_kb = NamedSharding(mesh, P(None, "data", "block"))
        zero_copy = single and dev0 == jax.devices("cpu")[0]
        pool_before = pool.snapshot()

        oshape = (PARITY_SHARDS_COUNT, b, width)

        def _out_factory():
            z = np.zeros(oshape, dtype=np.int32)
            return jax.device_put(z, dev0 if single else sharding_kb)

        okey = ("maint-out", mesh, oshape)
        out_leases = [pool.lease(okey, _out_factory,
                                 PARITY_SHARDS_COUNT * b * chunk,
                                 device=dev_label)
                      for _ in range(depth + 1)]
        out_ring = deque(out_leases)
        # staging ring: a buffer is refilled only after its batch has
        # been synchronized (dlpack aliases it as the device input)
        staging = [np.zeros((DATA_SHARDS_COUNT, b, chunk), dtype=np.uint8)
                   for _ in range(depth + 2)]
        free_bufs = deque(staging)
        pending: deque = deque()  # (out_lease, buf, metas, t_disp)

        def _complete():
            out, buf, metas, t_disp = pending.popleft()
            t0 = time.perf_counter()
            parity = np.asarray(out.payload)  # blocks until ready
            pool.note_d2h(parity.nbytes, device=dev_label)
            pbytes = parity.view(np.uint8).reshape(
                PARITY_SHARDS_COUNT, b, chunk)
            for k, (ti, off) in enumerate(metas):
                t = targets[ti]
                if not t.recompute:
                    continue  # went unreadable mid-sweep: chain invalid
                for j in range(PARITY_SHARDS_COUNT):
                    psize = t.sizes[DATA_SHARDS_COUNT + j]
                    if psize < 0:
                        psize = t.shard_len
                    real = min(chunk, max(0, psize - off))
                    if real > 0:
                        t.computed[j] = crc_host.crc32c(
                            pbytes[j, k, :real], t.computed[j])
            out_ring.append(out)
            free_bufs.append(buf)
            timers["encode_crc"] += time.perf_counter() - t0

        try:
            for start in range(0, len(units), b):
                metas = units[start:start + b]
                if len(pending) >= depth:
                    _complete()
                buf = free_bufs.popleft()
                t0 = time.perf_counter()
                buf.fill(0)
                for k, (ti, off) in enumerate(metas):
                    t = targets[ti]
                    for i in range(DATA_SHARDS_COUNT):
                        raw = _read_span(t, i, off, chunk, throttle)
                        if raw and t.recompute:
                            buf[i, k, :len(raw)] = np.frombuffer(
                                raw, dtype=np.uint8)
                    # parity spans ride along for the plain file-CRC
                    # chain (bitrot in a parity file is still bitrot)
                    for j in range(PARITY_SHARDS_COUNT):
                        _read_span(t, DATA_SHARDS_COUNT + j, off,
                                   chunk, throttle)
                t1 = time.perf_counter()
                timers["read"] += t1 - t0
                # background device lane: yield to in-flight foreground
                # (degraded-read recover) decodes before dispatching
                timers["lane_wait"] = timers.get("lane_wait", 0.0) \
                    + _lanes.LANES.background_checkpoint()
                words = buf.view(np.int32)
                if zero_copy:
                    din = jax.dlpack.from_dlpack(words)
                else:
                    din = jax.device_put(
                        words, dev0 if single else sharding_kb)
                    pool.note_h2d(words.nbytes, device=dev_label)
                out = out_ring.popleft()
                # donation swap: the step aliases its result into the
                # leased slot; the old handle is dead
                out.payload = step(din, out.payload)
                timers["dispatch"] += time.perf_counter() - t1
                pending.append((out, buf, metas, t1))
                batches += 1
            while pending:
                _complete()
        finally:
            for ls in out_leases:
                pool.release(ls)
        pool_after = pool.snapshot()

    # file-CRC-only sweep for targets with no recompute units
    t0 = time.perf_counter()
    for t in targets:
        if t.recompute and t.shard_len > 0:
            continue
        for sid in range(TOTAL_SHARDS_COUNT):
            off = 0
            while t.sizes[sid] >= 0 and off < t.sizes[sid]:
                raw = _read_span(t, sid, off, chunk, throttle)
                if not raw:
                    break
                off += len(raw)
    timers["read"] += time.perf_counter() - t0

    volumes = []
    for t in targets:
        volumes.append(_verdict(t))
        if t.close is not None:
            t.close()
    wall = time.perf_counter() - wall0
    if stage_stats is not None:
        stage_stats.update({k: round(v, 3) for k, v in timers.items()})
        stage_stats["wall"] = round(wall, 3)
        stage_stats["backend"] = backend
        stage_stats["batches"] = batches
        stage_stats["batch_units"] = b
        stage_stats["k_shapes"] = [DATA_SHARDS_COUNT] if units else []
        stage_stats["inflight"] = depth
        stage_stats["span_bytes"] = chunk
        for k in ("read", "dispatch", "encode_crc"):
            stage_stats[f"{k}_frac"] = (
                round(timers[k] / wall, 3) if wall > 0 else 0.0)
        if pool_before is not None and pool_after is not None:
            stage_stats["pool"] = {
                "allocs": pool_after.get("allocs", 0),
                "lease_hits": (pool_after.get("lease_hits", 0)
                               - pool_before.get("lease_hits", 0))}
    total = sum(v["bytes"] for v in volumes)
    from ..stats import metrics
    metrics.MaintScrubbedBytesCounter.inc(total)
    # a parity record that disagrees with the recompute is corruption
    # too (either the parity file or the record) — surface both kinds
    return {"volumes": volumes, "scrubbed_bytes": total,
            "corrupt": [{"volume": v["volume"],
                         "shards": sorted(set(v["corrupt"])
                                          | set(v["parity_mismatch"]))}
                        for v in volumes
                        if v["corrupt"] or v["parity_mismatch"]],
            "backend": backend}


def deep_scrub_host(directory: str, collection: str, vid: int,
                    throttle=None, needle_walk: bool = True) -> dict:
    """Host fallback: chunked+paced whole-file CRC verification plus a
    needle-level walk — every live needle in the sorted .ecx is
    re-read and its own CRC verified (Needle.read_bytes raises on
    mismatch), catching corruption the whole-file CRC localises only
    to a shard, at needle granularity."""
    from ..storage import types as t
    from ..storage.erasure_coding.ec_volume import EcVolume, EcVolumeShard
    from ..storage.erasure_coding.encoder import load_volume_info
    from ..storage.tools import verify_shard_files

    base = (os.path.join(directory, f"{collection}_{vid}") if collection
            else os.path.join(directory, str(vid)))
    if os.path.exists(base + ".scl"):
        # inline EC volume: shard logs have no whole-file CRC record;
        # the audit recomputes every committed stripe's parity + CRC
        # against the commit log and re-reads every live needle
        from ..storage.erasure_coding.inline import verify_inline_volume

        return verify_inline_volume(directory, collection, vid)
    info = load_volume_info(base) or {}
    stored = info.get("shard_crc32c")
    clean, corrupt, absent = verify_shard_files(base, stored,
                                                throttle=throttle)
    checked = bad = 0
    bad_needles: list[int] = []
    if needle_walk and os.path.exists(base + ".ecx"):
        ev = EcVolume(directory, collection, vid)
        try:
            for sid in range(TOTAL_SHARDS_COUNT):
                if os.path.exists(base + to_ext(sid)):
                    ev.add_shard(EcVolumeShard(directory, collection,
                                               vid, sid))
            n_entries = ev.ecx_file_size // t.NEEDLE_MAP_ENTRY_SIZE
            for pos in range(n_entries):
                nid, _, size = ev._read_ecx_entry(pos)
                if t.size_is_deleted(size):
                    continue
                checked += 1
                try:
                    ev.read_needle(nid)
                except Exception:
                    bad += 1
                    if len(bad_needles) < 64:
                        bad_needles.append(nid)
        finally:
            ev.close()
    return {"volume": vid, "collection": collection,
            "clean": clean, "corrupt": corrupt, "missing": absent,
            "needles_checked": checked, "needles_bad": bad,
            "bad_needles": bad_needles,
            "ok": not (corrupt or bad)}
