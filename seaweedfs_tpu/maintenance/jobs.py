"""Typed maintenance jobs (the curator's unit of work).

Each job targets one volume (or the whole cluster for the global
types) and carries a small params dict the executor interprets.  Jobs
are deduped by (type, volume, collection) while live, so a detector
firing every scan cannot flood the queue — at most one live job per
target exists at a time (single-flight per volume)."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

# Job types in repair-urgency order: a lost/corrupt EC shard burns
# durability margin, so it outranks replica fixes, which outrank
# space reclaim, which outranks the background integrity sweep and
# cosmetic placement moves.  Elasticity jobs (the autoscaler's
# scale.up / scale.drain) come last: capacity changes are never more
# urgent than durability repairs.
TYPE_EC_REBUILD = "ec.rebuild"
TYPE_FIX_REPLICATION = "fix.replication"
TYPE_VACUUM = "vacuum"
TYPE_DEEP_SCRUB = "deep.scrub"
TYPE_BALANCE = "balance"
TYPE_SCALE_UP = "scale.up"
TYPE_SCALE_DRAIN = "scale.drain"
# filer shard-count elasticity: handled by the curator proposing
# filer.resize through raft directly, never enqueued as worker jobs
TYPE_SHARD_SPLIT = "filer.shard_split"
TYPE_SHARD_MERGE = "filer.shard_merge"
# advisory placement hint from the temperature detector: this volume
# is cold enough for the remote tier (storage/tier.py); least urgent
# of all — moving cold data is never time-critical
TYPE_TIER_MOVE = "tier.move"

PRIORITIES = {
    TYPE_EC_REBUILD: 0,
    TYPE_FIX_REPLICATION: 1,
    TYPE_VACUUM: 2,
    TYPE_DEEP_SCRUB: 3,
    TYPE_BALANCE: 4,
    TYPE_SCALE_UP: 5,
    TYPE_SCALE_DRAIN: 6,
    TYPE_TIER_MOVE: 7,
}
JOB_TYPES = tuple(PRIORITIES)

# job lifecycle states
PENDING = "pending"
LEASED = "leased"
DONE = "done"


@dataclass
class Job:
    id: str
    type: str
    volume: int = 0            # 0 for cluster-global jobs
    collection: str = ""
    params: dict = field(default_factory=dict)
    priority: int = 0
    state: str = PENDING
    created_at: float = 0.0
    not_before: float = 0.0    # retry backoff gate
    attempts: int = 0
    worker: str = ""
    lease_expires: float = 0.0
    last_error: str = ""
    outcome: str = ""

    @property
    def key(self) -> tuple:
        return (self.type, self.volume, self.collection)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Job":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})
