"""Curator detectors: topology state -> maintenance job specs.

`snapshot()` flattens the leader's live Topology (under its lock) into
a plain dict; `scan()` is a pure function over that dict, so detector
behaviour is unit-testable with fabricated snapshots and the detector
pass itself never blocks on the topology lock or the network (the old
auto-vacuum synchronously called every volume server from the reap
loop — the curator only *reads heartbeat state* here and defers the
actual RPCs to the worker executing the job)."""

from __future__ import annotations

import os
from typing import Optional

from ..storage.erasure_coding import TOTAL_SHARDS_COUNT
from .jobs import (TYPE_BALANCE, TYPE_DEEP_SCRUB, TYPE_EC_REBUILD,
                   TYPE_FIX_REPLICATION, TYPE_SCALE_DRAIN,
                   TYPE_SCALE_UP, TYPE_SHARD_MERGE, TYPE_SHARD_SPLIT,
                   TYPE_TIER_MOVE, TYPE_VACUUM)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def snapshot(topo) -> dict:
    """Flatten a master Topology into the dict `scan()` consumes."""
    volumes: dict[int, dict] = {}
    node_ec: dict[str, int] = {}
    node_volumes: dict[str, int] = {}
    nodes: list[dict] = []
    with topo.lock:
        for dc in topo.dcs.values():
            for rack in dc.racks.values():
                for node in rack.nodes.values():
                    node_ec[node.url] = sum(
                        b.count() for b in node.ec_shards.values())
                    node_volumes[node.url] = len(node.volumes)
                    tele = getattr(node, "telemetry", None) or {}
                    nodes.append({
                        "url": node.url,
                        "volumes": len(node.volumes),
                        "ec_shards": node_ec[node.url],
                        "occupancy": float(tele.get("occupancy", 0.0)),
                        "rps": float(tele.get("rps", 0.0)),
                        "mbps": float(tele.get("mbps", 0.0)),
                        "draining": bool(tele.get("draining", False)),
                        "free": max(0, node.max_volume_count
                                    - len(node.volumes)),
                    })
                    for v in node.volumes.values():
                        agg = volumes.setdefault(v.id, {
                            "id": v.id, "collection": v.collection,
                            "size": 0, "deleted_bytes": 0,
                            "replication": v.replica_placement,
                            "replicas": 0, "read_only": False})
                        agg["replicas"] += 1
                        agg["size"] = max(agg["size"], v.size)
                        agg["deleted_bytes"] = max(
                            agg["deleted_bytes"], v.deleted_byte_count)
                        agg["read_only"] = (agg["read_only"]
                                            or v.read_only)
        ec = [{"id": vid,
               "collection": topo.ec_collections.get(vid, ""),
               "shards": sorted(sid for sid, nodes in shard_map.items()
                                if nodes)}
              for vid, shard_map in topo.ec_shard_map.items()]
    return {"volumes": sorted(volumes.values(), key=lambda v: v["id"]),
            "ec": sorted(ec, key=lambda e: e["id"]),
            "node_ec_shards": node_ec,
            "node_volumes": node_volumes,
            "nodes": sorted(nodes, key=lambda n: n["url"])}


def scan(snap: dict, now: float, last_scrub: dict,
         garbage_threshold: float = 0.3,
         scrub_interval: Optional[float] = None,
         balance_skew: Optional[int] = None,
         vacuum_enabled: bool = True,
         scale_enabled: Optional[bool] = None,
         scale_up_occ: Optional[float] = None,
         scale_drain_occ: Optional[float] = None,
         scale_min_nodes: Optional[int] = None,
         alerts: Optional[list] = None) -> list[dict]:
    """All detectors over one snapshot -> job specs
    ({type, volume, collection, params}), urgent first."""
    if scrub_interval is None:
        scrub_interval = _env_float("WEED_MAINT_SCRUB_INTERVAL", 86400.0)
    if balance_skew is None:
        balance_skew = int(_env_float("WEED_MAINT_BALANCE_SKEW", 4))
    specs: list[dict] = []

    # missing-or-lost EC shards -> rebuild (most urgent: every missing
    # shard is erasure-budget already spent)
    for e in snap.get("ec", []):
        have = set(e["shards"])
        if have and len(have) < TOTAL_SHARDS_COUNT:
            missing = sorted(set(range(TOTAL_SHARDS_COUNT)) - have)
            specs.append({"type": TYPE_EC_REBUILD, "volume": e["id"],
                          "collection": e["collection"],
                          "params": {"missing": missing}})

    # replica count below placement -> one cluster-wide fix pass
    from ..storage.super_block import ReplicaPlacement

    under = []
    for v in snap.get("volumes", []):
        want = ReplicaPlacement.from_byte(v.get("replication", 0) or 0) \
            .copy_count()
        if v["replicas"] < want:
            under.append(v["id"])
    if under:
        specs.append({"type": TYPE_FIX_REPLICATION, "volume": 0,
                      "collection": "",
                      "params": {"volumes": sorted(under)}})

    # garbage ratio over threshold -> vacuum (replaces the master's
    # in-reap-loop auto-vacuum pass)
    if vacuum_enabled:
        for v in snap.get("volumes", []):
            size = v.get("size", 0)
            if size <= 0 or v.get("read_only"):
                continue
            ratio = v.get("deleted_bytes", 0) / float(size)
            if ratio > garbage_threshold:
                specs.append({"type": TYPE_VACUUM, "volume": v["id"],
                              "collection": v["collection"],
                              "params": {"garbage_ratio":
                                         round(ratio, 4)}})

    # stale scrub -> deep scrub (never-scrubbed volumes are due
    # immediately; the queue's dedupe + the pacer bound the sweep)
    for e in snap.get("ec", []):
        if len(e["shards"]) < TOTAL_SHARDS_COUNT:
            continue  # rebuild first; scrub after it converges
        if now - last_scrub.get(e["id"], 0.0) >= scrub_interval:
            specs.append({"type": TYPE_DEEP_SCRUB, "volume": e["id"],
                          "collection": e["collection"], "params": {}})

    # placement skew -> balance.  Both populations count: EC
    # shard-count spread AND plain-volume count spread (the original
    # detector only watched EC shards, so a cluster whose plain
    # volumes all landed on one server never rebalanced).
    kinds = []
    skew = 0
    ec_counts = list(snap.get("node_ec_shards", {}).values())
    if len(ec_counts) >= 2:
        ec_skew = max(ec_counts) - min(ec_counts)
        if ec_skew > balance_skew:
            kinds.append("ec")
            skew = max(skew, ec_skew)
    vol_counts = list(snap.get("node_volumes", {}).values())
    if len(vol_counts) >= 2:
        vol_skew = max(vol_counts) - min(vol_counts)
        if vol_skew > balance_skew:
            kinds.append("volume")
            skew = max(skew, vol_skew)
    if kinds:
        specs.append({"type": TYPE_BALANCE, "volume": 0,
                      "collection": "",
                      "params": {"skew": skew,
                                 "kinds": sorted(kinds)}})

    specs.extend(scan_scale(snap, scale_enabled=scale_enabled,
                            scale_up_occ=scale_up_occ,
                            scale_drain_occ=scale_drain_occ,
                            scale_min_nodes=scale_min_nodes,
                            alerts=alerts))
    return specs


def scan_scale(snap: dict, scale_enabled: Optional[bool] = None,
               scale_up_occ: Optional[float] = None,
               scale_drain_occ: Optional[float] = None,
               scale_min_nodes: Optional[int] = None,
               scale_up_rps: Optional[float] = None,
               scale_drain_rps: Optional[float] = None,
               alerts: Optional[list] = None,
               scale_on_alert: Optional[bool] = None) -> list[dict]:
    """Autoscaler detectors over per-node telemetry.

    Opt-in via WEED_SCALE=1 (capacity changes must never surprise a
    cluster that didn't ask for them).  Scale UP when either pressure
    signal trips fleet-wide: peak admission-gate occupancy above
    WEED_SCALE_UP_OCC (clients queueing), or mean per-node rps above
    WEED_SCALE_UP_RPS (0 disables the rps trigger).  Scale DOWN when
    every node idles below WEED_SCALE_DRAIN_OCC *and* mean rps is
    under WEED_SCALE_DRAIN_RPS, with spare nodes beyond
    WEED_SCALE_MIN_NODES -> drain the emptiest server (fewest
    volumes + shards, so the evacuation moves the least data)."""
    if scale_enabled is None:
        scale_enabled = os.environ.get("WEED_SCALE", "0") not in (
            "0", "", "false", "no")
    if not scale_enabled:
        return []
    if scale_up_occ is None:
        scale_up_occ = _env_float("WEED_SCALE_UP_OCC", 0.75)
    if scale_drain_occ is None:
        scale_drain_occ = _env_float("WEED_SCALE_DRAIN_OCC", 0.15)
    if scale_min_nodes is None:
        scale_min_nodes = int(_env_float("WEED_SCALE_MIN_NODES", 1))
    if scale_up_rps is None:
        scale_up_rps = _env_float("WEED_SCALE_UP_RPS", 0.0)
    if scale_drain_rps is None:
        scale_drain_rps = _env_float("WEED_SCALE_DRAIN_RPS", 1.0)
    if scale_on_alert is None:
        scale_on_alert = os.environ.get("WEED_SCALE_ON_ALERT", "0") \
            not in ("0", "", "false", "no")
    nodes = [n for n in snap.get("nodes", []) if not n["draining"]]
    if not nodes:
        return []
    # opt-in SLO trigger: a firing burn-rate alert (health plane) means
    # the error budget is being spent NOW — add capacity without
    # waiting for occupancy to cross its threshold
    if scale_on_alert and alerts:
        return [{"type": TYPE_SCALE_UP, "volume": 0, "collection": "",
                 "params": {"reason": "slo.alert",
                            "alerts": sorted(alerts),
                            "nodes": len(nodes)}}]
    occs = [n["occupancy"] for n in nodes]
    mean_occ = sum(occs) / len(occs)
    mean_rps = sum(n["rps"] for n in nodes) / len(nodes)
    if mean_occ > scale_up_occ \
            or (scale_up_rps > 0 and mean_rps > scale_up_rps):
        return [{"type": TYPE_SCALE_UP, "volume": 0, "collection": "",
                 "params": {"occupancy": round(mean_occ, 4),
                            "rps": round(mean_rps, 1),
                            "nodes": len(nodes)}}]
    if len(nodes) > scale_min_nodes and max(occs) < scale_drain_occ \
            and mean_rps < scale_drain_rps:
        victim = min(nodes, key=lambda n: (n["volumes"] + n["ec_shards"],
                                           n["url"]))
        return [{"type": TYPE_SCALE_DRAIN, "volume": 0,
                 "collection": "",
                 "params": {"server": victim["url"],
                            "occupancy": round(max(occs), 4),
                            "rps": round(mean_rps, 1)}}]
    return []


def heat_tier_enabled() -> bool:
    return os.environ.get("WEED_HEAT_TIER", "0") not in (
        "0", "", "false", "no")


def scan_temperature(snap: dict, usage: Optional[dict],
                     enabled: Optional[bool] = None,
                     cold_reads: Optional[float] = None,
                     max_hints: Optional[int] = None) -> list[dict]:
    """Heat-driven placement hints over the leader's merged usage view.

    Opt-in via WEED_HEAT_TIER=1 (placement advice must never surprise
    a cluster that didn't ask for it).  A volume whose decay-weighted
    read count in the fleet sketch sits below WEED_HEAT_TIER_COLD_READS
    while holding live data is *cold*: emit an advisory ``tier.move``
    spec pointing at storage/tier.py's remote backends.  The decayed
    sketch means a volume hot last week but idle now qualifies —
    exactly the temperature signal ROADMAP item 3's cold-tier work
    needs.  At most WEED_HEAT_TIER_MAX_HINTS hints per scan (coldest
    first) so a freshly-enabled detector cannot flood the queue."""
    if enabled is None:
        enabled = heat_tier_enabled()
    if not enabled or not usage:
        return []
    if cold_reads is None:
        cold_reads = _env_float("WEED_HEAT_TIER_COLD_READS", 1.0)
    if max_hints is None:
        max_hints = int(_env_float("WEED_HEAT_TIER_MAX_HINTS", 4))
    vol_reads = {str(k): float(v)
                 for k, v in (usage.get("volumes") or {}).items()}
    total_reads = float(usage.get("totals", {}).get("reads", 0) or 0)
    if total_reads <= 0:
        return []   # no traffic at all means no temperature signal
    cold = []
    for v in snap.get("volumes", []):
        if v.get("size", 0) <= 0:
            continue   # nothing to move
        reads = vol_reads.get(str(v["id"]), 0.0)
        if reads < cold_reads:
            cold.append((reads, v))
    cold.sort(key=lambda rv: (rv[0], rv[1]["id"]))
    return [{"type": TYPE_TIER_MOVE, "volume": v["id"],
             "collection": v["collection"],
             "params": {"reads": round(reads, 3),
                        "fleet_reads": round(total_reads, 1),
                        "advisory": True, "dest": "cold"}}
            for reads, v in cold[:max(0, max_hints)]]


def scan_shard_scale(shards: dict,
                     enabled: Optional[bool] = None,
                     split_per_holder: Optional[float] = None,
                     merge_per_holder: Optional[float] = None
                     ) -> list[dict]:
    """Filer shard-count elasticity over the replicated shard map.

    Opt-in via WEED_SHARD_SCALE=1.  `shards` is the curator's view:
    {"slots": N, "holders": active store servers, "resize": in-flight}.
    SPLIT when holders outgrow the slot space (fewer than
    WEED_SHARD_SPLIT_PER_HOLDER slots per holder means joiners sit
    idle) — to the smallest doubling that restores the floor.  MERGE
    one halving at a time when the space is far too fine
    (more than WEED_SHARD_MERGE_PER_HOLDER slots per holder), so a
    shrunk fleet stops paying per-slot lease/handover overhead.  The
    doubling/halving rule keeps old and new counts divisible, which is
    what makes holders' re-sharding purely local."""
    if enabled is None:
        enabled = os.environ.get("WEED_SHARD_SCALE", "0") not in (
            "0", "", "false", "no")
    if not enabled or shards.get("resize"):
        return []
    slots = int(shards.get("slots", 0))
    holders = int(shards.get("holders", 0))
    if slots <= 0 or holders <= 0:
        return []
    if split_per_holder is None:
        split_per_holder = _env_float("WEED_SHARD_SPLIT_PER_HOLDER", 1.0)
    if merge_per_holder is None:
        merge_per_holder = _env_float("WEED_SHARD_MERGE_PER_HOLDER",
                                      16.0)
    if split_per_holder > 0 and slots < holders * split_per_holder:
        to = slots
        while to < holders * split_per_holder:
            to *= 2
        return [{"type": TYPE_SHARD_SPLIT, "volume": 0, "collection": "",
                 "params": {"from": slots, "to": to,
                            "holders": holders}}]
    if merge_per_holder > 0 and slots % 2 == 0 \
            and slots > holders * merge_per_holder:
        return [{"type": TYPE_SHARD_MERGE, "volume": 0, "collection": "",
                 "params": {"from": slots, "to": slots // 2,
                            "holders": holders}}]
    return []
