"""Curator detectors: topology state -> maintenance job specs.

`snapshot()` flattens the leader's live Topology (under its lock) into
a plain dict; `scan()` is a pure function over that dict, so detector
behaviour is unit-testable with fabricated snapshots and the detector
pass itself never blocks on the topology lock or the network (the old
auto-vacuum synchronously called every volume server from the reap
loop — the curator only *reads heartbeat state* here and defers the
actual RPCs to the worker executing the job)."""

from __future__ import annotations

import os
from typing import Optional

from ..storage.erasure_coding import TOTAL_SHARDS_COUNT
from .jobs import (TYPE_BALANCE, TYPE_DEEP_SCRUB, TYPE_EC_REBUILD,
                   TYPE_FIX_REPLICATION, TYPE_VACUUM)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def snapshot(topo) -> dict:
    """Flatten a master Topology into the dict `scan()` consumes."""
    volumes: dict[int, dict] = {}
    node_ec: dict[str, int] = {}
    with topo.lock:
        for dc in topo.dcs.values():
            for rack in dc.racks.values():
                for node in rack.nodes.values():
                    node_ec[node.url] = sum(
                        b.count() for b in node.ec_shards.values())
                    for v in node.volumes.values():
                        agg = volumes.setdefault(v.id, {
                            "id": v.id, "collection": v.collection,
                            "size": 0, "deleted_bytes": 0,
                            "replication": v.replica_placement,
                            "replicas": 0, "read_only": False})
                        agg["replicas"] += 1
                        agg["size"] = max(agg["size"], v.size)
                        agg["deleted_bytes"] = max(
                            agg["deleted_bytes"], v.deleted_byte_count)
                        agg["read_only"] = (agg["read_only"]
                                            or v.read_only)
        ec = [{"id": vid,
               "collection": topo.ec_collections.get(vid, ""),
               "shards": sorted(sid for sid, nodes in shard_map.items()
                                if nodes)}
              for vid, shard_map in topo.ec_shard_map.items()]
    return {"volumes": sorted(volumes.values(), key=lambda v: v["id"]),
            "ec": sorted(ec, key=lambda e: e["id"]),
            "node_ec_shards": node_ec}


def scan(snap: dict, now: float, last_scrub: dict,
         garbage_threshold: float = 0.3,
         scrub_interval: Optional[float] = None,
         balance_skew: Optional[int] = None,
         vacuum_enabled: bool = True) -> list[dict]:
    """All detectors over one snapshot -> job specs
    ({type, volume, collection, params}), urgent first."""
    if scrub_interval is None:
        scrub_interval = _env_float("WEED_MAINT_SCRUB_INTERVAL", 86400.0)
    if balance_skew is None:
        balance_skew = int(_env_float("WEED_MAINT_BALANCE_SKEW", 4))
    specs: list[dict] = []

    # missing-or-lost EC shards -> rebuild (most urgent: every missing
    # shard is erasure-budget already spent)
    for e in snap.get("ec", []):
        have = set(e["shards"])
        if have and len(have) < TOTAL_SHARDS_COUNT:
            missing = sorted(set(range(TOTAL_SHARDS_COUNT)) - have)
            specs.append({"type": TYPE_EC_REBUILD, "volume": e["id"],
                          "collection": e["collection"],
                          "params": {"missing": missing}})

    # replica count below placement -> one cluster-wide fix pass
    from ..storage.super_block import ReplicaPlacement

    under = []
    for v in snap.get("volumes", []):
        want = ReplicaPlacement.from_byte(v.get("replication", 0) or 0) \
            .copy_count()
        if v["replicas"] < want:
            under.append(v["id"])
    if under:
        specs.append({"type": TYPE_FIX_REPLICATION, "volume": 0,
                      "collection": "",
                      "params": {"volumes": sorted(under)}})

    # garbage ratio over threshold -> vacuum (replaces the master's
    # in-reap-loop auto-vacuum pass)
    if vacuum_enabled:
        for v in snap.get("volumes", []):
            size = v.get("size", 0)
            if size <= 0 or v.get("read_only"):
                continue
            ratio = v.get("deleted_bytes", 0) / float(size)
            if ratio > garbage_threshold:
                specs.append({"type": TYPE_VACUUM, "volume": v["id"],
                              "collection": v["collection"],
                              "params": {"garbage_ratio":
                                         round(ratio, 4)}})

    # stale scrub -> deep scrub (never-scrubbed volumes are due
    # immediately; the queue's dedupe + the pacer bound the sweep)
    for e in snap.get("ec", []):
        if len(e["shards"]) < TOTAL_SHARDS_COUNT:
            continue  # rebuild first; scrub after it converges
        if now - last_scrub.get(e["id"], 0.0) >= scrub_interval:
            specs.append({"type": TYPE_DEEP_SCRUB, "volume": e["id"],
                          "collection": e["collection"], "params": {}})

    # EC placement skew -> balance
    counts = list(snap.get("node_ec_shards", {}).values())
    if len(counts) >= 2 and max(counts) - min(counts) > balance_skew:
        specs.append({"type": TYPE_BALANCE, "volume": 0,
                      "collection": "",
                      "params": {"skew": max(counts) - min(counts)}})
    return specs
