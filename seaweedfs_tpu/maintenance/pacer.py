"""Byte-rate pacer for background maintenance I/O.

A token bucket that debits every chunk a maintenance worker reads (or
fetches from a peer) and sleeps once the bucket runs dry — so a deep
scrub or vacuum never streams faster than the configured rate.  The
effective rate additionally backs off against *foreground* load: the
volume server wires `load_fn` to its request shedder (in-flight /
limit), so a busy front end squeezes maintenance down to a floor
fraction instead of competing with user reads.

`throttle(nbytes)` is the hook `storage.tools.shard_file_crc32c` and
`verify_shard_files` accept, and what the deep-scrub reader calls per
span — one signature everywhere."""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..stats import metrics


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class BytePacer:
    """Token-bucket byte-rate limiter with foreground-load backoff."""

    def __init__(self, rate_bytes: Optional[float] = None,
                 load_fn: Optional[Callable[[], float]] = None,
                 floor_frac: Optional[float] = None,
                 burst_seconds: float = 0.25):
        self._rate_bytes = rate_bytes
        self.load_fn = load_fn
        self._floor_frac = floor_frac
        self.burst_seconds = burst_seconds
        self._lock = threading.Lock()
        self._bucket = 0.0
        self._last = None  # lazily initialised on first throttle
        self.throttled_seconds = 0.0
        self.paced_bytes = 0
        # injectable for fake-clock tests (rpc.policy convention)
        self.sleep = time.sleep
        self.now = time.monotonic

    def base_rate(self) -> float:
        """Configured ceiling, bytes/second (WEED_MAINT_RATE_MB)."""
        if self._rate_bytes is not None:
            return float(self._rate_bytes)
        return _env_float("WEED_MAINT_RATE_MB", 32.0) * (1 << 20)

    def floor_frac(self) -> float:
        if self._floor_frac is not None:
            return float(self._floor_frac)
        return _env_float("WEED_MAINT_FLOOR", 0.1)

    def effective_rate(self) -> float:
        """Ceiling scaled down by foreground load (0..1), never below
        the floor fraction — maintenance always makes *some* progress
        so repairs cannot be starved forever."""
        rate = self.base_rate()
        if self.load_fn is not None:
            try:
                load = min(1.0, max(0.0, float(self.load_fn())))
            except Exception:
                load = 0.0
            rate *= max(self.floor_frac(), 1.0 - load)
        return max(1.0, rate)

    def throttle(self, nbytes: int):
        """Debit `nbytes`; sleep whatever the bucket cannot cover."""
        if nbytes <= 0:
            return
        rate = self.effective_rate()
        with self._lock:
            now = self.now()
            if self._last is None:
                self._last = now
                self._bucket = rate * self.burst_seconds
            self._bucket = min(rate * self.burst_seconds,
                               self._bucket + (now - self._last) * rate)
            self._last = now
            self._bucket -= nbytes
            debt = -self._bucket
            self.paced_bytes += nbytes
        metrics.MaintPacerRateGauge.set(rate)
        if debt > 0:
            delay = debt / rate
            self.throttled_seconds += delay
            self.sleep(delay)

    def snapshot(self) -> dict:
        return {"rate": round(self.effective_rate()),
                "base_rate": round(self.base_rate()),
                "paced_bytes": self.paced_bytes,
                "throttled_seconds": round(self.throttled_seconds, 3)}
