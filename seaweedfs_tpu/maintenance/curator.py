"""The curator: leader-resident continuous maintenance scheduler.

Runs next to the master's topology: a detector pass every
WEED_MAINT_INTERVAL seconds (leader only) snapshots heartbeat state,
turns anomalies into typed jobs, and feeds the persistent deduped
priority queue.  Volume servers lease jobs over /maintenance/lease,
renew while executing, and report complete/fail; a worker that dies
mid-job simply stops renewing and the lease expiry requeues the work.

The curator also owns the last-deep-scrub clock per EC volume (the
heartbeats carry no scrub timestamps) and converts deep-scrub findings
into rebuild jobs — detect once, repair automatically."""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..util import glog
from . import detectors
from .jobs import (JOB_TYPES, LEASED, TYPE_SHARD_SPLIT,
                   TYPE_BALANCE, TYPE_DEEP_SCRUB,
                   TYPE_EC_REBUILD, TYPE_SCALE_DRAIN, TYPE_SCALE_UP,
                   TYPE_TIER_MOVE, Job)
from .queue import JobQueue


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class RaftQueueProxy:
    """JobQueue facade that commits every mutation through the raft log
    before acknowledging it.  Reads come straight from the local FSM's
    queue (each replica applies the same committed commands, so the view
    is the replicated truth); writes become `curator.*` commands whose
    knob-derived inputs (lease duration, attempt cap, backoff) are
    pinned by THIS proposer, keeping the apply deterministic across
    replicas with drifted env config.

    On a follower, every mutation raises the raft 409 with a leader
    hint — exactly what /maintenance/* should return there."""

    def __init__(self, raft):
        self.raft = raft
        self.now = time.time  # fake-clock seam, mirrors JobQueue

    @property
    def _q(self) -> JobQueue:
        return self.raft.fsm.queue

    # -- replicated mutations -------------------------------------------------
    def enqueue(self, type_: str, volume: int = 0, collection: str = "",
                params: Optional[dict] = None,
                priority: Optional[int] = None) -> Optional[str]:
        return self.raft.propose({
            "type": "curator.enqueue", "now": self.now(),
            "job_type": type_, "volume": int(volume),
            "collection": collection, "params": dict(params or {}),
            "priority": priority})

    def lease(self, worker: str, types: Optional[list] = None,
              limit: int = 1,
              ec_volumes: Optional[list] = None) -> list[dict]:
        return self.raft.propose({
            "type": "curator.lease", "now": self.now(),
            "worker": worker, "types": types, "limit": int(limit),
            "ec_volumes": ec_volumes,
            "lease_seconds": self.lease_seconds}) or []

    def renew(self, job_id: str, worker: str) -> bool:
        return bool(self.raft.propose({
            "type": "curator.renew", "now": self.now(),
            "id": job_id, "worker": worker,
            "lease_seconds": self.lease_seconds}))

    def complete(self, job_id: str, worker: str,
                 outcome: str = "ok") -> Optional[Job]:
        d = self.raft.propose({
            "type": "curator.done", "now": self.now(),
            "id": job_id, "worker": worker, "outcome": outcome})
        return Job.from_dict(d) if d else None

    def fail(self, job_id: str, worker: str, error: str) -> Optional[Job]:
        d = self.raft.propose({
            "type": "curator.fail", "now": self.now(),
            "id": job_id, "worker": worker, "error": str(error),
            "max_attempts": self._q.max_attempts,
            "backoff": self._q.retry_backoff})
        return Job.from_dict(d) if d else None

    def expire_leases(self) -> list[str]:
        # probe locally first: proposing an expire command on every tick
        # would grow the log with no-ops, so only pay a quorum round when
        # some lease has actually lapsed
        now = self.now()
        q = self._q
        with q._lock:
            any_expired = any(
                j.state == LEASED and j.lease_expires < now
                for j in q._jobs.values())
        if not any_expired:
            return []
        return self.raft.propose(
            {"type": "curator.expire", "now": now}) or []

    @property
    def paused(self) -> bool:
        return self._q.paused

    @paused.setter
    def paused(self, value: bool):
        self.raft.propose({"type": "curator.pause", "now": self.now(),
                           "paused": bool(value)})

    # -- read-through views ---------------------------------------------------
    @property
    def lease_seconds(self) -> float:
        return self._q.lease_seconds

    @property
    def history(self):
        return self._q.history

    def get(self, job_id: str) -> Optional[Job]:
        return self._q.get(job_id)

    def stats(self) -> dict:
        return self._q.stats()

    def jobs(self) -> list[dict]:
        return self._q.jobs()


class Curator:
    def __init__(self, master, journal_dir: str = "",
                 interval: Optional[float] = None):
        self.master = master
        self._interval = interval
        raft = getattr(master, "raft", None)
        if getattr(raft, "fsm", None) is not None \
                and hasattr(raft, "propose"):
            # the raft log IS the journal: a failed-over leader resumes
            # with the exact pending/leased set, committed before ack
            self.queue = RaftQueueProxy(raft)
        else:
            journal = (os.path.join(journal_dir, "maintenance.jlog")
                       if journal_dir else "")
            self.queue = JobQueue(journal_path=journal)
        self.last_scrub: dict[int, float] = {}
        self._recent: dict[tuple, float] = {}  # (type, vid) -> done at
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.enabled = os.environ.get("WEED_MAINT", "1") != "0"
        self.scans = 0
        self.enqueued = 0
        self.now = time.time  # fake-clock seam
        # health plane seam: returns the names of firing SLO alerts so
        # scan_scale() can use them as an opt-in scale-up trigger
        self.alerts_fn = None

    @property
    def interval(self) -> float:
        if self._interval is not None:
            return self._interval
        return _env_float("WEED_MAINT_INTERVAL", 30.0)

    def cooldown(self) -> float:
        return _env_float("WEED_MAINT_COOLDOWN", 60.0)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="curator", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval):
            if not self.master.raft.is_leader:
                continue
            try:
                self.tick()
            except Exception as e:  # detector bugs must not kill the loop
                glog.warning(f"curator tick failed: {e}")

    # -- one detector pass ---------------------------------------------------
    def tick(self) -> list[str]:
        """Expire dead-worker leases, scan topology, enqueue.  Returns
        the ids enqueued this pass (for /maintenance/run)."""
        self.queue.expire_leases()
        snap = detectors.snapshot(self.master.topo)
        now = self.now()
        vacuum_on = getattr(self.master, "auto_vacuum_interval", 0) > 0
        alerts = None
        if self.alerts_fn is not None:
            try:
                alerts = self.alerts_fn()
            except Exception:
                alerts = None
        specs = detectors.scan(
            snap, now=now, last_scrub=self.last_scrub,
            garbage_threshold=getattr(self.master, "garbage_threshold",
                                      0.3),
            vacuum_enabled=vacuum_on, alerts=alerts)
        if detectors.heat_tier_enabled():
            # heat-driven placement hints over the leader's merged
            # access-sketch view (stats/access.py UsageAggregator)
            usage = None
            health = getattr(self.master, "health", None)
            if health is not None:
                try:
                    usage = health.usage.usage()
                except Exception:
                    usage = None
            specs.extend(detectors.scan_temperature(snap, usage))
        self.scans += 1
        ids = []
        cooldown = self.cooldown()
        for spec in specs:
            done_at = self._recent.get((spec["type"], spec["volume"]), 0)
            if now - done_at < cooldown:
                continue  # just repaired; wait for heartbeats to settle
            jid = self.queue.enqueue(spec["type"], spec["volume"],
                                     spec["collection"], spec["params"])
            if jid is not None:
                ids.append(jid)
                self.enqueued += 1
                from ..stats import events as events_mod

                if spec["type"] in (TYPE_SCALE_UP, TYPE_SCALE_DRAIN):
                    from ..stats import metrics as stats

                    action = ("up" if spec["type"] == TYPE_SCALE_UP
                              else "drain")
                    stats.ScaleEventsCounter.labels(action).inc()
                    events_mod.emit(
                        events_mod.SCALE_UP if action == "up"
                        else events_mod.SCALE_DRAIN,
                        service="master", node=spec["type"],
                        detail=dict(spec["params"]))
                elif spec["type"] == TYPE_TIER_MOVE:
                    events_mod.emit(
                        events_mod.TIER_MOVE, service="master",
                        node=spec["type"],
                        detail=dict(spec["params"],
                                    volume=spec["volume"]))
                else:
                    events_mod.emit(events_mod.JOB_ENQUEUED,
                                    service="master", node=spec["type"],
                                    detail={"id": jid,
                                            "volume": spec["volume"]})
        self._scan_shard_scale(now, cooldown)
        return ids

    def _scan_shard_scale(self, now: float, cooldown: float):
        """Shard-count elasticity: unlike volume-server jobs these are
        not queued for workers — the curator proposes the filer.resize
        directly and the master's driver completes the two-phase flip."""
        raft = getattr(self.master, "raft", None)
        if raft is None or getattr(raft, "fsm", None) is None \
                or not hasattr(raft, "lock"):
            return
        with raft.lock:
            m = raft.fsm.shard_map
            shards = {"slots": m.slots,
                      "holders": sum(1 for exp in m.members.values()
                                     if exp > now),
                      "resize": m.resize is not None}
        for spec in detectors.scan_shard_scale(shards):
            if now - self._recent.get((spec["type"], 0), 0) < cooldown:
                continue
            try:
                r = raft.propose({"type": "filer.resize", "op": "start",
                                  "to": int(spec["params"]["to"]),
                                  "now": now})
            except Exception:
                continue  # lost leadership mid-tick: next leader rescans
            if isinstance(r, dict) and r.get("error"):
                continue
            self._recent[(spec["type"], 0)] = now
            from ..stats import events as events_mod

            events_mod.emit(
                events_mod.SHARD_SPLIT
                if spec["type"] == TYPE_SHARD_SPLIT
                else events_mod.SHARD_MERGE,
                service="master", node="curator",
                detail=dict(spec["params"], phase="prepare"))

    # -- completion hook -----------------------------------------------------
    def on_complete(self, job, report: Optional[dict]):
        self._recent[(job.type, job.volume)] = self.now()
        from ..stats import events as events_mod

        events_mod.emit(events_mod.JOB_DONE, service="master",
                        node=job.type,
                        detail={"id": job.id, "volume": job.volume,
                                "outcome": job.outcome})
        if job.type == TYPE_DEEP_SCRUB:
            self.last_scrub[job.volume] = self.now()
            # scrub findings close the loop: corruption becomes a
            # rebuild job right now, not on the next detector pass
            if report and (report.get("corrupt")
                           or report.get("parity_mismatch")
                           or report.get("missing")):
                self.queue.enqueue(
                    TYPE_EC_REBUILD, job.volume, job.collection,
                    {"from": "deep.scrub",
                     "corrupt": report.get("corrupt", []),
                     "missing": report.get("missing", [])})
        if job.type == TYPE_SCALE_UP:
            # the newcomer joins empty: immediately re-shard hot
            # collections onto it under live traffic (the balance
            # worker runs as background QoS, so interactive isolation
            # bounds hold during the move)
            self.queue.enqueue(
                TYPE_BALANCE, 0, "",
                {"from": "scale.up", "kinds": ["ec", "volume"]})

    # -- admin surface -------------------------------------------------------
    def status(self) -> dict:
        return {"enabled": self.enabled,
                "leader": bool(self.master.raft.is_leader),
                "interval": self.interval,
                "scans": self.scans, "enqueued": self.enqueued,
                "autoscale": {
                    "enabled": os.environ.get("WEED_SCALE", "0")
                    not in ("0", "", "false", "no"),
                    "up_occupancy": _env_float("WEED_SCALE_UP_OCC", 0.75),
                    "drain_occupancy": _env_float(
                        "WEED_SCALE_DRAIN_OCC", 0.15),
                    "min_nodes": int(_env_float(
                        "WEED_SCALE_MIN_NODES", 1))},
                "queue": self.queue.stats(),
                "last_scrub": {str(k): round(v, 3)
                               for k, v in self.last_scrub.items()}}

    def mount(self, server, guard):
        """Register /maintenance/* on the master's RpcServer.  Worker
        endpoints (lease/renew/complete/fail) are open like
        /api/heartbeat; operator endpoints go through the IP guard."""
        s = server

        def status(req):
            return self.status()

        def queue_view(req):
            return {"jobs": self.queue.jobs(),
                    "history": list(self.queue.history)[-50:]}

        def lease(req):
            d = req.json()
            types = d.get("types") or list(JOB_TYPES)
            jobs = self.queue.lease(d.get("worker", ""), types,
                                    int(d.get("limit", 1)),
                                    ec_volumes=d.get("ec_volumes"))
            return {"jobs": jobs,
                    "lease_seconds": self.queue.lease_seconds}

        def renew(req):
            d = req.json()
            return {"ok": self.queue.renew(d.get("id", ""),
                                           d.get("worker", ""))}

        def complete(req):
            d = req.json()
            job = self.queue.complete(d.get("id", ""),
                                      d.get("worker", ""),
                                      d.get("outcome", "ok"))
            if job is not None:
                self.on_complete(job, d.get("report"))
            return {"ok": job is not None}

        def fail(req):
            d = req.json()
            job = self.queue.fail(d.get("id", ""), d.get("worker", ""),
                                  d.get("error", ""))
            return {"ok": job is not None,
                    "state": job.state if job else "lost"}

        def pause(req):
            d = req.json()
            self.queue.paused = bool(d.get("paused", True))
            return {"paused": self.queue.paused}

        def run(req):
            d = req.json()
            if d.get("type"):  # enqueue one explicit job
                jid = self.queue.enqueue(
                    d["type"], int(d.get("volume", 0)),
                    d.get("collection", ""), d.get("params") or {})
                return {"enqueued": [jid] if jid else []}
            return {"enqueued": self.tick()}

        s.add("GET", "/maintenance/status", status)
        s.add("GET", "/maintenance/queue", guard(queue_view))
        s.add("POST", "/maintenance/lease", lease)
        s.add("POST", "/maintenance/renew", renew)
        s.add("POST", "/maintenance/complete", complete)
        s.add("POST", "/maintenance/fail", fail)
        s.add("POST", "/maintenance/pause", guard(pause))
        s.add("POST", "/maintenance/run", guard(run))
