"""Volume-server maintenance worker: lease, execute, report.

Each volume server runs one worker thread (WEED_MAINT_WORKER=0
disables) that polls the master's /maintenance/lease every
WEED_MAINT_POLL seconds, executes the job through the matching shell
repair primitive or the deep-scrub pipeline, renews the lease while
working, and reports complete/fail.  All maintenance I/O the worker
performs locally runs under one BytePacer wired to the server's
request shedder, so foreground traffic automatically squeezes
background repairs down to the pacer floor."""

from __future__ import annotations

import os
import threading
import time

from .. import qos
from ..rpc.http_rpc import RpcError, call
from ..stats import metrics
from ..storage.erasure_coding import TOTAL_SHARDS_COUNT
from ..util import glog
from .jobs import (TYPE_BALANCE, TYPE_DEEP_SCRUB, TYPE_EC_REBUILD,
                   TYPE_FIX_REPLICATION, TYPE_SCALE_DRAIN,
                   TYPE_SCALE_UP, TYPE_TIER_MOVE, TYPE_VACUUM)
from .pacer import BytePacer


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class MaintenanceWorker:
    def __init__(self, server):
        self.server = server  # the VolumeServer
        self.pacer = BytePacer(load_fn=self._foreground_load)
        self._stop = threading.Event()
        self._thread = None
        self.executed = 0
        self.failed = 0
        self.last_job = {}

    @property
    def worker_id(self) -> str:
        return self.server.address

    def enabled(self) -> bool:
        return os.environ.get("WEED_MAINT_WORKER", "1") != "0"

    def poll_seconds(self) -> float:
        return _env_float("WEED_MAINT_POLL", 5.0)

    def _foreground_load(self) -> float:
        """Occupancy of the QoS admission gate (in-flight + queued over
        the limit) — the same signal that queues/sheds foreground
        requests drives pacer backoff.  With QoS disabled, fall back to
        the legacy request-shedder fraction."""
        gate = getattr(self.server, "qos_gate", None)
        if gate is not None and qos.enabled():
            return gate.occupancy()
        shed = getattr(self.server, "request_shedder", None)
        if shed is None:
            return 0.0
        limit = shed._effective_limit()
        if not limit or limit <= 0:
            return 0.0
        return min(1.0, shed.current / float(limit))

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if not self.enabled() or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="maint-worker", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.poll_seconds()):
            try:
                self.poll_once()
            except Exception as e:
                glog.warning(f"maintenance worker poll failed: {e}")

    # -- one lease/execute/report round --------------------------------------
    def poll_once(self) -> int:
        """Lease and run up to one job; returns jobs executed."""
        try:
            resp = call(self.server.master_address, "/maintenance/lease",
                        {"worker": self.worker_id, "limit": 1,
                         "ec_volumes": self._held_ec_volumes()},
                        timeout=10)
        except (RpcError, OSError):
            return 0  # master unreachable / follower: retry next poll
        jobs = resp.get("jobs") or []
        lease_seconds = float(resp.get("lease_seconds", 60.0))
        for job in jobs:
            self._run(job, lease_seconds)
        return len(jobs)

    def _held_ec_volumes(self) -> list:
        out = []
        for loc in self.server.store.locations:
            out.extend(loc.ec_volumes)
        return sorted(set(out))

    def _run(self, job: dict, lease_seconds: float):
        stop_renew = threading.Event()

        def renew_loop():
            while not stop_renew.wait(max(1.0, lease_seconds / 3.0)):
                try:
                    call(self.server.master_address,
                         "/maintenance/renew",
                         {"id": job["id"], "worker": self.worker_id},
                         timeout=10)
                except (RpcError, OSError):
                    pass  # expiry requeues if the master stays away

        rt = threading.Thread(target=renew_loop, daemon=True,
                              name=f"maint-renew-{job['id']}")
        rt.start()
        t0 = time.perf_counter()
        self.last_job = {"id": job["id"], "type": job["type"],
                         "volume": job["volume"]}
        try:
            # curator jobs run (and fan out RPCs) as background-class
            # maintenance traffic: peers admit them behind foreground
            with qos.qos_scope(qos.BACKGROUND, tenant="maintenance"):
                report = self._execute(job)
            metrics.MaintJobSecondsHistogram.labels(job["type"]) \
                .observe(time.perf_counter() - t0)
            self.executed += 1
            self._report("/maintenance/complete",
                         {"id": job["id"], "worker": self.worker_id,
                          "outcome": "ok", "report": report})
        except Exception as e:
            self.failed += 1
            glog.warning(f"maintenance job {job['id']} "
                         f"({job['type']} v{job['volume']}) failed: {e}")
            self._report("/maintenance/fail",
                         {"id": job["id"], "worker": self.worker_id,
                          "error": f"{type(e).__name__}: {e}"})
        finally:
            stop_renew.set()
            rt.join(timeout=5)

    def _report(self, route: str, payload: dict):
        try:
            call(self.server.master_address, route, payload, timeout=10)
        except (RpcError, OSError):
            pass  # lease expiry recovers; don't crash the worker

    # -- executors -----------------------------------------------------------
    def _shell_env(self):
        from ..shell.commands import CommandEnv

        return CommandEnv(self.server.master_address)

    def _execute(self, job: dict) -> dict:
        fn = {TYPE_EC_REBUILD: self._exec_ec_rebuild,
              TYPE_FIX_REPLICATION: self._exec_fix_replication,
              TYPE_VACUUM: self._exec_vacuum,
              TYPE_DEEP_SCRUB: self._exec_deep_scrub,
              TYPE_BALANCE: self._exec_balance,
              TYPE_SCALE_UP: self._exec_scale_up,
              TYPE_SCALE_DRAIN: self._exec_scale_drain,
              TYPE_TIER_MOVE: self._exec_tier_move}.get(job["type"])
        if fn is None:
            raise ValueError(f"unknown job type {job['type']!r}")
        return fn(job)

    def _exec_ec_rebuild(self, job: dict) -> dict:
        """Repair corrupt AND missing shards: the scrub-with-repair
        pass deletes bad shards cluster-wide, rebuilds from clean
        survivors, and re-verifies against the stored CRCs."""
        from ..shell import commands as sh

        out = sh.ec_scrub(self._shell_env(), vid=job["volume"],
                          repair=True)
        # clean_shards/corrupt/missing are the PRE-repair state; a report
        # that was degraded converged iff the rebuild actually ran
        bad = [v for v in out
               if v.get("rebuild_error")
               or ((v.get("corrupt") or v.get("missing"))
                   and "rebuild" not in v)]
        if bad:
            raise RuntimeError(f"rebuild did not converge: {bad}")
        return {"volumes": len(out),
                "rebuilt": [v["volume"] for v in out if "rebuild" in v]}

    def _exec_fix_replication(self, job: dict) -> dict:
        from ..shell import commands_volume as vol

        actions = vol.volume_fix_replication(self._shell_env())
        return {"actions": actions}

    def _exec_vacuum(self, job: dict) -> dict:
        """The old master auto-vacuum pass, for one volume, from a
        worker: check garbage on every holder, then compact+commit —
        the synchronous holder RPCs now burn a worker thread, not the
        leader's reap loop."""
        vid = job["volume"]
        threshold = float(job.get("params", {})
                          .get("garbage_threshold", 0.0))
        looked = call(self.server.master_address,
                      f"/dir/lookup?volumeId={vid}", timeout=10)
        urls = sorted({loc["url"] for loc in looked.get("locations", [])})
        compacted = []
        for url in urls:
            check = call(url, "/admin/vacuum/check", {"volume": vid},
                         timeout=60)
            if check.get("garbage_ratio", 0.0) <= max(0.0, threshold):
                continue
            call(url, "/admin/vacuum/compact", {"volume": vid},
                 timeout=600)
            call(url, "/admin/vacuum/commit", {"volume": vid},
                 timeout=600)
            compacted.append(url)
        return {"volume": vid, "compacted": compacted}

    def _exec_deep_scrub(self, job: dict) -> dict:
        """Device-batched deep scrub of one locally-held EC volume:
        local shards stream from disk, missing shards fetch from peers
        via /admin/ec/shard_read, everything paced."""
        from .deep_scrub import ScrubTarget, deep_scrub

        vid = job["volume"]
        collection = job.get("collection", "")
        ev = self.server.store.find_ec_volume(vid)
        if ev is None:
            raise RuntimeError(f"ec volume {vid} not held here")
        if getattr(ev, "writer", None):
            # inline EC volume: audit the live writer in place —
            # recompute every committed stripe's parity + CRC against
            # the commit log and re-read every live needle
            from ..storage.erasure_coding.inline import \
                audit_inline_volume

            report = audit_inline_volume(ev)
            report["pacer"] = self.pacer.snapshot()
            return report
        from ..storage.erasure_coding.encoder import load_volume_info

        base = ev.base_file_name()
        info = load_volume_info(base) or {}
        stored = info.get("shard_crc32c")
        if not isinstance(stored, list) \
                or len(stored) != TOTAL_SHARDS_COUNT:
            raise RuntimeError(f"{base}.vif has no shard_crc32c record")
        local = dict(ev.shards)
        nominal = ev.shard_size
        sizes = [local[s].ecd_file_size if s in local else nominal
                 for s in range(TOTAL_SHARDS_COUNT)]
        remote = self.server._make_remote_reader(vid)

        def reader(sid: int, offset: int, size: int) -> bytes:
            shard = local.get(sid)
            if shard is not None:
                return shard.read_at(size, offset)
            data = remote(sid, offset, size)
            if data is None:
                raise RpcError(f"shard {vid}.{sid} unreachable", 502)
            return data

        target = ScrubTarget(volume=vid, collection=collection,
                             stored=list(stored), sizes=sizes,
                             reader=reader)
        stage_stats: dict = {}
        out = deep_scrub([target], throttle=self.pacer.throttle,
                         stage_stats=stage_stats)
        v = out["volumes"][0]
        report = {**v, "stage_stats": stage_stats,
                  "pacer": self.pacer.snapshot()}
        return report

    def _exec_balance(self, job: dict) -> dict:
        """Rebalance whichever populations the detector flagged
        (params["kinds"]): EC shards, plain volumes, or both."""
        from ..shell import commands as sh
        from ..shell import commands_volume as vol

        kinds = job.get("params", {}).get("kinds") or ["ec"]
        report: dict = {}
        if "ec" in kinds:
            report["ec_moves"] = sh.ec_balance(self._shell_env())
        if "volume" in kinds:
            report["volume_moves"] = vol.volume_balance(self._shell_env())
        return report

    # -- elasticity executors ------------------------------------------------
    def _exec_scale_up(self, job: dict) -> dict:
        """Grow the cluster by one volume server.  In-process when the
        host installed a spawn seam (tests / bench on the 1-core
        harness); otherwise fork a `weed.py volume` subprocess and wait
        until the master's topology shows the newcomer."""
        spawn = getattr(self.server, "spawn_volume_server", None)
        if callable(spawn):
            url = spawn(job)
            return {"spawned": url, "mode": "in-process"}
        import subprocess
        import sys
        import tempfile

        base = os.environ.get("WEED_SCALE_DIR") or tempfile.gettempdir()
        workdir = tempfile.mkdtemp(prefix="weed-scale-", dir=base)
        weed = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "weed.py")
        before = self._cluster_node_count()
        proc = subprocess.Popen(
            [sys.executable, weed, "volume", "-dir", workdir,
             "-mserver", self.server.master_address, "-port", "0"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.server.scale_children.append(proc)
        deadline = time.monotonic() + _env_float(
            "WEED_SCALE_SPAWN_TIMEOUT", 90.0)
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"spawned volume server exited rc={proc.returncode}")
            if self._cluster_node_count() > before:
                return {"spawned": workdir, "mode": "subprocess",
                        "nodes": before + 1}
            time.sleep(0.5)
        proc.terminate()
        raise RuntimeError("spawned volume server never registered")

    def _cluster_node_count(self) -> int:
        try:
            status = call(self.server.master_address, "/dir/status",
                          timeout=10)
        except (RpcError, OSError):
            return -1
        return sum(len(r.get("nodes", []))
                   for dc in status.get("datacenters", [])
                   for r in dc.get("racks", []))

    def _exec_scale_drain(self, job: dict) -> dict:
        """Graceful drain: read-only demotion, curator-paced volume and
        EC-shard evacuation, then deregistration — all as background
        QoS traffic, so interactive reads stay inside their isolation
        bounds while the node empties."""
        from ..shell import commands as sh
        from ..shell import commands_volume as vol

        server = job.get("params", {}).get("server")
        if not server:
            raise ValueError("scale.drain needs params.server")
        env = self._shell_env()
        call(server, "/admin/drain", {"draining": True}, timeout=30)
        moves = vol.volume_server_evacuate(env, server)
        shard_moves = sh.ec_evacuate(env, server)
        call(server, "/admin/leave", {}, timeout=30)
        return {"server": server, "volume_moves": moves,
                "ec_shard_moves": shard_moves}

    def _exec_tier_move(self, job: dict) -> dict:
        """Advisory for now: the temperature detector flagged this
        volume as cold.  Surface the hint (journal + job report) so an
        operator — or the future cold-tier mover (ROADMAP item 3) —
        can act on it with storage/tier.py's tier_upload; the hint
        itself performs no data movement."""
        params = dict(job.get("params", {}))
        return {"volume": job["volume"], "advisory": True,
                "action": "none", "hint": params}
