"""Load benchmark: concurrent write-then-read of small files.

Port of `weed benchmark` (weed/command/benchmark.go:27-90): N files of a
given size written through master assign + volume POST at a set
concurrency, then read back randomly, with a latency histogram and the
same percentile report (p50..p99.9/max) as the reference README's
published numbers (README.md:342-391).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from .rpc.http_rpc import RpcError, call


@dataclass
class BenchResult:
    requests: int = 0
    errors: int = 0
    bytes: int = 0
    seconds: float = 0.0
    latencies_ms: list = field(default_factory=list)

    def percentile(self, p: float) -> float:
        if not self.latencies_ms:
            return 0.0
        data = sorted(self.latencies_ms)
        idx = min(len(data) - 1, int(len(data) * p / 100))
        return data[idx]

    def report(self, title: str) -> str:
        rps = self.requests / self.seconds if self.seconds else 0
        mbps = self.bytes / 1e6 / self.seconds if self.seconds else 0
        lines = [
            f"--- {title} ---",
            f"requests: {self.requests}, errors: {self.errors}",
            f"time: {self.seconds:.2f}s, {rps:.1f} req/s, {mbps:.2f} MB/s",
        ]
        for p in (50, 66, 75, 80, 90, 95, 98, 99, 99.9):
            lines.append(f"  p{p}: {self.percentile(p):.2f} ms")
        if self.latencies_ms:
            lines.append(f"  max: {max(self.latencies_ms):.2f} ms")
        return "\n".join(lines)


def run_benchmark(master_address: str, num_files: int = 1000,
                  file_size: int = 1024, concurrency: int = 16,
                  delete_percent: int = 0, replication: str = "000",
                  do_read: bool = True, quiet: bool = False,
                  use_tcp: bool = False, use_native: bool = False,
                  assign_batch: int = 256, per_file_assign: bool = False):
    if per_file_assign:
        return _run_full_native(master_address, num_files, file_size,
                                concurrency, quiet)
    if use_native:
        return _run_native(master_address, num_files, file_size,
                           concurrency, delete_percent, replication,
                           do_read, quiet, assign_batch)
    tcp_client = None
    if use_tcp:  # benchmark -useTcp (command/benchmark.go)
        from .wdclient.volume_tcp_client import VolumeTcpClient

        tcp_client = VolumeTcpClient(max_conns_per_server=concurrency)
    payload = random.randbytes(file_size)
    fids: list[tuple[str, str]] = []
    fid_lock = threading.Lock()
    write = BenchResult()
    counter = {"n": 0}

    def write_worker():
        while True:
            with fid_lock:
                if counter["n"] >= num_files:
                    return
                counter["n"] += 1
            t0 = time.perf_counter()
            try:
                a = call(master_address,
                         f"/dir/assign?replication={replication}")
                headers = ({"Authorization": "BEARER " + a["auth"]}
                           if a.get("auth") else {})
                call(a["url"], f"/{a['fid']}", raw=payload, method="POST",
                     headers=headers)
                dt = (time.perf_counter() - t0) * 1e3
                with fid_lock:
                    write.requests += 1
                    write.bytes += file_size
                    write.latencies_ms.append(dt)
                    fids.append((a["url"], a["fid"]))
            except RpcError:
                with fid_lock:
                    write.errors += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=write_worker)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    write.seconds = time.perf_counter() - t0

    read = BenchResult()
    if tcp_client is not None and not (do_read and fids):
        tcp_client.close()
    if do_read and fids:
        reads_left = {"n": len(fids)}

        def read_worker():
            while True:
                with fid_lock:
                    if reads_left["n"] <= 0:
                        return
                    reads_left["n"] -= 1
                url, fid = random.choice(fids)
                t0 = time.perf_counter()
                try:
                    # broad catch: the TCP path raises VolumeTcpError/
                    # OSError/TimeoutError, not just RpcError — a dead
                    # reader thread would silently skew the report
                    data = (tcp_client.read_needle(url, fid)
                            if tcp_client is not None
                            else call(url, f"/{fid}"))
                    dt = (time.perf_counter() - t0) * 1e3
                    with fid_lock:
                        read.requests += 1
                        read.bytes += len(data)
                        read.latencies_ms.append(dt)
                except Exception:
                    with fid_lock:
                        read.errors += 1

        t0 = time.perf_counter()
        threads = [threading.Thread(target=read_worker)
                   for _ in range(concurrency)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            if tcp_client is not None:
                tcp_client.close()
        read.seconds = time.perf_counter() - t0

    if delete_percent > 0:
        for url, fid in fids[: len(fids) * delete_percent // 100]:
            try:
                call(url, f"/{fid}", method="DELETE")
            except RpcError:
                pass

    if not quiet:
        print(write.report("write"))
        if do_read:
            print(read.report("read"))
    return write, read


def _run_full_native(master_address: str, num_files: int, file_size: int,
                     concurrency: int, quiet: bool):
    """Per-file assign + write, both off the GIL: each request fetches a
    fresh fid from the master's native 'A' handler (lease-fed by the
    Python master) and writes it to the assigned volume server — the
    reference benchmark's exact per-file flow (command/benchmark.go
    writeFiles).  Requires master AND volume servers started with -tcp
    on conventional ports (native port = http port + 20000).  Reads are
    not run (fids/cookies are minted inside the C++ driver); use the
    batched mode for read rates."""
    from .storage import native_engine

    if not native_engine.available():
        raise RuntimeError("native engine unavailable (build native/)")
    status = call(master_address, "/dir/status")
    nport = status.get("native_assign_port", 0)
    if not nport:
        raise RuntimeError(
            "master native assign not enabled (start master with -tcp)")
    host = master_address.rsplit(":", 1)[0]
    write = BenchResult()
    secs, errs, lat = native_engine.bench(
        host, int(nport), "F", ["-"], num_files, file_size, concurrency)
    write.requests = num_files - errs
    write.errors = errs
    write.bytes = (num_files - errs) * file_size
    write.seconds = secs
    write.latencies_ms = lat.tolist()
    if not quiet:
        print(write.report("write (per-file native assign)"))
    return write, BenchResult()


def _run_native(master_address: str, num_files: int, file_size: int,
                concurrency: int, delete_percent: int, replication: str,
                do_read: bool, quiet: bool, assign_batch: int,
                http_phase: bool = False, pre_phase_hook=None):
    """Native-engine benchmark: the load generator is the C++ driver in
    native/vol_native.cpp (like the reference's compiled Go benchmark
    client), hitting the volume server's native fast-path port.  File ids
    are assigned from the master in batches via /dir/assign?count=N (the
    reference's Assign count parameter, operation/assign_file_id.go) and
    expanded with the fid "_delta" convention.

    JWT-secured clusters: assign replies carry fid-scoped tokens that
    ride with each fid; the cluster's jwt.signing expires_after_seconds
    must outlive the whole write phase (the harness uses 3600 s), since
    every token is minted during the up-front assign loop.

    pre_phase_hook(by_server): called after assigns, before the write
    phase — e.g. to wait for replica-set propagation on replicated
    volumes so the native plane serves the writes rather than 307ing."""
    from .storage import native_engine
    from .wdclient.volume_tcp_client import VolumeTcpClient

    if not native_engine.available():
        raise RuntimeError("native engine unavailable (build native/)")
    resolver = VolumeTcpClient()
    by_server: dict[str, list[str]] = {}
    write = BenchResult()
    t_assign0 = time.perf_counter()
    remaining = num_files
    while remaining > 0:
        k = min(assign_batch, remaining)
        a = call(master_address,
                 f"/dir/assign?replication={replication}&count={k}")
        fid = a["fid"]
        # JWT clusters: carry the assign's token with each fid ("fid jwt"
        # entries; the C++ driver appends it to the framed request line —
        # one batch token authorizes fid and its _N variants)
        suffix = f" {a['auth']}" if a.get("auth") else ""
        group = by_server.setdefault(a["url"], [])
        group.append(fid + suffix)
        group.extend(f"{fid}_{i}{suffix}" for i in range(1, k))
        remaining -= k
    assign_seconds = time.perf_counter() - t_assign0

    def tcp_endpoint(url: str) -> tuple[str, int]:
        host, port = resolver.tcp_address(url).rsplit(":", 1)
        return host, int(port)

    def run_phase(op: str, result: BenchResult, payload: int):
        """Drive every server concurrently (svn_bench releases the GIL);
        wall-clock is the slowest server, so multi-server runs report
        true aggregate throughput."""
        from concurrent.futures import ThreadPoolExecutor

        def one(item):
            url, fids = item
            host, port = tcp_endpoint(url)
            return native_engine.bench(host, port, op, fids, len(fids),
                                       payload, concurrency)

        with ThreadPoolExecutor(max_workers=len(by_server)) as pool:
            outs = list(pool.map(one, by_server.items()))
        for (url, fids), (secs, errs, lat) in zip(by_server.items(), outs):
            result.requests += len(fids) - errs
            result.errors += errs
            result.bytes += (len(fids) - errs) * file_size
            result.seconds = max(result.seconds, secs)
            result.latencies_ms.extend(lat.tolist())

    if pre_phase_hook is not None:
        pre_phase_hook(by_server)
    run_phase("W", write, file_size)

    read = BenchResult()
    if do_read:
        run_phase("R", read, 0)
    read.http_rps = 0.0
    if http_phase:
        # the native port also answers plain HTTP GETs: measure the
        # reference benchmark's own modality (README.md:372-381)
        http = BenchResult()
        run_phase("H", http, 0)
        read.http_rps = (http.requests / http.seconds
                         if http.seconds else 0.0)

    if delete_percent > 0:
        for url, fids in by_server.items():
            host, port = tcp_endpoint(url)
            n = len(fids) * delete_percent // 100
            if n:
                native_engine.bench(host, port, "D", fids[:n], n, 0,
                                    concurrency)

    if not quiet:
        print(f"(assign: {num_files} fids in {assign_seconds:.2f}s, "
              f"batch={assign_batch})")
        print(write.report("write (native)"))
        if do_read:
            print(read.report("read (native)"))
    return write, read
