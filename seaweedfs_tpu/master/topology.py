"""Cluster topology: DataCenter -> Rack -> DataNode tree + volume layouts.

Parity with weed/topology/: heartbeat-driven registration
(topology.go:24-71, data_node.go), per-(collection, replication, ttl)
VolumeLayout tracking writable volumes (volume_layout.go), EC shard
locations (topology_ec.go:16-161), and lookup with EC fallback
(topology.go:128-133).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..storage.erasure_coding.ec_volume import ShardBits
from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import TTL
from .sequence import MemorySequencer


@dataclass
class VolumeInfo:
    id: int
    collection: str = ""
    size: int = 0
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: int = 0
    ttl: int = 0
    compact_revision: int = 0
    modified_at_second: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeInfo":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})


class DataNode:
    def __init__(self, node_id: str, ip: str, port: int, public_url: str,
                 max_volume_count: int, dc: "DataCenter", rack: "Rack"):
        self.id = node_id
        self.ip = ip
        self.port = port
        self.public_url = public_url
        self.max_volume_count = max_volume_count
        self.dc = dc
        self.rack = rack
        self.volumes: dict[int, VolumeInfo] = {}
        self.ec_shards: dict[int, ShardBits] = {}
        self.last_seen = time.time()
        # load telemetry from the latest heartbeat (rps / occupancy /
        # draining), consumed by the curator's autoscale detectors
        self.telemetry: dict = {}
        # access-sketch summary from the latest heartbeat, folded into
        # the leader's UsageAggregator (stats/access.py)
        self.access: dict = {}

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def available_slots(self) -> int:
        from ..storage.erasure_coding import TOTAL_SHARDS_COUNT

        ec_used = sum(b.count() for b in self.ec_shards.values()) / float(
            TOTAL_SHARDS_COUNT)
        return max(0, int(self.max_volume_count - len(self.volumes) - ec_used))

    def to_dict(self) -> dict:
        return {
            "id": self.id, "url": self.url, "publicUrl": self.public_url,
            "volumes": len(self.volumes),
            "ecShards": sum(b.count() for b in self.ec_shards.values()),
            "max": self.max_volume_count, "free": self.available_slots(),
            "dc": self.dc.id, "rack": self.rack.id,
            "occupancy": round(
                float(self.telemetry.get("occupancy", 0.0)), 4),
            "rps": round(float(self.telemetry.get("rps", 0.0)), 1),
            "draining": bool(self.telemetry.get("draining", False)),
            "volume_list": [
                {"id": v.id, "collection": v.collection, "size": v.size,
                 "file_count": v.file_count,
                 "delete_count": v.delete_count,
                 "deleted_bytes": v.deleted_byte_count,
                 "read_only": v.read_only,
                 "replication": v.replica_placement, "ttl": v.ttl,
                 "modified_at": v.modified_at_second}
                for v in self.volumes.values()
            ],
        }


class Rack:
    def __init__(self, rack_id: str, dc: "DataCenter"):
        self.id = rack_id
        self.dc = dc
        self.nodes: dict[str, DataNode] = {}

    def available_slots(self) -> int:
        return sum(n.available_slots() for n in self.nodes.values())


class DataCenter:
    def __init__(self, dc_id: str):
        self.id = dc_id
        self.racks: dict[str, Rack] = {}

    def available_slots(self) -> int:
        return sum(r.available_slots() for r in self.racks.values())


def _layout_key(collection: str, rp_byte: int, ttl: int) -> tuple:
    return (collection, rp_byte, ttl)


class VolumeLayout:
    """Writable-volume tracking per (collection, replication, ttl)
    (weed/topology/volume_layout.go)."""

    def __init__(self, rp: ReplicaPlacement, ttl: TTL,
                 volume_size_limit: int):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.vid_to_nodes: dict[int, list[DataNode]] = {}
        self.writables: set[int] = set()
        self.readonly: set[int] = set()
        self.oversized: set[int] = set()

    def register(self, v: VolumeInfo, node: DataNode):
        nodes = self.vid_to_nodes.setdefault(v.id, [])
        if node not in nodes:
            nodes.append(node)
        # both conditions clear again after vacuum / readonly=false
        if v.size >= self.volume_size_limit:
            self.oversized.add(v.id)
        else:
            self.oversized.discard(v.id)
        if v.read_only:
            self.readonly.add(v.id)
        else:
            self.readonly.discard(v.id)
        if (v.id not in self.oversized and v.id not in self.readonly
                and len(nodes) >= self.rp.copy_count()):
            self.writables.add(v.id)
        else:
            self.writables.discard(v.id)

    def unregister(self, vid: int, node: DataNode):
        nodes = self.vid_to_nodes.get(vid, [])
        if node in nodes:
            nodes.remove(node)
        if len(nodes) < self.rp.copy_count():
            self.writables.discard(vid)
        if not nodes:
            self.vid_to_nodes.pop(vid, None)
            self.writables.discard(vid)
            self.readonly.discard(vid)
            self.oversized.discard(vid)

    def pick_for_write(self) -> Optional[tuple[int, list[DataNode]]]:
        import random

        if not self.writables:
            return None
        vid = random.choice(sorted(self.writables))
        return vid, self.vid_to_nodes[vid]

    def active_writable_count(self) -> int:
        return len(self.writables)


class Topology:
    def __init__(self, volume_size_limit: int = 30 * 1000 * 1000 * 1000,
                 pulse_seconds: float = 5.0):
        self.lock = threading.RLock()
        self.dcs: dict[str, DataCenter] = {}
        self.nodes: dict[str, DataNode] = {}
        self.layouts: dict[tuple, VolumeLayout] = {}
        self.ec_shard_map: dict[int, dict[int, list[DataNode]]] = {}
        self.ec_collections: dict[int, str] = {}
        self.sequencer = MemorySequencer()
        self.max_volume_id = 0
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        # optional hooks: raft-backed id allocation and location-change
        # notifications (KeepConnected push, master_grpc_server.go:63-93)
        self.vid_allocator: Optional[Callable[[], int]] = None
        self.on_change: Optional[Callable[[dict], None]] = None

    # -- registration (master_grpc_server.go heartbeat ingest) ---------------
    def process_heartbeat(self, hb: dict) -> DataNode:
        with self.lock:
            dc_name = hb.get("data_center") or "DefaultDataCenter"
            rack_name = hb.get("rack") or "DefaultRack"
            node_id = f"{hb['ip']}:{hb['port']}"
            dc = self.dcs.setdefault(dc_name, DataCenter(dc_name))
            rack = dc.racks.setdefault(rack_name, Rack(rack_name, dc))
            node = self.nodes.get(node_id)
            if node is None:
                node = DataNode(node_id, hb["ip"], hb["port"],
                                hb.get("public_url") or node_id,
                                hb.get("max_volume_count", 8), dc, rack)
                self.nodes[node_id] = node
                rack.nodes[node_id] = node
                from ..stats import events as events_mod

                events_mod.emit(events_mod.NODE_UP, service="volume",
                                node=node_id,
                                detail={"dc": dc_name, "rack": rack_name})
            node.last_seen = time.time()
            node.max_volume_count = hb.get("max_volume_count",
                                           node.max_volume_count)
            node.telemetry = hb.get("telemetry") or {}
            node.access = hb.get("access") or {}
            self.sequencer.set_max(hb.get("max_file_key", 0))
            from ..stats import metrics as stats

            stats.ScaleNodeOccupancyGauge.labels(node_id).set(
                float(node.telemetry.get("occupancy", 0.0)))
            stats.ScaleNodeRpsGauge.labels(node_id).set(
                float(node.telemetry.get("rps", 0.0)))
            stats.ScaleClusterSizeGauge.set(len(self.nodes))

            # full volume list replaces node state (simple full-sync model;
            # the reference also supports incremental deltas)
            old_vids = set(node.volumes)
            new_volumes = {v["id"]: VolumeInfo.from_dict(v)
                           for v in hb.get("volumes", [])}
            for vid in old_vids - set(new_volumes):
                self._unregister_volume(node.volumes[vid], node)
            for vid, info in new_volumes.items():
                self._register_volume(info, node)
                self.max_volume_id = max(self.max_volume_id, vid)

            old_ec = set(node.ec_shards)
            new_ec = {e["id"]: ShardBits(e["ec_index_bits"])
                      for e in hb.get("ec_shards", [])}
            for vid in old_ec - set(new_ec):
                self._unregister_ec(vid, node)
            for vid, bits in new_ec.items():
                collection = next(
                    (e.get("collection", "") for e in hb.get("ec_shards", [])
                     if e["id"] == vid), "")
                self._register_ec(vid, collection, bits, node)
                self.max_volume_id = max(self.max_volume_id, vid)
            return node

    def _register_volume(self, v: VolumeInfo, node: DataNode):
        is_new = v.id not in node.volumes
        node.volumes[v.id] = v
        layout = self._layout_for(v.collection, v.replica_placement, v.ttl)
        layout.register(v, node)
        if is_new and self.on_change:
            self.on_change({"op": "add", "volume": v.id,
                            "url": node.url, "publicUrl": node.public_url})

    def _unregister_volume(self, v: VolumeInfo, node: DataNode):
        node.volumes.pop(v.id, None)
        layout = self._layout_for(v.collection, v.replica_placement, v.ttl)
        layout.unregister(v.id, node)
        if self.on_change:
            self.on_change({"op": "remove", "volume": v.id,
                            "url": node.url, "publicUrl": node.public_url})

    def _register_ec(self, vid: int, collection: str, bits: ShardBits,
                     node: DataNode):
        node.ec_shards[vid] = bits
        self.ec_collections[vid] = collection
        shard_map = self.ec_shard_map.setdefault(vid, {})
        for sid in range(32):
            nodes = shard_map.setdefault(sid, [])
            if bits.has(sid):
                if node not in nodes:
                    nodes.append(node)
            elif node in nodes:
                nodes.remove(node)

    def _unregister_ec(self, vid: int, node: DataNode):
        node.ec_shards.pop(vid, None)
        shard_map = self.ec_shard_map.get(vid, {})
        for nodes in shard_map.values():
            if node in nodes:
                nodes.remove(node)
        if all(not nodes for nodes in shard_map.values()):
            self.ec_shard_map.pop(vid, None)
            self.ec_collections.pop(vid, None)

    def unregister_node(self, node_id: str):
        """Node stream dropped / dead (master_grpc_server.go:63-93)."""
        with self.lock:
            node = self.nodes.pop(node_id, None)
            if node is None:
                return
            for v in list(node.volumes.values()):
                self._unregister_volume(v, node)
            for vid in list(node.ec_shards):
                self._unregister_ec(vid, node)
            node.rack.nodes.pop(node_id, None)
            from ..stats import metrics as stats

            stats.ScaleClusterSizeGauge.set(len(self.nodes))

    def reap_dead_nodes(self, timeout: Optional[float] = None):
        timeout = timeout or self.pulse_seconds * 3
        now = time.time()
        with self.lock:
            dead = [nid for nid, n in self.nodes.items()
                    if now - n.last_seen > timeout]
        for nid in dead:
            self.unregister_node(nid)
        if dead:
            from ..stats import events as events_mod
            from ..stats import metrics as stats

            stats.TopologyDeadNodesCounter.inc(len(dead))
            for nid in dead:
                events_mod.emit(events_mod.NODE_DOWN, service="volume",
                                node=nid,
                                detail={"reason": "heartbeat timeout"})
        return dead

    # -- layouts / lookup ----------------------------------------------------
    def _layout_for(self, collection: str, rp_byte: int,
                    ttl: int) -> VolumeLayout:
        key = _layout_key(collection, rp_byte, ttl)
        layout = self.layouts.get(key)
        if layout is None:
            layout = VolumeLayout(ReplicaPlacement.from_byte(rp_byte),
                                  TTL.from_uint32(ttl),
                                  self.volume_size_limit)
            self.layouts[key] = layout
        return layout

    def lookup(self, vid: int, collection: str = "") -> list[dict]:
        """vid -> locations, EC fallback included (topology.go:118-135)."""
        with self.lock:
            for key, layout in self.layouts.items():
                if collection and key[0] != collection:
                    continue
                nodes = layout.vid_to_nodes.get(vid)
                if nodes:
                    return [{"url": n.url, "publicUrl": n.public_url}
                            for n in nodes]
            shard_map = self.ec_shard_map.get(vid)
            if shard_map:
                seen, out = set(), []
                for nodes in shard_map.values():
                    for n in nodes:
                        if n.id not in seen:
                            seen.add(n.id)
                            out.append({"url": n.url,
                                        "publicUrl": n.public_url})
                return out
            return []

    def lookup_ec_shards(self, vid: int) -> Optional[dict]:
        """LookupEcVolume (topology_ec.go): shard id -> locations."""
        with self.lock:
            shard_map = self.ec_shard_map.get(vid)
            if not shard_map:
                return None
            return {
                "volume_id": vid,
                "collection": self.ec_collections.get(vid, ""),
                "shard_id_locations": [
                    {"shard_id": sid,
                     "locations": [{"url": n.url, "publicUrl": n.public_url}
                                   for n in nodes]}
                    for sid, nodes in sorted(shard_map.items()) if nodes
                ],
            }

    # -- id allocation -------------------------------------------------------
    def pick_for_write(self, collection: str, rp_byte: int,
                       ttl: int) -> Optional[tuple[int, list[dict]]]:
        """Thread-safe write target pick: returns (vid, location dicts)
        snapshotted under the topology lock."""
        with self.lock:
            layout = self._layout_for(collection, rp_byte, ttl)
            picked = layout.pick_for_write()
            if picked is None:
                return None
            vid, nodes = picked
            return vid, [{"url": n.url, "publicUrl": n.public_url}
                         for n in nodes]

    def writable_count(self, collection: str, rp_byte: int,
                       ttl: int) -> int:
        with self.lock:
            return self._layout_for(collection, rp_byte,
                                    ttl).active_writable_count()

    def next_volume_id(self) -> int:
        if self.vid_allocator is not None:
            vid = self.vid_allocator()  # raft boundary (topology.go:138)
            with self.lock:
                self.max_volume_id = max(self.max_volume_id, vid)
            return vid
        with self.lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def assign_file_id(self, count: int = 1) -> tuple[int, int]:
        """-> (first_key, count)"""
        return self.sequencer.next_batch(count), count

    # -- views ---------------------------------------------------------------
    def to_dict(self) -> dict:
        with self.lock:
            return {
                "max_volume_id": self.max_volume_id,
                "volume_size_limit": self.volume_size_limit,
                "datacenters": [
                    {
                        "id": dc.id,
                        "racks": [
                            {
                                "id": rack.id,
                                "nodes": [n.to_dict()
                                          for n in rack.nodes.values()],
                            } for rack in dc.racks.values()
                        ],
                    } for dc in self.dcs.values()
                ],
                "layouts": [
                    {
                        "collection": key[0],
                        "replication": str(layout.rp),
                        "ttl": str(layout.ttl),
                        "writables": sorted(layout.writables),
                    } for key, layout in self.layouts.items()
                ],
                "ec_volumes": sorted(self.ec_shard_map),
            }
