"""Master server: assign/lookup HTTP API + heartbeat ingest + vacuum drive.

Parity with weed/server/master_server.go + master_server_handlers*.go:
  /dir/assign, /dir/lookup, /dir/status, /vol/grow, /vol/vacuum,
  /cluster/status, plus the heartbeat endpoint volume servers post to
  (the reference's bidirectional gRPC stream becomes periodic POSTs) and
  the EC shard lookup (LookupEcVolume).
Single-master; the reference's Raft FSM replicates only MaxVolumeId
(raft_server.go:78) so a single-node deployment is semantically complete.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..rpc.http_rpc import RpcError, RpcServer, call
from ..security import Guard, gen_write_jwt
from ..stats import metrics as stats
from ..storage import types as t
from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import TTL
from . import volume_growth
from .topology import Topology
from .volume_growth import VolumeGrowOption


class MasterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 volume_size_limit_mb: int = 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 guard: Optional[Guard] = None):
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds)
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.guard = guard or Guard()
        self.server = RpcServer(host, port)
        self._register_routes()
        self._reaper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._grow_lock = threading.Lock()

    @property
    def address(self) -> str:
        return self.server.address

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self.server.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()

    def stop(self):
        self._stop.set()
        self.server.stop()

    def _reap_loop(self):
        while not self._stop.wait(self.topo.pulse_seconds):
            self.topo.reap_dead_nodes()

    # -- routes --------------------------------------------------------------
    def _guarded(self, fn):
        """IP allow-list on admin/UI routes (guard.go WhiteList wrapper)."""
        def wrapped(req):
            peer = req.handler.client_address[0]
            if not self.guard.check_white_list(peer):
                raise RpcError(f"ip {peer} not allowed", 403)
            return fn(req)
        return wrapped

    def _register_routes(self):
        s = self.server
        g = self._guarded
        s.add("POST", "/api/heartbeat", self._handle_heartbeat)
        s.add("GET", "/dir/assign", self._handle_assign)
        s.add("POST", "/dir/assign", self._handle_assign)
        s.add("GET", "/dir/lookup", self._handle_lookup)
        s.add("GET", "/dir/status", g(lambda r: self.topo.to_dict()))
        s.add("GET", "/cluster/status", self._handle_cluster_status)
        s.add("POST", "/vol/grow", g(self._handle_grow))
        s.add("POST", "/vol/vacuum", g(self._handle_vacuum))
        s.add("GET", "/vol/status", g(lambda r: self.topo.to_dict()))
        s.add("GET", "/ec/lookup", self._handle_ec_lookup)
        s.add("GET", "/metrics", stats.metrics_handler)

    # -- heartbeat (master_grpc_server.go:60-170) ----------------------------
    def _handle_heartbeat(self, req):
        hb = req.json()
        stats.MasterReceivedHeartbeatCounter.labels("total").inc()
        self.topo.process_heartbeat(hb)
        return {
            "volume_size_limit": self.topo.volume_size_limit,
            "leader": True,
        }

    # -- assign (master_server_handlers.go:102-165) --------------------------
    def _handle_assign(self, req):
        count = int(req.param("count", "1"))
        collection = req.param("collection", "") or ""
        replication = req.param("replication") or self.default_replication
        ttl_s = req.param("ttl", "") or ""
        rp = ReplicaPlacement.parse(replication)
        ttl = TTL.parse(ttl_s)

        rp_byte, ttl_u32 = rp.to_byte(), ttl.to_uint32()
        if self.topo.writable_count(collection, rp_byte, ttl_u32) == 0:
            self._grow(collection, rp, ttl, only_if_needed=True)
        picked = self.topo.pick_for_write(collection, rp_byte, ttl_u32)
        if picked is None:
            raise RpcError("no writable volumes", 404)
        vid, locations = picked
        key, _ = self.topo.assign_file_id(count)
        cookie = random.getrandbits(32)
        fid = t.format_file_id(vid, key, cookie)
        result = {
            "fid": fid,
            "url": locations[0]["url"],
            "publicUrl": locations[0]["publicUrl"],
            "count": count,
        }
        if self.guard.signing:
            # JWT scoped to the assigned fid (master_server_handlers.go:150)
            result["auth"] = gen_write_jwt(self.guard.signing, fid)
        return result

    def _grow(self, collection: str, rp: ReplicaPlacement, ttl: TTL,
              target_count: Optional[int] = None,
              only_if_needed: bool = False):
        with self._grow_lock:
            if only_if_needed and self.topo.writable_count(
                    collection, rp.to_byte(), ttl.to_uint32()) > 0:
                return 0  # another request already grew the layout
            option = VolumeGrowOption(collection=collection,
                                      replica_placement=rp, ttl=ttl)
            count = target_count or volume_growth.find_volume_count(
                rp.copy_count())
            grown = 0
            for _ in range(count):
                try:
                    vid, servers = volume_growth.grow_one_volume(
                        self.topo, option,
                        lambda server, vid: call(
                            server.url, "/admin/assign_volume",
                            {"volume": vid, "collection": collection,
                             "replication": str(rp), "ttl": str(ttl)}))
                    grown += 1
                except (ValueError, RpcError):
                    break
            return grown

    def _handle_grow(self, req):
        collection = req.param("collection", "") or ""
        replication = req.param("replication") or self.default_replication
        count = req.param("count")
        rp = ReplicaPlacement.parse(replication)
        ttl = TTL.parse(req.param("ttl", "") or "")
        grown = self._grow(collection, rp, ttl,
                           target_count=int(count) if count else None)
        if grown == 0:
            raise RpcError("cannot grow any volume", 500)
        return {"count": grown}

    # -- lookup (master_server_handlers.go:34-80) ----------------------------
    def _handle_lookup(self, req):
        vid_s = req.param("volumeId")
        if vid_s is None:
            file_id = req.param("fileId")
            if not file_id:
                raise RpcError("volumeId or fileId required", 400)
            vid_s = file_id.split(",")[0]
        vid = int(vid_s.split(",")[0])
        collection = req.param("collection", "") or ""
        locations = self.topo.lookup(vid, collection)
        if not locations:
            raise RpcError(f"volume id {vid} not found", 404)
        return {"volumeId": str(vid), "locations": locations}

    def _handle_ec_lookup(self, req):
        vid = int(req.param("volumeId", "0"))
        result = self.topo.lookup_ec_shards(vid)
        if result is None:
            raise RpcError(f"ec volume {vid} not found", 404)
        return result

    def _handle_cluster_status(self, req):
        return {
            "IsLeader": True,
            "Leader": self.address,
            "MaxVolumeId": self.topo.max_volume_id,
        }

    # -- vacuum orchestration (topology_vacuum.go) ---------------------------
    def _handle_vacuum(self, req):
        threshold = float(req.param("garbageThreshold",
                                    str(self.garbage_threshold)))
        vacuumed = []
        with self.topo.lock:
            nodes = list(self.topo.nodes.values())
        for node in nodes:
            for vid, info in list(node.volumes.items()):
                try:
                    check = call(node.url, f"/admin/vacuum/check",
                                 {"volume": vid})
                    if check.get("garbage_ratio", 0) <= threshold:
                        continue
                    call(node.url, "/admin/vacuum/compact", {"volume": vid},
                         timeout=600)
                    call(node.url, "/admin/vacuum/commit", {"volume": vid},
                         timeout=600)
                    vacuumed.append({"node": node.url, "volume": vid})
                except RpcError:
                    continue
        return {"vacuumed": vacuumed}
