"""Master server: assign/lookup HTTP API + heartbeat ingest + vacuum drive.

Parity with weed/server/master_server.go + master_server_handlers*.go:
  /dir/assign, /dir/lookup, /dir/status, /vol/grow, /vol/vacuum,
  /cluster/status, plus the heartbeat endpoint volume servers post to
  (the reference's bidirectional gRPC stream becomes periodic POSTs) and
  the EC shard lookup (LookupEcVolume).
Single-master; the reference's Raft FSM replicates only MaxVolumeId
(raft_server.go:78) so a single-node deployment is semantically complete.
"""

from __future__ import annotations

import os
import random
import threading
import time
import urllib.parse
from typing import Optional

from .. import profiling, qos, tracing
from ..rpc.http_rpc import RpcError, RpcServer, call
from ..security import Guard, gen_write_jwt
from ..stats import events as events_mod
from ..stats import healthz
from ..stats import metrics as stats
from ..storage import types as t
from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import TTL
from ..util import faults, glog
from . import volume_growth
from .raft import RaftNode
from .topology import Topology
from .volume_growth import VolumeGrowOption


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class MasterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 volume_size_limit_mb: int = 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 guard: Optional[Guard] = None,
                 peers: Optional[list[str]] = None,
                 raft_dir: str = "",
                 raft_election_timeout: Optional[float] = None,
                 auto_vacuum_interval: float = 15 * 60.0,
                 enable_native_assign: bool = False,
                 maintenance_interval: Optional[float] = None,
                 join: bool = False):
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds)
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.guard = guard or Guard()
        self.server = RpcServer(host, port, service_name="master")
        if raft_election_timeout is None:
            raft_election_timeout = _env_float("WEED_RAFT_ELECTION", 0.8)
        # `join`: this master is NOT part of the configured cluster yet —
        # it boots as a non-voting learner and registers with the leader
        # via /raft/join; the leader commits the membership change and
        # auto-promotes it to voter once its log has caught up
        self.join_mode = bool(join)
        self._join_targets = list(peers or [])
        self.raft = RaftNode(
            self.server.address,
            (peers or []) if join else
            (peers or []) + [self.server.address],
            state_dir=raft_dir,
            election_timeout=raft_election_timeout,
            heartbeat_interval=_env_float("WEED_RAFT_HEARTBEAT", 0.25),
            learner=join)
        self.topo.vid_allocator = self.raft.next_volume_id
        self.topo.max_volume_id = self.raft.max_volume_id
        # location-change feed for /dir/watch long-polls (KeepConnected).
        # feed_id identifies THIS master's sequence space: watch clients
        # must reset their cursor when it changes (failover to a peer)
        self._changes: list[tuple[int, dict]] = []
        self._change_seq = 0
        self._change_cond = threading.Condition()
        self._feed_id = f"{self.server.address}/{random.getrandbits(32):08x}"
        self.topo.on_change = self._record_change
        # cluster membership registry (cluster/cluster.go) + admin locks
        self._members: dict[tuple[str, str], dict] = {}
        self._admin_locks: dict[str, dict] = {}
        self._admin_locks_mutex = threading.Lock()
        self.auto_vacuum_interval = auto_vacuum_interval
        # leader-resident maintenance curator: detectors + the
        # persistent job queue the volume-server workers pull from
        # (the journal lives next to the raft state so a failed-over
        # leader replays the same pending set)
        from ..maintenance.curator import Curator

        self.curator = Curator(self, journal_dir=raft_dir,
                               interval=maintenance_interval)
        # leader-resident health plane: /metrics scrape loop -> ring
        # TSDB -> SLO burn-rate alerts + the merged cluster event
        # journal (GET /cluster/health|alerts|events)
        from .health import HealthPlane

        self.health = HealthPlane(self)
        self.curator.alerts_fn = self.health.firing
        self.raft.on_become_leader = self._on_leader
        self.raft.on_step_down = self._on_step_down
        self.raft.on_membership = self._on_membership
        self._register_routes()
        self._reaper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._grow_lock = threading.Lock()
        self.enable_native_assign = enable_native_assign
        self._native_assign = False
        self._native_assign_owner = False

    @property
    def address(self) -> str:
        return self.server.address

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self.server.start()
        self.raft.start()
        if self.join_mode:
            threading.Thread(target=self._join_loop, daemon=True).start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        self.curator.start()
        self.health.start()
        if self.enable_native_assign:
            self._start_native_assign()

    def stop(self):
        self._stop.set()
        self.health.stop()
        self.curator.stop()
        self.raft.stop()
        with self._change_cond:
            self._change_cond.notify_all()
        if self._native_assign:
            from ..storage import native_engine

            # join the refiller BEFORE clearing: a tick mid-refill could
            # otherwise plant a lease that outlives this master in the
            # process-global registry
            t = getattr(self, "_lease_thread", None)
            if t is not None:
                t.join(timeout=5)
            native_engine.assign_clear()
            if getattr(self, "_native_jwt_owner", False):
                # owner-aware: the master only ever set the WRITE key,
                # so it must only clear the write key — None leaves the
                # read key alone for an in-process volume server whose
                # secured reads would otherwise fail open
                native_engine.server_set_jwt("", None, 10)
                self._native_jwt_owner = False
            if self._native_assign_owner:
                native_engine.server_stop()
            self._native_assign = False
        self.server.stop()

    # -- native assign leases -------------------------------------------------
    def _start_native_assign(self):
        """Serve per-file assigns off the GIL: lease contiguous fid key
        ranges for default-parameter (replication 000, no TTL) assigns
        to the native engine's 'A' handler.  Placement, growth and
        sequencing stay here; the engine only hands out pre-planned
        ranges.  Opt-in (-tcp), like the volume fast path."""
        from ..storage import native_engine

        if not native_engine.available():
            return
        if self.guard.signing:
            # the 'A' handler mints fid-scoped write tokens itself; the
            # keys are engine-global, so set/clear ONLY the write key
            # (None = leave the read key to its owner, the in-process
            # volume server) and clear it on stop
            native_engine.server_set_jwt(
                self.guard.signing.key, None,
                self.guard.signing.expires_after_seconds)
            self._native_jwt_owner = True
        host, port = self.server.address.rsplit(":", 1)
        wanted = int(port) + 20000
        if native_engine.server_port() <= 0:
            try:
                native_engine.server_start(
                    host, wanted if wanted <= 65535 else 0)
                self._native_assign_owner = True
            except OSError:
                pass  # combined process: another daemon's listener
                # serves 'A' (the lease registry is process-global)
        if native_engine.server_port() <= 0:
            return
        self._native_assign = True
        self._lease_thread = threading.Thread(
            target=self._assign_lease_loop, daemon=True)
        self._lease_thread.start()

    def _assign_lease_loop(self):
        """Keep several leases' worth of keys outstanding; leases expire
        individually after REFRESH seconds so placement staleness (a
        leased volume going readonly/oversized/away) is bounded without
        a global clear stalling every assigner at once."""
        from ..storage import native_engine
        from ..storage.ttl import TTL

        # LOW keeps several leases outstanding so a burst cannot drain
        # the pool between 0.2 s refill ticks (a drought answers 503)
        LEASE, LOW, REFRESH_MS = 8192, 32768, 10_000
        # leases follow the master's default placement: replicated
        # volumes are fine — the volume server's native engine fans the
        # leased writes out (or 307s them to its Python handler)
        rp = ReplicaPlacement.parse(self.default_replication)
        rp_byte = rp.to_byte()
        while not self._stop.wait(0.2):
            if not self.raft.is_leader:
                native_engine.assign_clear()
                continue
            try:
                # refill up to a few leases per tick: a single lease per
                # 0.2 s would cap sustained assigns at LEASE/0.2 ≈ 40k/s
                for _ in range(8):
                    if native_engine.assign_remaining(REFRESH_MS) >= LOW:
                        break
                    if self.topo.writable_count("", rp_byte, 0) == 0:
                        self._grow("", rp, TTL.parse(""),
                                   only_if_needed=True)
                    picked = self.topo.pick_for_write("", rp_byte, 0)
                    if picked is None:
                        break
                    vid, locations = picked
                    key, _ = self.topo.assign_file_id(LEASE)
                    native_engine.assign_add_lease(
                        vid, locations[0]["url"],
                        locations[0].get("publicUrl", ""), key,
                        key + LEASE - 1)
            except Exception:
                continue  # lease refill must never die; retry next tick

    def _handle_dir_status(self, req):
        d = self.topo.to_dict()
        if self._native_assign:
            from ..storage import native_engine

            d["native_assign_port"] = native_engine.server_port()
        return d

    def _reap_loop(self):
        # Nothing but liveness reaping runs here.  The periodic garbage
        # vacuum used to ride this loop, synchronously calling every
        # volume server's check/compact/commit — blocking the leader's
        # dead-node reaping (and heartbeat-driven liveness) for the
        # duration.  The curator's garbage-ratio detector now reads the
        # heartbeat state the nodes already report and routes vacuums
        # through the maintenance queue, where a volume-server worker
        # burns its own thread on the holder RPCs.
        while not self._stop.wait(self.topo.pulse_seconds):
            self.topo.reap_dead_nodes()
            try:
                self._drive_shard_resize()
            except Exception as e:  # driver must not kill the reaper
                glog.v(1).infof("shard-resize driver: %s", e)

    def _join_loop(self):
        """Learner registration: keep asking the existing cluster to
        admit us until a leader commits the add_learner entry (the
        leader then replicates/snapshots us up and auto-promotes)."""
        payload = {"address": self.address}
        while not self._stop.wait(1.0):
            with self.raft.lock:
                if self.address in self.raft.voters:
                    return  # promoted: registration complete
            for target in self._join_targets:
                try:
                    call(target, "/raft/join", payload=payload,
                         method="POST", timeout=5)
                    break
                except RpcError as e:
                    hint = (e.headers or {}).get("X-Raft-Leader", "")
                    if hint and hint != target:
                        try:
                            call(hint, "/raft/join", payload=payload,
                                 method="POST", timeout=5)
                            break
                        except RpcError:
                            continue

    def _drive_shard_resize(self):
        """Leader-side two-phase coordinator for filer shard split/merge:
        once every active holder acked its local re-shard, commit the
        slot-map flip; a prepare that cannot complete within
        WEED_SHARD_RESIZE_TIMEOUT is aborted (holders discard staging
        on the next lease)."""
        if not self.raft.is_leader:
            return
        now = time.time()
        with self.raft.lock:
            m = self.raft.fsm.shard_map
            if m.resize is None:
                return
            rz = dict(m.resize)
            frm = m.slots
            pending = m.resize_pending(now)
        kind = (events_mod.SHARD_SPLIT if int(rz["to"]) > frm
                else events_mod.SHARD_MERGE)
        if not pending:
            r = self.raft.propose({"type": "filer.resize",
                                   "op": "commit", "now": now})
            if isinstance(r, dict) and not r.get("error"):
                events_mod.emit(kind, service="master",
                                node=self.address,
                                detail={"from": frm, "to": rz["to"],
                                        "phase": "commit",
                                        "epoch": r.get("epoch")})
        elif now - float(rz.get("started", now)) > \
                _env_float("WEED_SHARD_RESIZE_TIMEOUT", 60.0):
            r = self.raft.propose({"type": "filer.resize",
                                   "op": "abort", "now": now})
            if isinstance(r, dict) and not r.get("error"):
                events_mod.emit(kind, service="master",
                                node=self.address,
                                detail={"from": frm, "to": rz["to"],
                                        "phase": "abort",
                                        "waiting_on": pending})

    # -- routes --------------------------------------------------------------
    def _guarded(self, fn):
        """IP allow-list on admin/UI routes (guard.go WhiteList wrapper)."""
        def wrapped(req):
            peer = req.handler.client_address[0]
            if not self.guard.check_white_list(peer):
                raise RpcError(f"ip {peer} not allowed", 403)
            return fn(req)
        return wrapped

    def _register_routes(self):
        s = self.server
        g = self._guarded
        # every data/control read serves raft + heartbeat-fed topology
        # state that exists ONLY in worker 0 — prefork read replicas
        # forked before any election or heartbeat and must proxy these
        # (only /metrics, /debug/* and the curator worker protocol stay
        # shardable on the master port)
        s.parent_prefixes.update((
            "/dir/", "/cluster/", "/vol/", "/ec/", "/raft/", "/filer/",
            "/col/", "/maintenance/", "/ui", "/readyz"))
        s.add("POST", "/api/heartbeat", self._handle_heartbeat)
        s.add("GET", "/dir/assign", self._handle_assign)
        s.add("POST", "/dir/assign", self._handle_assign)
        s.add("GET", "/dir/lookup", self._handle_lookup)
        s.add("GET", "/dir/status", g(self._handle_dir_status))
        s.add("GET", "/cluster/status", self._handle_cluster_status)
        s.add("POST", "/vol/grow", g(self._handle_grow))
        s.add("POST", "/vol/vacuum", g(self._handle_vacuum))
        s.add("GET", "/vol/status", g(lambda r: self.topo.to_dict()))
        s.add("GET", "/ec/lookup", self._handle_ec_lookup)
        s.add("GET", "/metrics", stats.metrics_handler)
        s.add("GET", "/debug/traces", tracing.traces_handler)
        faults.mount(s)
        profiling.mount(s)
        qos.mount(s)  # quota/lane state; assigns are metered, not queued
        s.add("POST", "/raft/request_vote",
              lambda r: self.raft.handle_request_vote(r.json()))
        s.add("POST", "/raft/append_entries",
              lambda r: self.raft.handle_append_entries(r.json()))
        s.add("GET", "/raft/status", self._handle_raft_status)
        s.add("POST", "/raft/add_peer", g(self._handle_raft_add_peer))
        s.add("POST", "/raft/remove_peer", g(self._handle_raft_remove_peer))
        s.add("POST", "/raft/join", self._handle_raft_join)
        s.add("POST", "/raft/update_peers",
              lambda req: (self.raft.set_peers(req.json()["peers"]),
                           {"peers": self.raft.peers})[1])
        s.add("POST", "/filer/shard_lease", self._handle_filer_shard_lease)
        s.add("POST", "/filer/shard_resize",
              self._handle_filer_shard_resize)
        s.add("GET", "/filer/shards", self._handle_filer_shards)
        s.add("POST", "/dir/leave", self._handle_leave)
        s.add("GET", "/col/list", self._handle_collection_list)
        s.add("POST", "/col/delete", g(self._handle_collection_delete))
        s.add("GET", "/dir/watch", self._handle_watch)
        s.add("POST", "/cluster/register", self._handle_cluster_register)
        s.add("GET", "/cluster/nodes", self._handle_cluster_nodes)
        s.add("POST", "/admin/lock", g(self._handle_admin_lock))
        s.add("POST", "/admin/unlock", g(self._handle_admin_unlock))
        s.add("GET", "/ui", self._handle_ui)
        # maintenance curator: status/queue views, worker lease
        # protocol, pause/run controls
        self.curator.mount(s, g)
        # cluster health plane + liveness/readiness probes
        self.health.mount(s)
        healthz.mount_health(s, ready=self._ready_checks)

    def _ready_checks(self):
        leader = self.raft.leader or ""
        return [("raft", bool(leader), f"leader={leader or 'unknown'}"),
                ("fsm", self.raft.fsm is not None, "raft fsm attached")]

    def _on_leader(self):
        events_mod.emit(events_mod.LEADER_ELECTED, service="master",
                        node=self.address,
                        detail={"term": self.raft.term})

    def _on_membership(self, change: dict):
        """Committed raft.config entry (leader-side): journal it so the
        cluster history shows who joined/left and why."""
        events_mod.emit(events_mod.MEMBERSHIP, service="master",
                        node=change.get("address", ""),
                        detail={"op": change.get("op", ""),
                                "voters": change.get("voters") or [],
                                "learners": change.get("learners") or [],
                                "index": change.get("index", 0)})

    def _on_step_down(self):
        events_mod.emit(events_mod.LEADER_STEPDOWN, service="master",
                        node=self.address,
                        detail={"term": self.raft.term})

    def _handle_ui(self, req):
        """Status page (server/master_ui/master.html)."""
        from ..rpc.http_rpc import Response
        from ..util import ui

        topo = self.topo.to_dict()
        nodes = [(n["id"], dc["id"], rack["id"], n["volumes"],
                  n["ecShards"], n["max"], n["free"])
                 for dc in topo["datacenters"]
                 for rack in dc["racks"] for n in rack["nodes"]]
        layouts = [(l["collection"] or "(default)", l["replication"],
                    l["ttl"], len(l["writables"]))
                   for l in topo["layouts"]]
        body = ui.page(
            f"SeaweedFS-TPU Master {self.address}",
            ui.section("Cluster", ui.kv_table({
                "leader": self.raft.leader or self.address,
                "raft state": self.raft.state,
                "raft peers": ", ".join(self.raft.peers),
                "max volume id": topo["max_volume_id"],
                "volume size limit": self.topo.volume_size_limit,
            })),
            ui.section("Topology", ui.table(
                ("node", "data center", "rack", "volumes", "ec shards",
                 "max", "free"), nodes)),
            ui.section("Volume layouts", ui.table(
                ("collection", "replication", "ttl", "writables"),
                layouts)),
        )
        return Response(body, content_type="text/html; charset=utf-8")

    # -- heartbeat (master_grpc_server.go:60-170) ----------------------------
    def _handle_heartbeat(self, req):
        hb = req.json()
        stats.MasterReceivedHeartbeatCounter.labels("total").inc()
        self.topo.process_heartbeat(hb)
        # keep the raft FSM aware of ids observed on disk (SetMax analogue)
        self.raft.observe_volume_id(self.topo.max_volume_id)
        return {
            "volume_size_limit": self.topo.volume_size_limit,
            "leader": self.raft.is_leader,
            "leader_address": self.raft.leader or self.address,
        }

    def _record_change(self, delta: dict):
        with self._change_cond:
            self._change_seq += 1
            self._changes.append((self._change_seq, delta))
            if len(self._changes) > 10000:
                del self._changes[:5000]
            self._change_cond.notify_all()

    def _handle_watch(self, req):
        """KeepConnected analogue: long-poll volume-location deltas
        (master_grpc_server.go broadcasts VolumeLocation to subscribers)."""
        since = int(req.param("since", "0"))
        timeout = min(float(req.param("timeout", "30")), 60.0)
        deadline = time.time() + timeout
        with self._change_cond:
            while (not self._stop.is_set()
                   and self._change_seq <= since
                   and time.time() < deadline):
                self._change_cond.wait(min(1.0, deadline - time.time()))
            # snapshot seq INSIDE the lock: reporting a seq newer than the
            # delta list would make the client skip that delta forever
            deltas = [{"seq": s, **d} for s, d in self._changes if s > since]
            seq = self._change_seq
            oldest = self._changes[0][0] if self._changes else 0
        return {"seq": seq, "deltas": deltas,
                "feed_id": self._feed_id,
                "leader": self.raft.leader or self.address,
                # a client whose `since` predates the retained window must
                # do a full resync via /dir/lookup
                "resync": bool(since and oldest and since + 1 < oldest)}

    def _proxy_to_leader(self, req, path: str):
        """Non-leader masters forward to the raft leader
        (master_server.go proxyToLeader)."""
        leader = self.raft.leader
        if not leader or leader == self.address:
            raise RpcError("no raft leader elected yet", 503)
        query = urllib.parse.urlencode(req.query)
        return call(leader, path + ("?" + query if query else ""),
                    method="POST" if req.body else "GET",
                    raw=req.body or None, timeout=30)

    # -- assign (master_server_handlers.go:102-165) --------------------------
    def _handle_assign(self, req):
        if not self.raft.is_leader:
            return self._proxy_to_leader(req, "/dir/assign")
        count = int(req.param("count", "1"))
        collection = req.param("collection", "") or ""
        replication = req.param("replication") or self.default_replication
        ttl_s = req.param("ttl", "") or ""
        rp = ReplicaPlacement.parse(replication)
        ttl = TTL.parse(ttl_s)

        # per-collection ops quota: meter assigns before topology work
        # so a runaway writer can't starve other collections' growth
        if qos.enabled() and not qos.QUOTAS.allow(collection,
                                                  ops=float(count)):
            raise RpcError(
                f"collection {collection!r} over its assign quota", 503,
                headers={"Retry-After": qos.retry_after(1, 3)})
        rp_byte, ttl_u32 = rp.to_byte(), ttl.to_uint32()
        if self.topo.writable_count(collection, rp_byte, ttl_u32) == 0:
            self._grow(collection, rp, ttl, only_if_needed=True)
        picked = self.topo.pick_for_write(collection, rp_byte, ttl_u32)
        if picked is None:
            # assign drought is a transient overload (growth may still
            # be racing ahead), not a missing resource: shed with 503 +
            # a jittered Retry-After so policy-aware writers back off
            # without re-arriving in one synchronized wave
            raise RpcError(
                "no writable volumes", 503,
                headers={"Retry-After": qos.retry_after(
                    1, max(1, int(self.topo.pulse_seconds)))})
        vid, locations = picked
        key, _ = self.topo.assign_file_id(count)
        cookie = random.getrandbits(32)
        fid = t.format_file_id(vid, key, cookie)
        result = {
            "fid": fid,
            "url": locations[0]["url"],
            "publicUrl": locations[0]["publicUrl"],
            "count": count,
        }
        if self.guard.signing:
            # JWT scoped to the assigned fid (master_server_handlers.go:150)
            result["auth"] = gen_write_jwt(self.guard.signing, fid)
            # let fid-lease caches cap their lease lifetime to the
            # token's, so a leased fid never outlives its write JWT
            if self.guard.signing.expires_after_seconds > 0:
                result["authExpiresSeconds"] = \
                    self.guard.signing.expires_after_seconds
        return result

    def _grow(self, collection: str, rp: ReplicaPlacement, ttl: TTL,
              target_count: Optional[int] = None,
              only_if_needed: bool = False):
        with self._grow_lock:
            if only_if_needed and self.topo.writable_count(
                    collection, rp.to_byte(), ttl.to_uint32()) > 0:
                return 0  # another request already grew the layout
            option = VolumeGrowOption(collection=collection,
                                      replica_placement=rp, ttl=ttl)
            count = target_count or volume_growth.find_volume_count(
                rp.copy_count())
            grown = 0
            for _ in range(count):
                try:
                    vid, servers = volume_growth.grow_one_volume(
                        self.topo, option,
                        lambda server, vid: call(
                            server.url, "/admin/assign_volume",
                            {"volume": vid, "collection": collection,
                             "replication": str(rp), "ttl": str(ttl)}))
                    grown += 1
                except (ValueError, RpcError):
                    break
            if grown:
                # placement generation bump rides the replicated log, so
                # a failed-over leader knows growth happened here
                try:
                    self.raft.propose({"type": "topology.epoch",
                                       "now": time.time()})
                except RpcError:
                    pass  # lost leadership mid-grow; epoch stays behind
            return grown

    def _handle_grow(self, req):
        if not self.raft.is_leader:
            return self._proxy_to_leader(req, "/vol/grow")
        collection = req.param("collection", "") or ""
        replication = req.param("replication") or self.default_replication
        count = req.param("count")
        rp = ReplicaPlacement.parse(replication)
        ttl = TTL.parse(req.param("ttl", "") or "")
        grown = self._grow(collection, rp, ttl,
                           target_count=int(count) if count else None)
        if grown == 0:
            raise RpcError("cannot grow any volume", 500)
        return {"count": grown}

    # -- lookup (master_server_handlers.go:34-80) ----------------------------
    def _handle_lookup(self, req):
        vid_s = req.param("volumeId")
        if vid_s is None:
            file_id = req.param("fileId")
            if not file_id:
                raise RpcError("volumeId or fileId required", 400)
            vid_s = file_id.split(",")[0]
        vid = int(vid_s.split(",")[0])
        collection = req.param("collection", "") or ""
        locations = self.topo.lookup(vid, collection)
        if not locations and not self.raft.is_leader:
            # volume locations are heartbeat soft state and heartbeats
            # only reach the leader — forward a miss one hop so lookups
            # against any master stay correct (hop guard: no ping-pong
            # while leaderless)
            leader = self.raft.leader
            if leader and leader != self.address \
                    and not req.headers.get("X-Lookup-Hop"):
                q = f"volumeId={vid}"
                if collection:
                    q += "&collection=" + urllib.parse.quote(collection)
                return call(leader, "/dir/lookup?" + q, timeout=5,
                            headers={"X-Lookup-Hop": "1"})
        if not locations:
            raise RpcError(f"volume id {vid} not found", 404)
        return {"volumeId": str(vid), "locations": locations}

    def _handle_ec_lookup(self, req):
        vid = int(req.param("volumeId", "0"))
        result = self.topo.lookup_ec_shards(vid)
        if result is None:
            raise RpcError(f"ec volume {vid} not found", 404)
        return result

    def _handle_cluster_status(self, req):
        return {
            "IsLeader": self.raft.is_leader,
            "Leader": self.raft.leader or "",
            "Peers": self.raft.peers,
            "MaxVolumeId": self.topo.max_volume_id,
            "TopologyEpoch": self.raft.fsm.topology_epoch,
        }

    def _handle_raft_status(self, req):
        """cluster.raft.ps / cluster.check surface: term, commit/applied
        index, per-follower replication lag."""
        return self.raft.status()

    # -- filer shard map (replicated through the master FSM) -----------------
    def _handle_filer_shard_lease(self, req):
        """Store servers acquire/renew/release directory-shard leases;
        every grant commits through the raft log, so a failed-over
        master serves the identical assignment."""
        d = req.json()
        return self.raft.propose({
            "type": "filer.lease", "now": time.time(),
            "holder": d.get("holder", ""),
            "ttl": float(d.get("ttl", 10.0)),
            "release": bool(d.get("release"))})

    def _handle_filer_shards(self, req):
        """Read-only shard-map view for routing clients (served from the
        local FSM replica — any master answers)."""
        m = self.raft.fsm.shard_map
        with self.raft.lock:
            return {"slots": m.slots, "epoch": m.epoch,
                    "map": m.assignments(),
                    "resize": dict(m.resize) if m.resize else None,
                    "leader": self.raft.leader or ""}

    def _handle_filer_shard_resize(self, req):
        """Online shard split/merge (filer.shards.split/merge): `start`
        opens the prepare window, holders `ack` their local re-shard,
        and the leader's driver commits the flip once all acks land
        (or aborts on WEED_SHARD_RESIZE_TIMEOUT)."""
        if not self.raft.is_leader:
            return self._proxy_to_leader(req, "/filer/shard_resize")
        d = req.json()
        op = d.get("op", "")
        if op not in ("start", "ack", "abort"):
            raise RpcError(f"unknown resize op {op!r}", 400)
        cmd = {"type": "filer.resize", "op": op, "now": time.time()}
        if op == "start":
            cmd["to"] = int(d.get("to", 0))
            with self.raft.lock:
                frm = self.raft.fsm.shard_map.slots
        if op == "ack":
            cmd["holder"] = d.get("holder", "")
        r = self.raft.propose(cmd)
        if isinstance(r, dict) and r.get("error"):
            raise RpcError(r["error"], 400)
        if op == "start":
            events_mod.emit(
                events_mod.SHARD_SPLIT if cmd["to"] > frm
                else events_mod.SHARD_MERGE,
                service="master", node=self.address,
                detail={"from": frm, "to": cmd["to"],
                        "phase": "prepare"})
        return r

    def _handle_leave(self, req):
        """A volume server announces departure (VolumeServerLeave);
        unregister immediately instead of waiting for the reaper."""
        p = req.json()
        self.topo.unregister_node(f"{p['ip']}:{p['port']}")
        return {}

    def _handle_raft_add_peer(self, req):
        """cluster.raft.add (shell/command_cluster_raft_add.go): commit
        an add-learner config entry through the log; the leader promotes
        the learner to voter once it has caught up."""
        if not self.raft.is_leader and self.raft.leader:
            return self._proxy_to_leader(req, "/raft/add_peer")
        change = self.raft.add_server(req.json()["address"])
        return {"peers": self.raft.peers, "change": change}

    def _handle_raft_remove_peer(self, req):
        """cluster.raft.remove (shell/command_cluster_raft_remove.go):
        commit a remove config entry; the removed server self-demotes to
        a single-node observer once it sees the committed entry."""
        if not self.raft.is_leader and self.raft.leader:
            return self._proxy_to_leader(req, "/raft/remove_peer")
        try:
            change = self.raft.remove_server(req.json()["address"])
        except ValueError as e:
            raise RpcError(str(e), 400)
        return {"peers": self.raft.peers, "change": change}

    def _handle_raft_join(self, req):
        """A booting learner announces itself (see _join_loop); only the
        leader can commit the config entry, so followers forward."""
        address = req.json().get("address", "")
        if not address:
            raise RpcError("address required", 400)
        if not self.raft.is_leader:
            return self._proxy_to_leader(req, "/raft/join")
        return self.raft.add_server(address)

    # -- collections (master_server_handlers_admin.go /col/*) ----------------
    def _handle_collection_list(self, req):
        names: set[str] = set()
        with self.topo.lock:
            for dc in self.topo.dcs.values():
                for rack in dc.racks.values():
                    for node in rack.nodes.values():
                        for v in node.volumes.values():
                            names.add(v.collection)
                        for vid in node.ec_shards:
                            names.add(
                                self.topo.ec_collections.get(vid, ""))
        return {"collections": sorted(n for n in names if n)}

    def _handle_collection_delete(self, req):
        """Delete every volume of a collection on every server
        (topology.DeleteCollection + DeleteVolume RPC fan-out)."""
        name = req.json().get("collection", "")
        if not name:
            raise RpcError("collection name required", 400)
        deleted = []
        with self.topo.lock:
            targets = [
                (node.url, v.id)
                for dc in self.topo.dcs.values()
                for rack in dc.racks.values()
                for node in rack.nodes.values()
                for v in node.volumes.values() if v.collection == name
            ]
            # EC shards of the collection go too (topology
            # DeleteCollection covers both normal and EC volumes)
            ec_targets = [
                (node.url, vid, sorted(node.ec_shards[vid].shard_ids()))
                for dc in self.topo.dcs.values()
                for rack in dc.racks.values()
                for node in rack.nodes.values()
                for vid in node.ec_shards
                if self.topo.ec_collections.get(vid, "") == name
            ]
        for url, vid in targets:
            try:
                call(url, "/admin/delete_volume",
                     {"volume": vid, "collection": name}, timeout=60)
                deleted.append({"url": url, "volume": vid})
            except RpcError as e:
                deleted.append({"url": url, "volume": vid,
                                "error": str(e)})
        for url, vid, shard_ids in ec_targets:
            try:
                call(url, "/admin/ec/delete_shards",
                     {"volume": vid, "collection": name,
                      "shard_ids": shard_ids}, timeout=60)
                deleted.append({"url": url, "volume": vid,
                                "ec_shards": shard_ids})
            except RpcError as e:
                deleted.append({"url": url, "volume": vid,
                                "ec_shards": shard_ids, "error": str(e)})
        return {"deleted": deleted}

    # -- cluster membership (cluster/cluster.go, KeepConnected registry) -----
    def _handle_cluster_register(self, req):
        p = req.json()
        key = (p.get("type", "filer"), p["address"])
        self._members[key] = {
            "type": key[0], "address": key[1],
            "group": p.get("group", ""),
            "last_seen": time.time(),
        }
        return {"leader": self.raft.leader or self.address,
                "pulse_seconds": self.topo.pulse_seconds}

    def _handle_cluster_nodes(self, req):
        kind = req.param("type", "filer")
        cutoff = time.time() - self.topo.pulse_seconds * 3
        alive = [dict(m) for (k, _), m in self._members.items()
                 if k == kind and m["last_seen"] >= cutoff]
        for m in alive:
            m.pop("last_seen", None)
        return {"cluster_nodes": alive}

    # -- admin locks (LeaseAdminToken, master_grpc_server_admin.go) ----------
    ADMIN_LOCK_TTL = 10.0

    def _handle_admin_lock(self, req):
        p = req.json()
        name = p.get("name", "admin")
        client = p.get("client", "")
        prev_token = int(p.get("token", 0))
        now = time.time()
        with self._admin_locks_mutex:
            lock = self._admin_locks.get(name)
            if (lock is not None and lock["expires"] > now
                    and lock["token"] != prev_token):
                raise RpcError(
                    f"lock {name} held by {lock['client']}", 423)
            token = prev_token if (lock is not None
                                   and lock.get("token") == prev_token
                                   ) else random.getrandbits(63)
            self._admin_locks[name] = {
                "token": token, "client": client,
                "expires": now + self.ADMIN_LOCK_TTL,
            }
        return {"token": token, "expires_at": now + self.ADMIN_LOCK_TTL}

    def _handle_admin_unlock(self, req):
        p = req.json()
        name = p.get("name", "admin")
        with self._admin_locks_mutex:
            lock = self._admin_locks.get(name)
            if lock is not None and lock["token"] == int(p.get("token", 0)):
                del self._admin_locks[name]
        return {}

    # -- vacuum orchestration (topology_vacuum.go) ---------------------------
    def _handle_vacuum(self, req):
        threshold = float(req.param("garbageThreshold",
                                    str(self.garbage_threshold)))
        return {"vacuumed": self._vacuum_pass(threshold)}

    def _vacuum_pass(self, threshold: float) -> list[dict]:
        vacuumed = []
        with self.topo.lock:
            nodes = list(self.topo.nodes.values())
        for node in nodes:
            for vid, info in list(node.volumes.items()):
                try:
                    check = call(node.url, f"/admin/vacuum/check",
                                 {"volume": vid})
                    if check.get("garbage_ratio", 0) <= threshold:
                        continue
                    call(node.url, "/admin/vacuum/compact", {"volume": vid},
                         timeout=600)
                    call(node.url, "/admin/vacuum/commit", {"volume": vid},
                         timeout=600)
                    vacuumed.append({"node": node.url, "volume": vid})
                except RpcError:
                    continue
        return vacuumed
