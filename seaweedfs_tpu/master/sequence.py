"""File-id sequencers (weed/sequence/): monotonically increasing needle keys.

MemorySequencer mirrors memory_sequencer.go (master-local counter, bumped by
heartbeat max_file_key); SnowflakeSequencer mirrors snowflake_sequencer.go
(time-ordered 64-bit ids for multi-master setups without shared state).
"""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_batch(self, count: int) -> int:
        """Reserve `count` ids; returns the first."""
        with self._lock:
            first = self._counter
            self._counter += count
            return first

    def set_max(self, seen: int):
        with self._lock:
            if seen >= self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node id | 12-bit sequence."""

    EPOCH_MS = 1_577_836_800_000  # 2020-01-01

    def __init__(self, node_id: int):
        if not 0 <= node_id < 1024:
            raise ValueError("snowflake node id must be in [0, 1024)")
        self.node_id = node_id
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_batch(self, count: int) -> int:
        with self._lock:
            first = None
            for _ in range(count):
                now = int(time.time() * 1000) - self.EPOCH_MS
                if now == self._last_ms:
                    self._seq = (self._seq + 1) & 0xFFF
                    if self._seq == 0:
                        while now <= self._last_ms:
                            now = int(time.time() * 1000) - self.EPOCH_MS
                else:
                    self._seq = 0
                self._last_ms = now
                value = (now << 22) | (self.node_id << 12) | self._seq
                if first is None:
                    first = value
            return first

    def set_max(self, seen: int):
        pass  # time-ordered; no catch-up needed
