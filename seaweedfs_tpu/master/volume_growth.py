"""Volume growth: replica-placement-aware slot finding + volume creation.

Parity with weed/topology/volume_growth.go:106-230: pick a main data
center / rack / node plus the "other" nodes demanded by the replica
placement (DiffDataCenter / DiffRack / SameRack counts), weighting choices
by free slots, then allocate the volume on every chosen server.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import TTL
from .topology import DataCenter, DataNode, Rack, Topology

# grow this many logical volumes per growth request, by copy count
# (master_server.go:92-96 defaults)
GROWTH_COUNTS = {1: 7, 2: 6, 3: 3}
DEFAULT_GROWTH_COUNT = 1


@dataclass
class VolumeGrowOption:
    collection: str = ""
    replica_placement: ReplicaPlacement = field(
        default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    preferred_data_center: str = ""
    preferred_rack: str = ""
    preferred_node: str = ""


def find_volume_count(copy_count: int) -> int:
    return GROWTH_COUNTS.get(copy_count, DEFAULT_GROWTH_COUNT)


def _pick_by_weight(candidates: list, count: int,
                    filter_fn: Callable) -> tuple[object, list]:
    """Pick `count` distinct nodes weighted by free slots; first is main.
    Raises ValueError when not enough candidates qualify."""
    qualified = []
    for c in candidates:
        try:
            filter_fn(c)
            qualified.append(c)
        except ValueError:
            continue
    if len(qualified) < count:
        raise ValueError(
            f"only {len(qualified)} of {len(candidates)} candidates "
            f"qualify, need {count}")
    picked = []
    pool = list(qualified)
    for _ in range(count):
        weights = [max(1, c.available_slots()) for c in pool]
        choice = random.choices(pool, weights=weights, k=1)[0]
        pool.remove(choice)
        picked.append(choice)
    return picked[0], picked[1:]


def find_empty_slots(topo: Topology, option: VolumeGrowOption
                     ) -> list[DataNode]:
    """The three-level placement search (findEmptySlotsForOneVolume)."""
    rp = option.replica_placement

    def dc_filter(dc: DataCenter):
        if (option.preferred_data_center
                and dc.id != option.preferred_data_center):
            raise ValueError("not preferred dc")
        if len(dc.racks) < rp.diff_rack + 1:
            raise ValueError("not enough racks")
        if dc.available_slots() < rp.diff_rack + rp.same_rack + 1:
            raise ValueError("not enough free slots in dc")
        racks_ok = sum(
            1 for rack in dc.racks.values()
            if sum(1 for n in rack.nodes.values()
                   if n.available_slots() >= 1) >= rp.same_rack + 1)
        if racks_ok < rp.diff_rack + 1:
            raise ValueError("not enough racks with free nodes")

    def rack_filter(rack: Rack):
        if option.preferred_rack and rack.id != option.preferred_rack:
            raise ValueError("not preferred rack")
        if rack.available_slots() < rp.same_rack + 1:
            raise ValueError("not enough free slots in rack")
        nodes_ok = sum(1 for n in rack.nodes.values()
                       if n.available_slots() >= 1)
        if nodes_ok < rp.same_rack + 1:
            raise ValueError("not enough free nodes in rack")

    def node_filter(node: DataNode):
        if option.preferred_node and node.id != option.preferred_node:
            raise ValueError("not preferred node")
        if node.available_slots() < 1:
            raise ValueError("node full")

    with topo.lock:
        main_dc, other_dcs = _pick_by_weight(
            list(topo.dcs.values()), rp.diff_dc + 1, dc_filter)
        main_rack, other_racks = _pick_by_weight(
            list(main_dc.racks.values()), rp.diff_rack + 1, rack_filter)
        main_node, other_nodes = _pick_by_weight(
            list(main_rack.nodes.values()), rp.same_rack + 1, node_filter)

        servers = [main_node] + other_nodes
        for rack in other_racks:
            node, _ = _pick_by_weight(list(rack.nodes.values()), 1,
                                      node_filter)
            servers.append(node)
        for dc in other_dcs:
            rack, _ = _pick_by_weight(list(dc.racks.values()), 1,
                                      rack_filter)
            node, _ = _pick_by_weight(list(rack.nodes.values()), 1,
                                      node_filter)
            servers.append(node)
        return servers


def grow_one_volume(topo: Topology, option: VolumeGrowOption,
                    allocate_fn: Callable[[DataNode, int], None]
                    ) -> tuple[int, list[DataNode]]:
    """Find placement, allocate a new vid, call allocate_fn per server.
    allocate_fn raises to abort (partial allocations are the caller's to
    clean up, as in the reference)."""
    servers = find_empty_slots(topo, option)
    vid = topo.next_volume_id()
    for server in servers:
        allocate_fn(server, vid)
    return vid, servers
