"""Master follower: a read-optimized lookup/assign cache node.

Parity with weed/command/master_follower.go: a process that keeps a
vid→locations cache warm from the true masters' update stream, answers
/dir/lookup locally, and forwards /dir/assign to the leader.  Useful to
fan out read lookups in large clusters without raft participation.
"""

from __future__ import annotations

import urllib.parse
from typing import Optional

from ..rpc.http_rpc import RpcError, RpcServer, call
from ..wdclient import MasterClient


class MasterFollower:
    def __init__(self, masters: list[str], host: str = "127.0.0.1",
                 port: int = 0):
        self.client = MasterClient(masters, name="master_follower")
        self.server = RpcServer(host, port)
        s = self.server
        s.add("GET", "/dir/lookup", self._handle_lookup)
        s.add("GET", "/dir/assign", self._handle_assign)
        s.add("POST", "/dir/assign", self._handle_assign)
        s.add("GET", "/cluster/status", self._handle_status)

    @property
    def address(self) -> str:
        return self.server.address

    def start(self):
        self.client.start()
        self.server.start()

    def stop(self):
        self.client.stop()
        self.server.stop()

    def _handle_lookup(self, req):
        vid_s = req.param("volumeId")
        if vid_s is None:
            file_id = req.param("fileId")
            if not file_id:
                raise RpcError("volumeId or fileId required", 400)
            vid_s = file_id.split(",")[0]
        vid = int(vid_s.split(",")[0])
        locations = self.client.lookup(vid)
        if not locations:
            raise RpcError(f"volume id {vid} not found", 404)
        return {"volumeId": str(vid), "locations": locations}

    def _handle_assign(self, req):
        query = urllib.parse.urlencode(req.query)
        return call(self.client.current_master,
                    "/dir/assign" + ("?" + query if query else ""),
                    timeout=30)

    def _handle_status(self, req):
        return {"IsLeader": False, "Follower": True,
                "Masters": self.client.masters,
                "CachedVolumes": len(self.client.vid_map)}
