"""Raft consensus for the master control plane.

The reference runs hashicorp/raft with a deliberately tiny FSM: the only
replicated state is MaxVolumeId (weed/server/raft_server.go:52-100 — the
FSM's Apply handles one command type, MaxVolumeIdCommand), persisted in
boltdb with leader election deciding which master may assign volume ids.

This implementation keeps that shape: full leader election (randomized
timeouts, term voting) with the single-integer FSM shipped inline on every
AppendEntries — because the state is one monotonically-increasing integer
and only the leader mutates it, the heartbeat IS the log replication, and
a majority ack of the new value before use gives the same linearizable
volume-id allocation the reference gets from raft.Apply.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from ..rpc.http_rpc import RpcError, call
from ..util import glog

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    def __init__(self, self_address: str, peers: list[str],
                 state_dir: str = "",
                 election_timeout: float = 0.8,
                 heartbeat_interval: float = 0.25):
        """peers includes self_address."""
        self.address = self_address
        self.peers = sorted(set(peers) | {self_address})
        self.state_dir = state_dir
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self.lock = threading.RLock()
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader: Optional[str] = None
        self.max_volume_id = 0
        self.on_become_leader: Optional[Callable[[], None]] = None

        self._last_heard = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._load_state()
        if len(self.peers) > 1 and not self.state_dir:
            # raft safety requires durable term/vote: a restarted node with
            # amnesia can double-vote in one term and elect two leaders
            glog.warningf(
                "raft: %d-peer cluster without -mdir: term/vote state is "
                "NOT persisted; a master restart can elect split leaders",
                len(self.peers))

    # -- persistence (raft_server.go boltdb store analogue) ------------------
    def _state_path(self) -> str:
        return os.path.join(self.state_dir, "raft_state.json")

    def _load_state(self):
        if not self.state_dir:
            return
        try:
            with open(self._state_path()) as f:
                d = json.load(f)
            self.term = int(d.get("term", 0))
            self.voted_for = d.get("voted_for")
            self.max_volume_id = int(d.get("max_volume_id", 0))
            # peers are persisted only once membership was changed via
            # cluster.raft.add/remove — a plain restart keeps the
            # configured list (addresses are identity here, so saving the
            # bootstrap list would resurrect stale self-addresses)
            persisted = d.get("peers")
            if persisted is not None:
                self.peers = sorted(set(persisted) | {self.address})
                self._peers_persisted = True
        except (OSError, ValueError):
            pass

    def _save_state(self):
        if not self.state_dir:
            return
        state = {"term": self.term, "voted_for": self.voted_for,
                 "max_volume_id": self.max_volume_id}
        if getattr(self, "_peers_persisted", False):
            state["peers"] = self.peers
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._state_path())

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if len(self.peers) == 1:
            # single-node cluster: immediately leader (no quorum needed)
            with self.lock:
                self.state = LEADER
                self.leader = self.address
            if self.on_become_leader:
                self.on_become_leader()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def quorum(self) -> int:
        return len(self.peers) // 2 + 1

    # -- membership changes (shell cluster.raft.add/remove) ------------------
    # The reference drives these through hashicorp/raft's joint-consensus
    # log.  Here membership is an administrative broadcast: the serving
    # master updates its list and pushes the new list to every old AND new
    # peer, so no node is left believing in a divergent quorum.

    def set_peers(self, peers: list[str]):
        """Adopt a broadcast membership list (internal /raft/update_peers).
        A node absent from the list has been expelled: it drops to a
        standalone cluster instead of continuing to campaign against its
        former peers."""
        with self.lock:
            if self.address in peers:
                self.peers = sorted(set(peers))
            else:
                self.peers = [self.address]
                self.state = FOLLOWER
                self.leader = None
            self._peers_persisted = True
            self._save_state()

    def _broadcast_membership(self, notify: set[str]):
        for peer in notify - {self.address}:
            try:
                call(peer, "/raft/update_peers", {"peers": self.peers},
                     timeout=5)
            except RpcError:
                pass  # unreachable peer adopts the list when it rejoins

    def add_peer(self, address: str):
        with self.lock:
            if address in self.peers:
                return
            self.peers = sorted(set(self.peers) | {address})
            self._peers_persisted = True
            self._save_state()
            notify = set(self.peers)
        self._broadcast_membership(notify)

    def remove_peer(self, address: str):
        if address == self.address:
            raise ValueError("cannot remove self from the raft cluster")
        with self.lock:
            if address not in self.peers:
                return
            notify = set(self.peers)  # incl. the removed node
            self.peers = [p for p in self.peers if p != address]
            self._peers_persisted = True
            self._save_state()
        self._broadcast_membership(notify)

    # -- main loop -----------------------------------------------------------
    def _run(self):
        while not self._stop.is_set():
            if self.state == LEADER:
                self._broadcast_heartbeat()
                self._stop.wait(self.heartbeat_interval)
            else:
                timeout = self.election_timeout * (1 + random.random())
                self._stop.wait(0.05)
                if time.monotonic() - self._last_heard > timeout:
                    self._campaign()

    def _campaign(self):
        with self.lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.address
            self.leader = None
            term = self.term
            self._save_state()
        votes = 1
        for peer in self.peers:
            if peer == self.address:
                continue
            try:
                r = call(peer, "/raft/request_vote",
                         {"term": term, "candidate": self.address,
                          "max_volume_id": self.max_volume_id},
                         timeout=1)
                if r.get("granted"):
                    votes += 1
                elif r.get("term", 0) > term:
                    self._step_down(r["term"])
                    return
            except RpcError:
                continue
        with self.lock:
            if self.state != CANDIDATE or self.term != term:
                return
            if votes >= self.quorum():
                glog.infof("raft: %s elected leader for term %d (%d votes)",
                           self.address, term, votes)
                self.state = LEADER
                self.leader = self.address
            else:
                self.state = FOLLOWER
                self._last_heard = time.monotonic()
                return
        if self.on_become_leader:
            self.on_become_leader()
        self._broadcast_heartbeat()

    def _step_down(self, term: int):
        with self.lock:
            if term > self.term:
                self.term = term
                self.voted_for = None
                self._save_state()
            if self.state != FOLLOWER:
                glog.infof("raft: %s stepping down at term %d",
                           self.address, term)
            self.state = FOLLOWER
            self._last_heard = time.monotonic()

    def _broadcast_heartbeat(self) -> int:
        """Returns the number of peers (incl. self) sharing our state."""
        with self.lock:
            payload = {"term": self.term, "leader": self.address,
                       "max_volume_id": self.max_volume_id}
        acked = 1
        for peer in self.peers:
            if peer == self.address:
                continue
            try:
                r = call(peer, "/raft/append_entries", payload, timeout=1)
                if r.get("term", 0) > payload["term"]:
                    self._step_down(r["term"])
                    return acked
                if r.get("ok"):
                    acked += 1
            except RpcError:
                continue
        return acked

    # -- RPC handlers --------------------------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        term = int(req["term"])
        candidate = req["candidate"]
        candidate_state = int(req.get("max_volume_id", 0))
        with self.lock:
            if term < self.term:
                return {"granted": False, "term": self.term}
            if term > self.term:
                self.term = term
                self.voted_for = None
                if self.state != FOLLOWER:
                    self.state = FOLLOWER
            if (self.voted_for in (None, candidate)
                    and candidate_state >= self.max_volume_id):
                self.voted_for = candidate
                self._last_heard = time.monotonic()
                self._save_state()
                return {"granted": True, "term": self.term}
            self._save_state()
            return {"granted": False, "term": self.term}

    def handle_append_entries(self, req: dict) -> dict:
        term = int(req["term"])
        with self.lock:
            if term < self.term:
                return {"ok": False, "term": self.term}
            if term > self.term:
                self.term = term
                self.voted_for = None
                self._save_state()
            self.state = FOLLOWER
            self.leader = req["leader"]
            self._last_heard = time.monotonic()
            incoming = int(req.get("max_volume_id", 0))
            if incoming > self.max_volume_id:
                self.max_volume_id = incoming
                self._save_state()
            return {"ok": True, "term": self.term}

    # -- the FSM: MaxVolumeId allocation (raft_server.go:78) -----------------
    def next_volume_id(self) -> int:
        """Allocate the next volume id, majority-replicated before use."""
        with self.lock:
            if self.state != LEADER:
                raise RpcError("not raft leader", 409)
            self.max_volume_id += 1
            vid = self.max_volume_id
            self._save_state()
        if len(self.peers) > 1:
            acked = self._broadcast_heartbeat()
            if acked < self.quorum():
                raise RpcError(
                    f"volume id {vid} not replicated to quorum", 503)
        return vid

    def observe_volume_id(self, vid: int):
        """Fold in a volume id seen in a heartbeat (SetMax semantics)."""
        with self.lock:
            if vid > self.max_volume_id:
                self.max_volume_id = vid
                self._save_state()
