"""Raft consensus for the master control plane.

The reference runs hashicorp/raft with a deliberately tiny FSM: the only
replicated state is MaxVolumeId (weed/server/raft_server.go:52-100 — the
FSM's Apply handles one command type, MaxVolumeIdCommand), persisted in
boltdb with snapshots.

This implementation runs the full raft machinery — a persisted replicated
LOG with prev-index/term consistency checks, per-follower next/match
tracking, majority commit, and log-compaction snapshots shipped to
stragglers — over a COMMAND-TYPED FSM (master/fsm.py): volume-id
allocation, topology epochs, every curator queue mutation, and the filer
shard map all commit through quorum before they are acknowledged.  A
failed-over leader on a different node resumes with the exact
pending/leased curator set and never double-allocates an id: propose()
returns only after the entry COMMITS, so a failed quorum leaves the
entry uncommitted and the result unreturned (at-most-once).

Seams for deterministic testing: `clock` (monotonic source), `rpc`
(peer transport) and `rand` (election jitter) are instance attributes,
so the fuzz suite drives whole clusters in-process on a fake clock with
partitionable transports and zero threads.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from ..rpc.http_rpc import RpcError, call
from ..util import glog
from .fsm import ControlFSM

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

SNAPSHOT_THRESHOLD = 64  # applied entries kept before compaction

# propose() results retained past the commit point, so a proposer that
# lost the race to _advance_commit can still collect its return value
_RESULT_WINDOW = 512


def _upgrade_entry(e: dict) -> dict:
    """Accept pre-command-log persisted entries ({"max_volume_id": N})
    by rewriting them as volume.assign commands."""
    if "cmd" in e:
        return e
    return {"index": int(e["index"]), "term": int(e["term"]),
            "cmd": {"type": "volume.assign",
                    "value": int(e.get("max_volume_id", 0))}}


class RaftNode:
    def __init__(self, self_address: str, peers: list[str],
                 state_dir: str = "",
                 election_timeout: float = 0.8,
                 heartbeat_interval: float = 0.25,
                 clock: Optional[Callable[[], float]] = None,
                 transport: Optional[Callable] = None,
                 fsm: Optional[ControlFSM] = None):
        """peers includes self_address."""
        self.address = self_address
        self.peers = sorted(set(peers) | {self_address})
        self.state_dir = state_dir
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.clock = clock or time.monotonic
        self.rpc = transport or call
        self.rand = random.random

        self.lock = threading.RLock()
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader: Optional[str] = None
        self.on_become_leader: Optional[Callable[[], None]] = None
        self.on_step_down: Optional[Callable[[], None]] = None

        # -- replicated log + snapshot (boltdb store analogue) ---------------
        # entry: {"index": i, "term": t, "cmd": {...}}; the entry at
        # global index i lives at log[i - snapshot_index - 1]
        self.fsm = fsm or ControlFSM()
        self.log: list[dict] = []
        self.snapshot_index = 0
        self.snapshot_term = 0
        self.snapshot_fsm: dict = {}  # FSM snapshot at the compaction point
        self.commit_index = 0
        self.applied_index = 0
        self._apply_results: dict[int, object] = {}
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        # leader lease: the last instant a quorum acknowledged this
        # leader; clients treat the hinted leader as fresh within it
        self._lease_until = 0.0

        self._last_heard = self.clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._load_state()
        self._sync_metrics()
        if len(self.peers) > 1 and not self.state_dir:
            # raft safety requires durable term/vote: a restarted node with
            # amnesia can double-vote in one term and elect two leaders
            glog.warningf(
                "raft: %d-peer cluster without -mdir: term/vote/log state "
                "is NOT persisted; a master restart can elect split leaders",
                len(self.peers))

    # -- FSM views -----------------------------------------------------------
    @property
    def max_volume_id(self) -> int:
        return self.fsm.max_volume_id

    # -- log helpers (lock held) ----------------------------------------------
    def _last_index(self) -> int:
        return self.snapshot_index + len(self.log)

    def _last_term(self) -> int:
        return self.log[-1]["term"] if self.log else self.snapshot_term

    def _entry(self, index: int) -> Optional[dict]:
        k = index - self.snapshot_index - 1
        if 0 <= k < len(self.log):
            return self.log[k]
        return None

    def _term_at(self, index: int) -> Optional[int]:
        if index == self.snapshot_index:
            return self.snapshot_term
        e = self._entry(index)
        return e["term"] if e else None

    def _pending_value(self) -> int:
        """Highest MaxVolumeId anywhere in the log (committed or not) —
        the allocation floor, so concurrent/unacked entries never collide."""
        value = self.fsm.max_volume_id
        for e in self.log:
            cmd = e["cmd"]
            if cmd.get("type") == "volume.assign" \
                    and int(cmd.get("value", 0)) > value:
                value = int(cmd["value"])
        return value

    def _advance_commit(self, new_commit: int):
        """Apply newly-committed entries to the FSM, then maybe compact."""
        new_commit = min(new_commit, self._last_index())
        if new_commit <= self.commit_index:
            return
        for i in range(self.commit_index + 1, new_commit + 1):
            e = self._entry(i)
            if e is not None:
                self._apply_results[i] = self.fsm.apply(e["cmd"])
        self.commit_index = new_commit
        self.applied_index = new_commit
        if len(self._apply_results) > _RESULT_WINDOW:
            floor = new_commit - _RESULT_WINDOW
            for i in [i for i in self._apply_results if i <= floor]:
                del self._apply_results[i]
        self._maybe_snapshot()
        self._save_state()
        self._sync_metrics()

    def _maybe_snapshot(self):
        """Compact the applied prefix once it outgrows the threshold
        (raft_server.go:91-100 snapshot persistence)."""
        applied = self.commit_index - self.snapshot_index
        if applied < SNAPSHOT_THRESHOLD:
            return
        cut = self.commit_index - self.snapshot_index  # entries to drop
        self.snapshot_term = self._term_at(self.commit_index) or \
            self.snapshot_term
        self.snapshot_index = self.commit_index
        self.snapshot_fsm = self.fsm.snapshot()
        self.log = self.log[cut:]

    def _sync_metrics(self):
        try:
            from ..stats import metrics as _m

            _m.RaftTermGauge.labels(self.address).set(self.term)
            _m.RaftCommitIndexGauge.labels(self.address) \
                .set(self.commit_index)
            _m.RaftAppliedLagGauge.labels(self.address) \
                .set(self._last_index() - self.applied_index)
        except Exception:
            pass  # metrics must never wedge consensus

    # -- persistence -----------------------------------------------------------
    def _state_path(self) -> str:
        return os.path.join(self.state_dir, "raft_state.json")

    def _load_state(self):
        if not self.state_dir:
            return
        try:
            with open(self._state_path()) as f:
                d = json.load(f)
            self.term = int(d.get("term", 0))
            self.voted_for = d.get("voted_for")
            snap = d.get("snapshot", {})
            self.snapshot_index = int(snap.get("index", 0))
            self.snapshot_term = int(snap.get("term", 0))
            fsm_snap = snap.get("fsm")
            if fsm_snap is None:
                # legacy MaxVolumeId-only snapshot
                fsm_snap = {"max_volume_id":
                            int(snap.get("max_volume_id",
                                         d.get("max_volume_id", 0)))}
            self.snapshot_fsm = fsm_snap
            self.log = [_upgrade_entry(e) for e in d.get("log", [])]
            self.commit_index = max(int(d.get("commit_index", 0)),
                                    self.snapshot_index)
            # replay: restore the snapshot FSM, apply the committed suffix
            self.fsm.restore(self.snapshot_fsm)
            for e in self.log:
                if e["index"] <= self.commit_index:
                    self.fsm.apply(e["cmd"])
            self.applied_index = self.commit_index
            # peers are persisted only once membership was changed via
            # cluster.raft.add/remove — a plain restart keeps the
            # configured list (addresses are identity here, so saving the
            # bootstrap list would resurrect stale self-addresses)
            persisted = d.get("peers")
            if persisted is not None:
                self.peers = sorted(set(persisted) | {self.address})
                self._peers_persisted = True
        except (OSError, ValueError):
            pass

    def _save_state(self):
        if not self.state_dir:
            return
        state = {
            "term": self.term, "voted_for": self.voted_for,
            "commit_index": self.commit_index,
            "snapshot": {"index": self.snapshot_index,
                         "term": self.snapshot_term,
                         "fsm": self.snapshot_fsm},
            "log": self.log,
        }
        if getattr(self, "_peers_persisted", False):
            state["peers"] = self.peers
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._state_path())

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if len(self.peers) == 1:
            # single-node cluster: immediately leader (no quorum needed)
            with self.lock:
                self.state = LEADER
                self.leader = self.address
                self._lease_until = self.clock() + self.election_timeout
            if self.on_become_leader:
                self.on_become_leader()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def quorum(self) -> int:
        return len(self.peers) // 2 + 1

    def _leader_hint(self) -> Optional[dict]:
        """Response headers pointing a rejected caller at the leader."""
        leader = self.leader
        if leader and leader != self.address:
            return {"X-Raft-Leader": leader}
        return None

    # -- membership changes (shell cluster.raft.add/remove) ------------------
    # The reference drives these through hashicorp/raft's joint-consensus
    # log.  Here membership is an administrative broadcast: the serving
    # master updates its list and pushes the new list to every old AND new
    # peer, so no node is left believing in a divergent quorum.

    def set_peers(self, peers: list[str]):
        """Adopt a broadcast membership list (internal /raft/update_peers).
        A node absent from the list has been expelled: it drops to a
        standalone cluster instead of continuing to campaign against its
        former peers."""
        with self.lock:
            if self.address in peers:
                self.peers = sorted(set(peers))
            else:
                self.peers = [self.address]
                self.state = FOLLOWER
                self.leader = None
            self._peers_persisted = True
            self._save_state()

    def _broadcast_membership(self, notify: set[str]):
        for peer in notify - {self.address}:
            try:
                self.rpc(peer, "/raft/update_peers",
                         {"peers": self.peers}, timeout=5)
            except RpcError:
                pass  # unreachable peer adopts the list when it rejoins

    def add_peer(self, address: str):
        with self.lock:
            if address in self.peers:
                return
            self.peers = sorted(set(self.peers) | {address})
            self._next_index[address] = self._last_index() + 1
            self._match_index[address] = 0
            self._peers_persisted = True
            self._save_state()
            notify = set(self.peers)
        self._broadcast_membership(notify)

    def remove_peer(self, address: str):
        if address == self.address:
            raise ValueError("cannot remove self from the raft cluster")
        with self.lock:
            if address not in self.peers:
                return
            notify = set(self.peers)  # incl. the removed node
            self.peers = [p for p in self.peers if p != address]
            self._peers_persisted = True
            self._save_state()
        self._broadcast_membership(notify)

    # -- main loop -----------------------------------------------------------
    def tick(self) -> float:
        """One scheduler step (factored out of _run so tests can drive
        a node on a fake clock without its thread).  Returns how long
        the loop should sleep before the next step."""
        if self.state == LEADER:
            self._broadcast_round()
            return self.heartbeat_interval
        timeout = self.election_timeout * (1 + self.rand())
        if self.clock() - self._last_heard > timeout:
            self._campaign()
        return 0.05

    def _run(self):
        while not self._stop.is_set():
            try:
                delay = self.tick()
            except Exception as e:  # consensus loop must never die
                glog.warningf("raft: tick failed on %s: %s",
                              self.address, e)
                delay = 0.05
            self._stop.wait(delay)

    def _campaign(self):
        with self.lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.address
            self.leader = None
            term = self.term
            last_index = self._last_index()
            last_term = self._last_term()
            self._save_state()
        votes = 1
        for peer in self.peers:
            if peer == self.address:
                continue
            try:
                r = self.rpc(peer, "/raft/request_vote",
                             {"term": term, "candidate": self.address,
                              "last_log_index": last_index,
                              "last_log_term": last_term},
                             timeout=1)
                if r.get("granted"):
                    votes += 1
                elif r.get("term", 0) > term:
                    self._step_down(r["term"])
                    return
            except RpcError:
                continue
        with self.lock:
            if self.state != CANDIDATE or self.term != term:
                return
            if votes >= self.quorum():
                glog.infof("raft: %s elected leader for term %d (%d votes)",
                           self.address, term, votes)
                self.state = LEADER
                self.leader = self.address
                # no-op entry of OUR term: prior-term entries cannot
                # commit by counting (§5.4.2), so without this the new
                # leader's FSM would lag until the next real proposal
                self.log.append({"index": self._last_index() + 1,
                                 "term": self.term,
                                 "cmd": {"type": "raft.noop"}})
                for peer in self.peers:
                    self._next_index[peer] = self._last_index()
                    self._match_index[peer] = 0
                self._save_state()
            else:
                self.state = FOLLOWER
                self._last_heard = self.clock()
                return
        self._sync_metrics()
        if self.on_become_leader:
            self.on_become_leader()
        self._broadcast_round()

    def _step_down(self, term: int):
        with self.lock:
            if term > self.term:
                self.term = term
                self.voted_for = None
                self._save_state()
            was_leader = self.state == LEADER
            if self.state != FOLLOWER:
                glog.infof("raft: %s stepping down at term %d",
                           self.address, term)
            self.state = FOLLOWER
            self._last_heard = self.clock()
        self._sync_metrics()
        if was_leader and self.on_step_down:
            self.on_step_down()

    # -- leader-side replication ----------------------------------------------
    def _replicate_to(self, peer: str) -> bool:
        """One AppendEntries (or snapshot-install) round to a follower."""
        with self.lock:
            if self.state != LEADER:
                return False
            term = self.term
            ni = self._next_index.get(peer, self._last_index() + 1)
            payload = {"term": term, "leader": self.address,
                       "commit_index": self.commit_index}
            if ni <= self.snapshot_index:
                # follower is behind the compaction horizon: ship the
                # snapshot (InstallSnapshot), then the remaining log
                payload["snapshot"] = {
                    "index": self.snapshot_index,
                    "term": self.snapshot_term,
                    "fsm": self.snapshot_fsm}
                payload["prev_index"] = self.snapshot_index
                payload["prev_term"] = self.snapshot_term
                payload["entries"] = list(self.log)
            else:
                payload["prev_index"] = ni - 1
                payload["prev_term"] = self._term_at(ni - 1) or 0
                payload["entries"] = [
                    e for e in self.log if e["index"] >= ni]
            sent_last = self._last_index()
        try:
            r = self.rpc(peer, "/raft/append_entries", payload, timeout=1)
        except RpcError:
            return False
        with self.lock:
            if r.get("term", 0) > self.term:
                pass  # handled below, outside the lock
            elif r.get("ok"):
                self._match_index[peer] = sent_last
                self._next_index[peer] = sent_last + 1
                return True
            else:
                # consistency miss: back off to the follower's tail
                follower_last = int(r.get("last_index", 0))
                self._next_index[peer] = max(
                    min(ni - 1, follower_last + 1), 1)
        if r.get("term", 0) > term:
            self._step_down(r["term"])
        return False

    def _broadcast_round(self) -> int:
        """Replicate to every follower; advance commit on majority match.
        Returns the number of peers (incl. self) matching our last index."""
        peers = [p for p in self.peers if p != self.address]
        acked = 1
        for peer in peers:
            if self._replicate_to(peer):
                acked += 1
        with self.lock:
            if self.state != LEADER:
                return acked
            if acked >= self.quorum():
                # a quorum just heard from us: refresh the leader lease
                self._lease_until = self.clock() + self.election_timeout
            # majority-match commit rule (only entries of the current term
            # commit by counting, per the raft paper's §5.4.2 restriction)
            for n in range(self._last_index(), self.commit_index, -1):
                matches = 1 + sum(
                    1 for p in peers if self._match_index.get(p, 0) >= n)
                if matches >= self.quorum() \
                        and self._term_at(n) == self.term:
                    self._advance_commit(n)
                    break
        return acked

    # -- RPC handlers --------------------------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        term = int(req["term"])
        candidate = req["candidate"]
        c_last_term = int(req.get("last_log_term", 0))
        c_last_index = int(req.get("last_log_index", 0))
        with self.lock:
            if term < self.term:
                return {"granted": False, "term": self.term}
            if term > self.term:
                self.term = term
                self.voted_for = None
                if self.state != FOLLOWER:
                    self.state = FOLLOWER
            # up-to-date check on the LOG (raft §5.4.1), not the FSM
            up_to_date = (c_last_term > self._last_term()
                          or (c_last_term == self._last_term()
                              and c_last_index >= self._last_index()))
            if self.voted_for in (None, candidate) and up_to_date:
                self.voted_for = candidate
                self._last_heard = self.clock()
                self._save_state()
                return {"granted": True, "term": self.term}
            self._save_state()
            return {"granted": False, "term": self.term}

    def handle_append_entries(self, req: dict) -> dict:
        term = int(req["term"])
        with self.lock:
            if term < self.term:
                return {"ok": False, "term": self.term,
                        "last_index": self._last_index()}
            if term > self.term:
                self.term = term
                self.voted_for = None
            self.state = FOLLOWER
            self.leader = req["leader"]
            self._last_heard = self.clock()

            snap = req.get("snapshot")
            if snap and snap["index"] > self.snapshot_index \
                    and snap["index"] > self.commit_index:
                # InstallSnapshot: replace everything up to the snapshot
                self.snapshot_index = int(snap["index"])
                self.snapshot_term = int(snap["term"])
                self.snapshot_fsm = snap.get("fsm") or {
                    "max_volume_id": int(snap.get("max_volume_id", 0))}
                self.log = []
                self.commit_index = self.snapshot_index
                self.applied_index = self.snapshot_index
                self.fsm.restore(self.snapshot_fsm)

            prev_index = int(req.get("prev_index", 0))
            prev_term = int(req.get("prev_term", 0))
            if prev_index > self._last_index():
                self._save_state()
                return {"ok": False, "term": self.term,
                        "last_index": self._last_index()}
            if prev_index > self.snapshot_index:
                local = self._term_at(prev_index)
                if local != prev_term:
                    # conflicting suffix: drop it and report our new tail
                    self.log = self.log[:prev_index - self.snapshot_index
                                        - 1]
                    self._save_state()
                    return {"ok": False, "term": self.term,
                            "last_index": self._last_index()}
            for e in req.get("entries", []):
                idx = int(e["index"])
                if idx <= self.snapshot_index:
                    continue  # already compacted (thus committed)
                existing = self._entry(idx)
                if existing is not None:
                    if existing["term"] == e["term"]:
                        continue
                    self.log = self.log[:idx - self.snapshot_index - 1]
                self.log.append({"index": idx, "term": int(e["term"]),
                                 "cmd": _upgrade_entry(e)["cmd"]})
            self._advance_commit(int(req.get("commit_index", 0)))
            self._save_state()
            self._sync_metrics()
            return {"ok": True, "term": self.term,
                    "last_index": self._last_index()}

    # -- proposing commands (the generalized FSM write path) ------------------
    def propose(self, cmd: Optional[dict] = None, *,
                build: Optional[Callable[[], dict]] = None):
        """Append a command, replicate it, and return its FSM apply
        result only after the entry COMMITS (majority-replicated).  A
        failed quorum leaves the entry uncommitted and nothing is
        returned — at-most-once, so a competing leader can never have
        acknowledged the same mutation.

        `build` constructs the command under the raft lock — required
        when the command reads log-dependent state (the volume-id
        allocation floor) that must be computed atomically with the
        append."""
        with self.lock:
            if self.state != LEADER:
                raise RpcError("not raft leader", 409,
                               headers=self._leader_hint())
            if build is not None:
                cmd = build()
            entry = {"index": self._last_index() + 1, "term": self.term,
                     "cmd": cmd}
            self.log.append(entry)
            self._save_state()
            if len(self.peers) == 1:
                self._advance_commit(entry["index"])
                self._lease_until = self.clock() + self.election_timeout
                return self._apply_results.pop(entry["index"], None)
        # two rounds: the second lets a consistency-miss follower that
        # backed off in round one catch up and count toward the quorum
        for _ in range(2):
            self._broadcast_round()
            with self.lock:
                if self.commit_index >= entry["index"]:
                    if self._term_at(entry["index"]) == entry["term"]:
                        return self._apply_results.pop(
                            entry["index"], None)
                    # compacted below the snapshot horizon: the entry is
                    # committed provided WE are still the leader of its
                    # term (no competing leader could have replaced it
                    # without first bumping our term and demoting us)
                    if (entry["index"] <= self.snapshot_index
                            and self.state == LEADER
                            and self.term == entry["term"]):
                        return self._apply_results.pop(
                            entry["index"], None)
                    # a competing leader's entry committed at our index:
                    # our command was dropped from the log, never applied
                    raise RpcError(
                        "leadership lost before commit", 409,
                        headers=self._leader_hint())
        raise RpcError(
            f"entry {entry['index']} not replicated to quorum", 503,
            headers=self._leader_hint())

    # -- the MaxVolumeId surface (raft_server.go:78) ---------------------------
    def next_volume_id(self) -> int:
        """Allocate the next volume id; returns only after the allocation's
        log entry is COMMITTED.  The floor is computed under the same lock
        as the append, so concurrent proposers never collide."""
        value = self.propose(build=lambda: {
            "type": "volume.assign",
            "value": self._pending_value() + 1,
            "now": time.time()})
        return int(value)

    def observe_volume_id(self, vid: int):
        """Fold in a volume id seen in a heartbeat (SetMax semantics): the
        leader appends a log entry so the observation replicates; followers
        ignore it (their leader will replicate its own observation)."""
        with self.lock:
            if self.state != LEADER or vid <= self._pending_value():
                return
            self.log.append({"index": self._last_index() + 1,
                             "term": self.term,
                             "cmd": {"type": "volume.assign",
                                     "value": int(vid),
                                     "now": time.time()}})
            if len(self.peers) == 1:
                self._advance_commit(self._last_index())
            self._save_state()

    # -- operator surface ------------------------------------------------------
    def status(self) -> dict:
        """cluster.check / raft.status view: term, commit/applied index,
        leader lease freshness, and per-follower replication lag so a
        straggler is visible before it matters."""
        with self.lock:
            followers = {}
            if self.state == LEADER:
                last = self._last_index()
                for p in self.peers:
                    if p == self.address:
                        continue
                    match = self._match_index.get(p, 0)
                    followers[p] = {
                        "match_index": match,
                        "next_index": self._next_index.get(p, last + 1),
                        "lag": last - match,
                    }
            lease = 0.0
            if self.state == LEADER:
                lease = max(0.0, self._lease_until - self.clock())
            return {
                "id": self.address,
                "state": self.state,
                "term": self.term,
                "leader": self.leader or "",
                "peers": self.peers,
                "commit_index": self.commit_index,
                "applied_index": self.applied_index,
                "last_index": self._last_index(),
                "snapshot_index": self.snapshot_index,
                "lease_remaining": round(lease, 3),
                "max_volume_id": self.fsm.max_volume_id,
                "topology_epoch": self.fsm.topology_epoch,
                "followers": followers,
            }
