"""Raft consensus for the master control plane.

The reference runs hashicorp/raft with a deliberately tiny FSM: the only
replicated state is MaxVolumeId (weed/server/raft_server.go:52-100 — the
FSM's Apply handles one command type, MaxVolumeIdCommand), persisted in
boltdb with snapshots.

This implementation runs the full raft machinery — a persisted replicated
LOG with prev-index/term consistency checks, per-follower next/match
tracking, majority commit, and log-compaction snapshots shipped to
stragglers — over a COMMAND-TYPED FSM (master/fsm.py): volume-id
allocation, topology epochs, every curator queue mutation, and the filer
shard map all commit through quorum before they are acknowledged.  A
failed-over leader on a different node resumes with the exact
pending/leased curator set and never double-allocates an id: propose()
returns only after the entry COMMITS, so a failed quorum leaves the
entry uncommitted and the result unreturned (at-most-once).

Membership is itself replicated state: single-server changes
(add-one/remove-one, the raft dissertation §4.1 simple form) commit as
`raft.config` log entries.  A joining master starts as a non-voting
LEARNER that catches up via snapshot + log replay before being promoted
to voter; removals keep replicating to the departing server until the
entry commits, then the server self-demotes to a single-node observer.
Configurations take effect when APPENDED (not committed), quorums are
counted over voters only, and at most one change may be in flight.

Seams for deterministic testing: `clock` (monotonic source), `rpc`
(peer transport) and `rand` (election jitter) are instance attributes,
so the fuzz suite drives whole clusters in-process on a fake clock with
partitionable transports and zero threads.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from ..rpc.http_rpc import RpcError, call
from ..util import glog
from .fsm import ControlFSM

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

SNAPSHOT_THRESHOLD = 64  # applied entries kept before compaction

# propose() results retained past the commit point, so a proposer that
# lost the race to _advance_commit can still collect its return value
_RESULT_WINDOW = 512


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _upgrade_entry(e: dict) -> dict:
    """Accept pre-command-log persisted entries ({"max_volume_id": N})
    by rewriting them as volume.assign commands."""
    if "cmd" in e:
        return e
    return {"index": int(e["index"]), "term": int(e["term"]),
            "cmd": {"type": "volume.assign",
                    "value": int(e.get("max_volume_id", 0))}}


class RaftNode:
    def __init__(self, self_address: str, peers: list[str],
                 state_dir: str = "",
                 election_timeout: float = 0.8,
                 heartbeat_interval: float = 0.25,
                 clock: Optional[Callable[[], float]] = None,
                 transport: Optional[Callable] = None,
                 fsm: Optional[ControlFSM] = None,
                 learner: bool = False):
        """peers includes self_address (unless `learner`, where peers is
        the existing cluster this node intends to join as a non-voter)."""
        self.address = self_address
        if learner:
            self.voters = sorted(set(peers) - {self_address})
            self.learners = [self_address]
        else:
            self.voters = sorted(set(peers) | {self_address})
            self.learners = []
        # the configuration before any raft.config entry / set_peers
        self._bootstrap_config = {"voters": list(self.voters),
                                  "learners": list(self.learners)}
        self.snapshot_config: Optional[dict] = None
        self.observer = False        # removed from the cluster: passive
        self._expelled: set[str] = set()  # committed-removed addresses
        self._config_index = 0       # log index of the config in force
        # departing peers still owed replication (§4.2.2): address ->
        # remaining post-commit grace rounds before we give up on
        # delivering the committed removal (the campaign-probe +
        # expelled-reply path covers a peer that never hears it)
        self._grace: dict[str, int] = {}
        self._learner_since: dict[str, float] = {}
        self.learner_timeout = _env_float("WEED_RAFT_LEARNER_TIMEOUT", 30.0)
        self.state_dir = state_dir
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.clock = clock or time.monotonic
        self.rpc = transport or call
        self.rand = random.random

        self.lock = threading.RLock()
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader: Optional[str] = None
        self.on_become_leader: Optional[Callable[[], None]] = None
        self.on_step_down: Optional[Callable[[], None]] = None
        # committed membership changes (leader-side event seam)
        self.on_membership: Optional[Callable[[dict], None]] = None

        # -- replicated log + snapshot (boltdb store analogue) ---------------
        # entry: {"index": i, "term": t, "cmd": {...}}; the entry at
        # global index i lives at log[i - snapshot_index - 1]
        self.fsm = fsm or ControlFSM()
        self.log: list[dict] = []
        self.snapshot_index = 0
        self.snapshot_term = 0
        self.snapshot_fsm: dict = {}  # FSM snapshot at the compaction point
        self.commit_index = 0
        self.applied_index = 0
        self._apply_results: dict[int, object] = {}
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        # leader lease: the last instant a quorum acknowledged this
        # leader; clients treat the hinted leader as fresh within it
        self._lease_until = 0.0

        self._last_heard = self.clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._peers_persisted = False
        self._load_state()
        self._sync_metrics()
        if len(self.peers) > 1 and not self.state_dir:
            # raft safety requires durable term/vote: a restarted node with
            # amnesia can double-vote in one term and elect two leaders
            glog.warningf(
                "raft: %d-peer cluster without -mdir: term/vote/log state "
                "is NOT persisted; a master restart can elect split leaders",
                len(self.peers))

    # -- membership views -----------------------------------------------------
    @property
    def peers(self) -> list[str]:
        """Every cluster member, voting or not (the operator/health view;
        quorum math uses `voters` only)."""
        return sorted(set(self.voters) | set(self.learners))

    def _known(self) -> set:
        return set(self.voters) | set(self.learners)

    # -- FSM views -----------------------------------------------------------
    @property
    def max_volume_id(self) -> int:
        return self.fsm.max_volume_id

    # -- log helpers (lock held) ----------------------------------------------
    def _last_index(self) -> int:
        return self.snapshot_index + len(self.log)

    def _last_term(self) -> int:
        return self.log[-1]["term"] if self.log else self.snapshot_term

    def _entry(self, index: int) -> Optional[dict]:
        k = index - self.snapshot_index - 1
        if 0 <= k < len(self.log):
            return self.log[k]
        return None

    def _term_at(self, index: int) -> Optional[int]:
        if index == self.snapshot_index:
            return self.snapshot_term
        e = self._entry(index)
        return e["term"] if e else None

    def _pending_value(self) -> int:
        """Highest MaxVolumeId anywhere in the log (committed or not) —
        the allocation floor, so concurrent/unacked entries never collide."""
        value = self.fsm.max_volume_id
        for e in self.log:
            cmd = e["cmd"]
            if cmd.get("type") == "volume.assign" \
                    and int(cmd.get("value", 0)) > value:
                value = int(cmd["value"])
        return value

    # -- configuration from the log (lock held) --------------------------------
    def _config_at(self, index: int) -> tuple[dict, int]:
        """The configuration in force at `index`: the last raft.config
        entry at or below it, else the snapshot's, else bootstrap."""
        for e in reversed(self.log):
            if e["index"] > index:
                continue
            if e["cmd"].get("type") == "raft.config":
                return e["cmd"], e["index"]
        if self.snapshot_config is not None:
            return self.snapshot_config, self.snapshot_index
        return self._bootstrap_config, 0

    def _refresh_config(self):
        """Adopt the latest configuration in the log.  Config entries
        take effect when APPENDED (raft §4.1) — truncating one reverts
        just as mechanically."""
        cfg, cfg_index = self._config_at(self._last_index())
        voters = sorted(set(cfg.get("voters") or []))
        learners = sorted(set(cfg.get("learners") or []))
        known = set(voters) | set(learners)
        if self.address in known:
            self.observer = False
            self._expelled.discard(self.address)
        elif self.observer:
            # a demoted observer keeps its standalone view until some
            # future configuration re-admits it
            voters, learners = [self.address], []
        self._expelled -= known
        for a in known:
            self._grace.pop(a, None)
        self.voters = voters
        self.learners = learners
        self._config_index = cfg_index
        now = self.clock()
        for a in learners:
            self._learner_since.setdefault(a, now)
        for a in [a for a in self._learner_since if a not in learners]:
            del self._learner_since[a]

    def _on_config_committed(self, e: dict):
        """Commit-time effects of a raft.config entry (lock held): mark
        explicit removals expelled (so a stale campaigner gets told),
        self-demote when the committed config excludes us, and surface
        the change to the membership event seam on the leader."""
        cmd = e["cmd"]
        known = set(cmd.get("voters") or []) | set(cmd.get("learners") or [])
        addr = cmd.get("address", "")
        if addr and addr not in known:
            if addr == self.address:
                self._demote()
            else:
                self._expelled.add(addr)
                if self.state == LEADER:
                    # keep replicating to the departing server for a few
                    # more rounds so it learns its removal committed
                    self._grace.setdefault(addr, 8)
        self._expelled -= known
        if self.state == LEADER and self.on_membership is not None:
            try:
                self.on_membership(dict(cmd, index=e["index"]))
            except Exception:
                pass  # event plumbing must never wedge consensus

    def _demote(self):
        """Become a single-node observer: the cluster removed us.  We
        stop campaigning entirely (no stale-term disruption) but keep
        answering reads; a future config re-admitting us reverses it."""
        with self.lock:
            if self.observer:
                return
            was_leader = self.state == LEADER
            self.observer = True
            self.state = FOLLOWER
            self.leader = None
            self.voters = [self.address]
            self.learners = []
            self._peers_persisted = True
            self._last_heard = self.clock()
            self._save_state()
        glog.infof("raft: %s removed from the cluster; now an observer",
                   self.address)
        self._sync_metrics()
        if was_leader and self.on_step_down:
            self.on_step_down()

    def _advance_commit(self, new_commit: int):
        """Apply newly-committed entries to the FSM, then maybe compact."""
        new_commit = min(new_commit, self._last_index())
        if new_commit <= self.commit_index:
            return
        old_commit = self.commit_index
        self.commit_index = new_commit
        for i in range(old_commit + 1, new_commit + 1):
            e = self._entry(i)
            if e is None:
                continue
            self._apply_results[i] = self.fsm.apply(e["cmd"])
            if e["cmd"].get("type") == "raft.config":
                # commit-time membership effects (expel / self-demote /
                # surface the change on the leader's event seam)
                self._on_config_committed(e)
        self.applied_index = new_commit
        if len(self._apply_results) > _RESULT_WINDOW:
            floor = new_commit - _RESULT_WINDOW
            for i in [i for i in self._apply_results if i <= floor]:
                del self._apply_results[i]
        self._maybe_snapshot()
        self._save_state()
        self._sync_metrics()

    def _maybe_snapshot(self):
        """Compact the applied prefix once it outgrows the threshold
        (raft_server.go:91-100 snapshot persistence)."""
        applied = self.commit_index - self.snapshot_index
        if applied < SNAPSHOT_THRESHOLD:
            return
        cut = self.commit_index - self.snapshot_index  # entries to drop
        # capture the committed config BEFORE the entries carrying it
        # are dropped — InstallSnapshot must ship membership too
        cfg, _ = self._config_at(self.commit_index)
        self.snapshot_config = {"voters": list(cfg.get("voters") or []),
                                "learners": list(cfg.get("learners") or [])}
        self.snapshot_term = self._term_at(self.commit_index) or \
            self.snapshot_term
        self.snapshot_index = self.commit_index
        self.snapshot_fsm = self.fsm.snapshot()
        self.log = self.log[cut:]

    def _sync_metrics(self):
        try:
            from ..stats import metrics as _m

            _m.RaftTermGauge.labels(self.address).set(self.term)
            _m.RaftCommitIndexGauge.labels(self.address) \
                .set(self.commit_index)
            _m.RaftAppliedLagGauge.labels(self.address) \
                .set(self._last_index() - self.applied_index)
        except Exception:
            pass  # metrics must never wedge consensus

    # -- persistence -----------------------------------------------------------
    def _state_path(self) -> str:
        return os.path.join(self.state_dir, "raft_state.json")

    def _load_state(self):
        if not self.state_dir:
            return
        try:
            with open(self._state_path()) as f:
                d = json.load(f)
            self.term = int(d.get("term", 0))
            self.voted_for = d.get("voted_for")
            snap = d.get("snapshot", {})
            self.snapshot_index = int(snap.get("index", 0))
            self.snapshot_term = int(snap.get("term", 0))
            self.snapshot_config = snap.get("config")
            fsm_snap = snap.get("fsm")
            if fsm_snap is None:
                # legacy MaxVolumeId-only snapshot
                fsm_snap = {"max_volume_id":
                            int(snap.get("max_volume_id",
                                         d.get("max_volume_id", 0)))}
            self.snapshot_fsm = fsm_snap
            self.log = [_upgrade_entry(e) for e in d.get("log", [])]
            self.commit_index = max(int(d.get("commit_index", 0)),
                                    self.snapshot_index)
            # replay: restore the snapshot FSM, apply the committed suffix
            self.fsm.restore(self.snapshot_fsm)
            for e in self.log:
                if e["index"] <= self.commit_index:
                    self.fsm.apply(e["cmd"])
            self.applied_index = self.commit_index
            self._refresh_config()
            # peers are persisted only once membership was changed via
            # cluster.raft.add/remove — a plain restart keeps the
            # configured list (addresses are identity here, so saving the
            # bootstrap list would resurrect stale self-addresses)
            persisted = d.get("peers")
            self.observer = bool(d.get("observer", False))
            self._expelled = set(d.get("expelled") or [])
            if self.observer:
                self.voters, self.learners = [self.address], []
                self._peers_persisted = True
            elif persisted is not None and self._config_index == 0 \
                    and self.snapshot_config is None:
                # legacy broadcast-driven membership (no config entries
                # anywhere in the log): adopt the persisted list
                self.voters = sorted(set(persisted) | {self.address})
                self.learners = sorted(set(d.get("learners") or []))
                self._peers_persisted = True
            elif persisted is not None:
                self._peers_persisted = True
        except (OSError, ValueError):
            pass

    def _save_state(self):
        if not self.state_dir:
            return
        state = {
            "term": self.term, "voted_for": self.voted_for,
            "commit_index": self.commit_index,
            "snapshot": {"index": self.snapshot_index,
                         "term": self.snapshot_term,
                         "fsm": self.snapshot_fsm},
            "log": self.log,
        }
        if self.snapshot_config is not None:
            state["snapshot"]["config"] = self.snapshot_config
        if self._peers_persisted:
            state["peers"] = self.voters
            state["learners"] = self.learners
            state["observer"] = self.observer
            state["expelled"] = sorted(self._expelled)
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._state_path())

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self.voters == [self.address] and not self.observer:
            # single-node cluster: immediately leader (no quorum needed)
            with self.lock:
                self.state = LEADER
                self.leader = self.address
                self._lease_until = self.clock() + self.election_timeout
            if self.on_become_leader:
                self.on_become_leader()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def quorum(self) -> int:
        return len(self.voters) // 2 + 1

    def _leader_hint(self) -> Optional[dict]:
        """Response headers pointing a rejected caller at the leader."""
        leader = self.leader
        if leader and leader != self.address:
            return {"X-Raft-Leader": leader}
        return None

    # -- membership changes (shell cluster.raft.add/remove) ------------------
    # Single-server changes committed through the replicated log, per the
    # raft dissertation §4.1: the new configuration is one raft.config
    # entry, effective when appended; at most one change is in flight.
    # Joins go learner-first: a non-voter catches up via snapshot + log
    # replay, then the leader auto-promotes it to voter.

    def _config_slot_free(self) -> bool:
        """lock held: may another config entry enter the log now?"""
        limit = max(1, int(_env_float("WEED_RAFT_MAX_CONFIG_CHANGES", 1)))
        pending = sum(1 for e in self.log
                      if e["index"] > self.commit_index
                      and e["cmd"].get("type") == "raft.config")
        return pending < limit

    def _propose_config(self, op: str, address: str,
                        build_membership: Callable[[], tuple]) -> dict:
        """Commit one raft.config entry; membership is computed under
        the raft lock (atomic with the append) by build_membership,
        which may raise RpcError to veto."""
        def build():
            if not self._config_slot_free():
                raise RpcError("raft config change already in flight", 409)
            voters, learners = build_membership()
            return {"type": "raft.config", "op": op, "address": address,
                    "voters": sorted(set(voters)),
                    "learners": sorted(set(learners)),
                    "now": time.time()}
        self.propose(build=build)
        with self.lock:
            departed = address in self._grace or address in self._expelled
            result = {"op": op, "address": address,
                      "voters": list(self.voters),
                      "learners": list(self.learners)}
        if departed:
            # one synchronous post-commit round so the removed server
            # hears the sealed removal (and demotes) before we return
            self._broadcast_round()
        return result

    def add_server(self, address: str) -> dict:
        """Add `address` as a non-voting learner (committed through the
        log).  Promotion to voter happens automatically once the learner
        has caught up (see _maybe_promote_learner)."""
        with self.lock:
            if address in self._known():
                return {"op": "noop", "address": address, "already": True,
                        "voters": list(self.voters),
                        "learners": list(self.learners)}

        def membership():
            if address in self._known():
                raise RpcError(f"{address} already a raft member", 409)
            return list(self.voters), list(self.learners) + [address]
        return self._propose_config("add_learner", address, membership)

    def remove_server(self, address: str, reason: str = "") -> dict:
        """Remove a voter or learner through the log.  Removing self is
        legal: we keep leading (without counting our own vote) until the
        entry commits, then step down and demote to observer."""
        def membership():
            if address not in self._known():
                raise RpcError(f"{address} not a raft member", 404)
            voters = [v for v in self.voters if v != address]
            if not voters:
                raise RpcError("cannot remove the last raft voter", 400)
            return voters, [l for l in self.learners if l != address]
        op = "remove" if not reason else f"remove:{reason}"
        return self._propose_config(op, address, membership)

    def _maybe_promote_learner(self):
        """Leader-side learner lifecycle, one change at a time: promote
        a caught-up learner to voter; abandon one that has not caught up
        within WEED_RAFT_LEARNER_TIMEOUT (a dead joiner must not squat
        in the config forever)."""
        action = None
        with self.lock:
            if self.state != LEADER or not self.learners \
                    or not self._config_slot_free():
                return
            last = self._last_index()
            now = self.clock()
            for addr in self.learners:
                match = self._match_index.get(addr, 0)
                if match >= self.commit_index and last - match <= 1:
                    action = ("promote", addr)
                    break
                since = self._learner_since.get(addr, now)
                if self.learner_timeout > 0 \
                        and now - since > self.learner_timeout:
                    action = ("abandon", addr)
                    break
        if action is None:
            return
        op, addr = action
        try:
            if op == "promote":
                def membership():
                    if addr not in self.learners:
                        raise RpcError(f"{addr} no longer a learner", 409)
                    return (list(self.voters) + [addr],
                            [l for l in self.learners if l != addr])
                self._propose_config("promote", addr, membership)
            else:
                self.remove_server(addr, reason="learner_timeout")
        except RpcError:
            pass  # lost leadership / lost the slot: next tick retries

    # -- legacy administrative broadcast (kept for mixed-version peers) -------
    def set_peers(self, peers: list[str]):
        """Adopt a broadcast membership list (internal /raft/update_peers).
        A node absent from the list has been expelled: it demotes to a
        single-node OBSERVER — it neither campaigns against its former
        peers nor keeps heartbeating a stale term."""
        was_leader = False
        with self.lock:
            if self.address in peers:
                gone = self._known() - set(peers) - {self.address}
                self._expelled |= gone
                self._expelled -= set(peers)
                self.voters = sorted(set(peers))
                self.learners = [l for l in self.learners if l in peers
                                 and l not in self.voters]
                self.observer = False
            else:
                was_leader = self.state == LEADER
                self.voters = [self.address]
                self.learners = []
                self.state = FOLLOWER
                self.leader = None
                self.observer = True
            self._peers_persisted = True
            self._save_state()
        if was_leader and self.on_step_down:
            self.on_step_down()

    def add_peer(self, address: str):
        return self.add_server(address)

    def remove_peer(self, address: str):
        return self.remove_server(address)

    # -- main loop -----------------------------------------------------------
    def tick(self) -> float:
        """One scheduler step (factored out of _run so tests can drive
        a node on a fake clock without its thread).  Returns how long
        the loop should sleep before the next step."""
        if self.state == LEADER:
            self._broadcast_round()
            self._maybe_promote_learner()
            return self.heartbeat_interval
        if self.observer or self.address in self.learners:
            # non-voters never campaign: they replicate passively and
            # wait to be promoted (or re-admitted)
            self._last_heard = self.clock()
            return self.heartbeat_interval
        timeout = self.election_timeout * (1 + self.rand())
        if self.clock() - self._last_heard > timeout:
            self._campaign()
        return 0.05

    def _run(self):
        while not self._stop.is_set():
            try:
                delay = self.tick()
            except Exception as e:  # consensus loop must never die
                glog.warningf("raft: tick failed on %s: %s",
                              self.address, e)
                delay = 0.05
            self._stop.wait(delay)

    def _campaign(self):
        with self.lock:
            if self.observer or self.address in self.learners:
                return
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.address
            self.leader = None
            term = self.term
            last_index = self._last_index()
            last_term = self._last_term()
            voters = list(self.voters)
            self._save_state()
        # a server excluded by a not-yet-committed config still campaigns
        # (§4.2.2: the change may yet be truncated) — but its own vote
        # only counts if it is a voter
        votes = 1 if self.address in voters else 0
        removed = False
        for peer in voters:
            if peer == self.address:
                continue
            try:
                r = self.rpc(peer, "/raft/request_vote",
                             {"term": term, "candidate": self.address,
                              "last_log_index": last_index,
                              "last_log_term": last_term},
                             timeout=1)
                if r.get("removed"):
                    removed = True
                    break
                if r.get("granted"):
                    votes += 1
                elif r.get("term", 0) > term:
                    self._step_down(r["term"])
                    return
            except RpcError:
                continue
        if removed:
            # the cluster committed our removal while we were away
            self._demote()
            return
        with self.lock:
            if self.state != CANDIDATE or self.term != term:
                return
            if votes >= self.quorum():
                glog.infof("raft: %s elected leader for term %d (%d votes)",
                           self.address, term, votes)
                self.state = LEADER
                self.leader = self.address
                # no-op entry of OUR term: prior-term entries cannot
                # commit by counting (§5.4.2), so without this the new
                # leader's FSM would lag until the next real proposal
                self.log.append({"index": self._last_index() + 1,
                                 "term": self.term,
                                 "cmd": {"type": "raft.noop"}})
                self._grace = {}
                for peer in self._known() | {self.address}:
                    self._next_index[peer] = self._last_index()
                    self._match_index[peer] = 0
                self._save_state()
            else:
                self.state = FOLLOWER
                self._last_heard = self.clock()
                return
        self._sync_metrics()
        if self.on_become_leader:
            self.on_become_leader()
        self._broadcast_round()

    def _step_down(self, term: int):
        with self.lock:
            if term > self.term:
                self.term = term
                self.voted_for = None
                self._save_state()
            was_leader = self.state == LEADER
            if self.state != FOLLOWER:
                glog.infof("raft: %s stepping down at term %d",
                           self.address, term)
            self.state = FOLLOWER
            self._last_heard = self.clock()
        self._sync_metrics()
        if was_leader and self.on_step_down:
            self.on_step_down()

    # -- leader-side replication ----------------------------------------------
    def _replicate_to(self, peer: str) -> bool:
        """One AppendEntries (or snapshot-install) round to a follower."""
        with self.lock:
            if self.state != LEADER:
                return False
            term = self.term
            ni = self._next_index.get(peer, self._last_index() + 1)
            payload = {"term": term, "leader": self.address,
                       "commit_index": self.commit_index}
            if ni <= self.snapshot_index:
                # follower is behind the compaction horizon: ship the
                # snapshot (InstallSnapshot), then the remaining log
                payload["snapshot"] = {
                    "index": self.snapshot_index,
                    "term": self.snapshot_term,
                    "fsm": self.snapshot_fsm,
                    "config": self.snapshot_config}
                payload["prev_index"] = self.snapshot_index
                payload["prev_term"] = self.snapshot_term
                payload["entries"] = list(self.log)
            else:
                payload["prev_index"] = ni - 1
                payload["prev_term"] = self._term_at(ni - 1) or 0
                payload["entries"] = [
                    e for e in self.log if e["index"] >= ni]
            sent_last = self._last_index()
        try:
            r = self.rpc(peer, "/raft/append_entries", payload, timeout=1)
        except RpcError:
            return False
        if r.get("removed"):
            # the peer knows a committed config expelled US
            self._demote()
            return False
        with self.lock:
            if r.get("term", 0) > self.term:
                pass  # handled below, outside the lock
            elif r.get("ok"):
                self._match_index[peer] = sent_last
                self._next_index[peer] = sent_last + 1
                return True
            else:
                # consistency miss: back off to the follower's tail
                follower_last = int(r.get("last_index", 0))
                self._next_index[peer] = max(
                    min(ni - 1, follower_last + 1), 1)
        if r.get("term", 0) > term:
            self._step_down(r["term"])
        return False

    def _broadcast_round(self) -> int:
        """Replicate to every member; advance commit on majority match
        among VOTERS.  Returns the number of voters (incl. self when
        voting) matching our last index.  A server being removed by an
        in-flight config keeps receiving entries until it has seen the
        committed removal (§4.2.2), so it demotes instead of lingering."""
        with self.lock:
            voters = set(self.voters)
            targets = self._known()
            cfg_idx = self._config_index
            in_flight = cfg_idx > self.commit_index
            if cfg_idx > 0:
                old_cfg, _ = self._config_at(cfg_idx - 1)
                old = (set(old_cfg.get("voters") or [])
                       | set(old_cfg.get("learners") or []))
                for a in old - self._known():
                    if in_flight:
                        targets.add(a)
                    elif self._grace.get(a, 0) > 0:
                        self._grace[a] -= 1
                        targets.add(a)
            targets.discard(self.address)
            pre_commit = self.commit_index
        acked = 1 if self.address in voters else 0
        for peer in sorted(targets):
            ok = self._replicate_to(peer)
            if not ok:
                continue
            if peer in voters:
                acked += 1
            elif pre_commit >= cfg_idx:
                # departing server has now seen the committed removal
                with self.lock:
                    self._grace.pop(peer, None)
        with self.lock:
            if self.state != LEADER:
                return acked
            if acked >= self.quorum():
                # a quorum just heard from us: refresh the leader lease
                self._lease_until = self.clock() + self.election_timeout
            # majority-match commit rule (only entries of the current term
            # commit by counting, per the raft paper's §5.4.2 restriction)
            voters = set(self.voters)
            for n in range(self._last_index(), self.commit_index, -1):
                matches = (1 if self.address in voters else 0) + sum(
                    1 for p in voters if p != self.address
                    and self._match_index.get(p, 0) >= n)
                if matches >= self.quorum() \
                        and self._term_at(n) == self.term:
                    self._advance_commit(n)
                    break
        return acked

    # -- RPC handlers --------------------------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        term = int(req["term"])
        candidate = req["candidate"]
        c_last_term = int(req.get("last_log_term", 0))
        c_last_index = int(req.get("last_log_index", 0))
        with self.lock:
            if candidate in self._expelled \
                    and candidate not in self._known():
                # a committed config removed the candidate: tell it so
                # WITHOUT adopting its term — a removed server must not
                # be able to disrupt the cluster it no longer belongs to
                return {"granted": False, "term": self.term,
                        "removed": True}
            if self.observer:
                return {"granted": False, "term": self.term}
            if term < self.term:
                return {"granted": False, "term": self.term}
            if term > self.term and self.state == FOLLOWER \
                    and self.leader and self.leader != candidate \
                    and self.clock() - self._last_heard \
                    < self.election_timeout:
                # leader stickiness (§4.2.3): we heard from a live leader
                # within the election timeout, so a fresher-term vote
                # request — typically a server that does not yet know it
                # was removed — is ignored without a term bump
                return {"granted": False, "term": self.term}
            if term > self.term:
                self.term = term
                self.voted_for = None
                if self.state != FOLLOWER:
                    self.state = FOLLOWER
            # up-to-date check on the LOG (raft §5.4.1), not the FSM
            up_to_date = (c_last_term > self._last_term()
                          or (c_last_term == self._last_term()
                              and c_last_index >= self._last_index()))
            if self.voted_for in (None, candidate) and up_to_date:
                self.voted_for = candidate
                self._last_heard = self.clock()
                self._save_state()
                return {"granted": True, "term": self.term}
            self._save_state()
            return {"granted": False, "term": self.term}

    def handle_append_entries(self, req: dict) -> dict:
        term = int(req["term"])
        leader_addr = req.get("leader", "")
        with self.lock:
            if leader_addr in self._expelled \
                    and leader_addr not in self._known():
                # stale heartbeat from a removed ex-leader: reject
                # without adopting its term or leadership
                return {"ok": False, "term": self.term, "removed": True}
            if term < self.term:
                return {"ok": False, "term": self.term,
                        "last_index": self._last_index()}
            if term > self.term:
                self.term = term
                self.voted_for = None
            self.state = FOLLOWER
            self.leader = leader_addr
            self._last_heard = self.clock()

            snap = req.get("snapshot")
            if snap and snap["index"] > self.snapshot_index \
                    and snap["index"] > self.commit_index:
                # InstallSnapshot: replace everything up to the snapshot
                self.snapshot_index = int(snap["index"])
                self.snapshot_term = int(snap["term"])
                self.snapshot_fsm = snap.get("fsm") or {
                    "max_volume_id": int(snap.get("max_volume_id", 0))}
                if snap.get("config") is not None:
                    self.snapshot_config = snap["config"]
                self.log = []
                self.commit_index = self.snapshot_index
                self.applied_index = self.snapshot_index
                self.fsm.restore(self.snapshot_fsm)
                self._refresh_config()

            prev_index = int(req.get("prev_index", 0))
            prev_term = int(req.get("prev_term", 0))
            if prev_index > self._last_index():
                self._save_state()
                return {"ok": False, "term": self.term,
                        "last_index": self._last_index()}
            if prev_index > self.snapshot_index:
                local = self._term_at(prev_index)
                if local != prev_term:
                    # conflicting suffix: drop it and report our new tail
                    self.log = self.log[:prev_index - self.snapshot_index
                                        - 1]
                    self._refresh_config()
                    self._save_state()
                    return {"ok": False, "term": self.term,
                            "last_index": self._last_index()}
            for e in req.get("entries", []):
                idx = int(e["index"])
                if idx <= self.snapshot_index:
                    continue  # already compacted (thus committed)
                existing = self._entry(idx)
                if existing is not None:
                    if existing["term"] == e["term"]:
                        continue
                    self.log = self.log[:idx - self.snapshot_index - 1]
                self.log.append({"index": idx, "term": int(e["term"]),
                                 "cmd": _upgrade_entry(e)["cmd"]})
            self._refresh_config()
            self._advance_commit(int(req.get("commit_index", 0)))
            # a snapshot-installed config that excludes us is committed
            # by definition: demote now rather than linger voiceless
            if not self.observer and self._config_index > 0 \
                    and self._config_index <= self.commit_index \
                    and self.address not in self._known():
                self._demote()
            self._save_state()
            self._sync_metrics()
            return {"ok": True, "term": self.term,
                    "last_index": self._last_index()}

    # -- proposing commands (the generalized FSM write path) ------------------
    def propose(self, cmd: Optional[dict] = None, *,
                build: Optional[Callable[[], dict]] = None):
        """Append a command, replicate it, and return its FSM apply
        result only after the entry COMMITS (majority-replicated).  A
        failed quorum leaves the entry uncommitted and nothing is
        returned — at-most-once, so a competing leader can never have
        acknowledged the same mutation.

        `build` constructs the command under the raft lock — required
        when the command reads log-dependent state (the volume-id
        allocation floor or the membership roster) that must be computed
        atomically with the append."""
        with self.lock:
            if self.state != LEADER:
                raise RpcError("not raft leader", 409,
                               headers=self._leader_hint())
            if build is not None:
                cmd = build()
            entry = {"index": self._last_index() + 1, "term": self.term,
                     "cmd": cmd}
            self.log.append(entry)
            if cmd.get("type") == "raft.config":
                self._refresh_config()
            self._save_state()
            if self.voters == [self.address]:
                self._advance_commit(entry["index"])
                self._lease_until = self.clock() + self.election_timeout
                return self._apply_results.pop(entry["index"], None)
        # two rounds: the second lets a consistency-miss follower that
        # backed off in round one catch up and count toward the quorum
        for _ in range(2):
            self._broadcast_round()
            with self.lock:
                if self.commit_index >= entry["index"]:
                    if self._term_at(entry["index"]) == entry["term"]:
                        return self._apply_results.pop(
                            entry["index"], None)
                    # compacted below the snapshot horizon: the entry is
                    # committed provided WE are still the leader of its
                    # term (no competing leader could have replaced it
                    # without first bumping our term and demoting us)
                    if (entry["index"] <= self.snapshot_index
                            and self.state == LEADER
                            and self.term == entry["term"]):
                        return self._apply_results.pop(
                            entry["index"], None)
                    # a competing leader's entry committed at our index:
                    # our command was dropped from the log, never applied
                    raise RpcError(
                        "leadership lost before commit", 409,
                        headers=self._leader_hint())
        raise RpcError(
            f"entry {entry['index']} not replicated to quorum", 503,
            headers=self._leader_hint())

    # -- the MaxVolumeId surface (raft_server.go:78) ---------------------------
    def next_volume_id(self) -> int:
        """Allocate the next volume id; returns only after the allocation's
        log entry is COMMITTED.  The floor is computed under the same lock
        as the append, so concurrent proposers never collide."""
        value = self.propose(build=lambda: {
            "type": "volume.assign",
            "value": self._pending_value() + 1,
            "now": time.time()})
        return int(value)

    def observe_volume_id(self, vid: int):
        """Fold in a volume id seen in a heartbeat (SetMax semantics): the
        leader appends a log entry so the observation replicates; followers
        ignore it (their leader will replicate its own observation)."""
        with self.lock:
            if self.state != LEADER or vid <= self._pending_value():
                return
            self.log.append({"index": self._last_index() + 1,
                             "term": self.term,
                             "cmd": {"type": "volume.assign",
                                     "value": int(vid),
                                     "now": time.time()}})
            if self.voters == [self.address]:
                self._advance_commit(self._last_index())
            self._save_state()

    # -- operator surface ------------------------------------------------------
    def status(self) -> dict:
        """cluster.check / raft.status view: term, commit/applied index,
        leader lease freshness, voters/learners and any in-flight config
        change, plus per-follower replication lag so a straggler (or a
        learner mid-catch-up) is visible before it matters."""
        with self.lock:
            followers = {}
            if self.state == LEADER:
                last = self._last_index()
                for p in self._known():
                    if p == self.address:
                        continue
                    match = self._match_index.get(p, 0)
                    followers[p] = {
                        "match_index": match,
                        "next_index": self._next_index.get(p, last + 1),
                        "lag": last - match,
                        "voting": p in self.voters,
                    }
            lease = 0.0
            if self.state == LEADER:
                lease = max(0.0, self._lease_until - self.clock())
            return {
                "id": self.address,
                "state": self.state,
                "term": self.term,
                "leader": self.leader or "",
                "peers": self.peers,
                "voters": list(self.voters),
                "learners": list(self.learners),
                "observer": self.observer,
                "config_index": self._config_index,
                "config_change_in_flight":
                    self._config_index > self.commit_index,
                "commit_index": self.commit_index,
                "applied_index": self.applied_index,
                "last_index": self._last_index(),
                "snapshot_index": self.snapshot_index,
                "lease_remaining": round(lease, 3),
                "max_volume_id": self.fsm.max_volume_id,
                "topology_epoch": self.fsm.topology_epoch,
                "followers": followers,
            }
