"""The master's replicated state machine (command-typed FSM).

The reference fork runs hashicorp/raft with a MaxVolumeId-only FSM
(raft_server.go:78).  This FSM generalizes that into a command-typed
log covering everything a failed-over leader must resume with exactly:

  volume.assign     MaxVolumeId allocation (SetMax fold)
  topology.epoch    placement-generation bump (volume growth)
  curator.*         every maintenance/queue.py mutation
  filer.lease       the directory-prefix shard map for filer metadata
  filer.resize      online shard split/merge (two-phase prepare/commit)

Commands are plain JSON dicts carrying their own `now` timestamp, so
replaying the same log (or a snapshot + suffix) on a fresh node yields
a byte-identical FSM — the determinism the failover guarantees rest
on.  The curator queue inside the FSM runs journal-less: the raft log
and snapshots ARE its durability, so a journal replay never
double-applies on top of log replay.
"""

from __future__ import annotations

from typing import Optional

from ..filer.shard_map import ShardMap
from ..maintenance.jobs import Job
from ..maintenance.queue import JobQueue


class ControlFSM:
    """Deterministic apply target for the raft log.  Not thread-safe by
    itself — the RaftNode applies commands under its own lock."""

    def __init__(self, shard_slots: Optional[int] = None):
        self.max_volume_id = 0
        self.topology_epoch = 0
        self._now = 0.0
        # journal-less queue: raft persistence replaces the jlog
        self.queue = JobQueue()
        self.queue.now = lambda: self._now
        self.shard_map = ShardMap(slots=shard_slots)

    # -- dispatch ------------------------------------------------------------
    def apply(self, cmd: dict):
        """Apply one committed command; returns the command's result
        (handed back to the proposer by RaftNode.propose).  Must never
        raise — a poisoned command would diverge replicas that handle
        the exception differently."""
        try:
            self._now = float(cmd.get("now", self._now))
            handler = self._HANDLERS.get(cmd.get("type", ""))
            if handler is None:
                return None
            return handler(self, cmd)
        except Exception:
            return None

    def _apply_volume_assign(self, cmd: dict):
        value = int(cmd.get("value", 0))
        if value > self.max_volume_id:
            self.max_volume_id = value
        return value

    def _apply_topology_epoch(self, cmd: dict):
        self.topology_epoch += 1
        return self.topology_epoch

    # -- curator queue mutations ---------------------------------------------
    # Knob-derived values (lease duration, attempt caps) ride in the
    # command, pinned by the proposing leader — two nodes with drifted
    # env config still apply identically.

    def _apply_curator_enqueue(self, cmd: dict):
        return self.queue.enqueue(
            cmd.get("job_type", ""), int(cmd.get("volume", 0)),
            cmd.get("collection", ""), cmd.get("params") or {},
            priority=cmd.get("priority"))

    def _with_lease_seconds(self, cmd: dict, fn):
        prev = self.queue._lease_seconds
        if cmd.get("lease_seconds") is not None:
            self.queue._lease_seconds = float(cmd["lease_seconds"])
        try:
            return fn()
        finally:
            self.queue._lease_seconds = prev

    def _apply_curator_lease(self, cmd: dict):
        return self._with_lease_seconds(cmd, lambda: self.queue.lease(
            cmd.get("worker", ""), cmd.get("types"),
            int(cmd.get("limit", 1)), ec_volumes=cmd.get("ec_volumes")))

    def _apply_curator_renew(self, cmd: dict):
        return self._with_lease_seconds(cmd, lambda: self.queue.renew(
            cmd.get("id", ""), cmd.get("worker", "")))

    def _apply_curator_done(self, cmd: dict):
        job = self.queue.complete(cmd.get("id", ""),
                                  cmd.get("worker", ""),
                                  cmd.get("outcome", "ok"))
        return job.to_dict() if job is not None else None

    def _apply_curator_fail(self, cmd: dict):
        prev_attempts = self.queue._max_attempts
        prev_backoff = self.queue.retry_backoff
        if cmd.get("max_attempts") is not None:
            self.queue._max_attempts = int(cmd["max_attempts"])
        if cmd.get("backoff") is not None:
            self.queue.retry_backoff = float(cmd["backoff"])
        try:
            job = self.queue.fail(cmd.get("id", ""),
                                  cmd.get("worker", ""),
                                  cmd.get("error", ""))
        finally:
            self.queue._max_attempts = prev_attempts
            self.queue.retry_backoff = prev_backoff
        return job.to_dict() if job is not None else None

    def _apply_curator_expire(self, cmd: dict):
        return self.queue.expire_leases()

    def _apply_curator_pause(self, cmd: dict):
        self.queue.paused = bool(cmd.get("paused", True))
        return self.queue.paused

    # -- filer shard leases ---------------------------------------------------
    def _apply_filer_lease(self, cmd: dict):
        if cmd.get("release"):
            return self.shard_map.release(cmd.get("holder", ""),
                                          self._now)
        return self.shard_map.lease(cmd.get("holder", ""), self._now,
                                    float(cmd.get("ttl", 10.0)))

    def _apply_filer_resize(self, cmd: dict):
        """Online shard split/merge, two-phase: start opens the prepare
        window (holders dual-write + re-shard locally), ack records one
        holder's readiness, commit flips the map, abort cancels."""
        op = cmd.get("op", "")
        if op == "start":
            return self.shard_map.resize_start(int(cmd.get("to", 0)),
                                               self._now)
        if op == "ack":
            return self.shard_map.resize_ack(cmd.get("holder", ""),
                                             self._now)
        if op == "commit":
            return self.shard_map.resize_commit(self._now)
        if op == "abort":
            return self.shard_map.resize_abort(self._now)
        return {"error": f"unknown resize op {op!r}"}

    _HANDLERS = {
        "volume.assign": _apply_volume_assign,
        "topology.epoch": _apply_topology_epoch,
        "curator.enqueue": _apply_curator_enqueue,
        "curator.lease": _apply_curator_lease,
        "curator.renew": _apply_curator_renew,
        "curator.done": _apply_curator_done,
        "curator.fail": _apply_curator_fail,
        "curator.expire": _apply_curator_expire,
        "curator.pause": _apply_curator_pause,
        "filer.lease": _apply_filer_lease,
        "filer.resize": _apply_filer_resize,
    }

    # -- snapshot / restore ----------------------------------------------------
    def snapshot(self) -> dict:
        """Deterministic JSON state: two FSMs that applied the same
        command sequence produce identical snapshots (sorted job order,
        no wall-clock reads)."""
        q = self.queue

        def _jid(job_id: str) -> int:
            try:
                return int(job_id[1:])
            except ValueError:
                return 0

        return {
            "max_volume_id": self.max_volume_id,
            "topology_epoch": self.topology_epoch,
            "now": self._now,
            "queue": {
                "seq": q._seq,
                "paused": q.paused,
                "jobs": [q._jobs[i].to_dict()
                         for i in sorted(q._jobs, key=_jid)],
                "history": list(q.history)[-64:],
            },
            "shards": self.shard_map.to_dict(),
        }

    def restore(self, snap: dict):
        snap = snap or {}
        self.max_volume_id = int(snap.get("max_volume_id", 0))
        self.topology_epoch = int(snap.get("topology_epoch", 0))
        self._now = float(snap.get("now", 0.0))
        qs = snap.get("queue", {})
        q = JobQueue()
        q.now = lambda: self._now
        q._seq = int(qs.get("seq", 0))
        q.paused = bool(qs.get("paused", False))
        for d in qs.get("jobs", []):
            job = Job.from_dict(d)
            q._jobs[job.id] = job
            q._by_key[job.key] = job.id
        for h in qs.get("history", []):
            q.history.append(dict(h))
        self.queue = q
        self.shard_map = ShardMap.from_dict(snap.get("shards", {}))
