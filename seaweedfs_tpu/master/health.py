"""Leader-resident cluster health plane.

One thread on the raft leader scrapes every registered daemon's
``/metrics`` (already fleet-merged across prefork workers by the
aggregation route) into the bounded ring TSDB, runs the SLO burn-rate
evaluator, and folds remote event journals into the leader's — so
``GET /cluster/health`` answers "is the cluster healthy" from a single
place, ``GET /cluster/alerts`` lists firing burn-rate alerts, and
``GET /cluster/events`` is the ordered cluster history.

Resilience: each target gets its own deadline (``rpc/policy.py``
deadline machinery) so one daemon hanging mid-exposition cannot stall
the round; failures count in
``SeaweedFS_cluster_scrape_errors_total{target}`` and flip the
target's liveness series, which is exactly what the availability SLO
rule watches.

Knobs: ``WEED_HEALTH_SCRAPE_MS`` (cadence, default 5000),
``WEED_HEALTH_DEADLINE_MS`` (per-target budget, default 1000).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..maintenance import detectors
from ..maintenance.jobs import (TYPE_DEEP_SCRUB, TYPE_EC_REBUILD,
                                TYPE_FIX_REPLICATION)
from ..rpc import policy
from ..stats import access as access_mod
from ..stats import events as events_mod
from ..stats import metrics as _stats
from ..stats import slo as slo_mod
from ..stats import tsdb as tsdb_mod
from ..util import glog


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def scrape_interval() -> float:
    return max(0.05, _env_float("WEED_HEALTH_SCRAPE_MS", 5000.0) / 1000.0)


def target_deadline() -> float:
    return max(0.05, _env_float("WEED_HEALTH_DEADLINE_MS", 1000.0) / 1000.0)


class HealthPlane:
    def __init__(self, master):
        self.master = master
        self.now = time.time  # fake-clock seam
        self.tsdb = tsdb_mod.Tsdb(interval=scrape_interval(), now=self.now)
        self.journal = events_mod.JOURNAL
        self.slo = slo_mod.SloEngine(self.tsdb, now=self.now,
                                     on_transition=self._on_transition,
                                     journal=self.journal)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # workload analytics: one access summary per daemon, merged on
        # demand behind GET /cluster/usage (stats/access.py)
        self.usage = access_mod.UsageAggregator(now=self.now)
        self._up: Dict[str, int] = {}      # target -> last liveness
        self._evt_cursor: Dict[str, int] = {}   # target -> remote seq
        self._evt_skip: set = set()        # same-process targets
        self.rounds = 0
        self.busy_seconds = 0.0
        self._duty = 0.0
        self._last_slo: Dict[str, dict] = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="health-plane", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(scrape_interval()):
            if not self.master.raft.is_leader:
                continue
            try:
                self.scrape_round()
            except Exception as e:  # the plane must outlive any scrape
                glog.warning(f"health plane round failed: {e}")

    # -- scraping ------------------------------------------------------------
    def targets(self) -> Dict[str, str]:
        """address -> kind, from every registry the master keeps:
        raft peers (masters), the heartbeat topology (volume servers)
        and /cluster/register members (filers, s3 gateways)."""
        out: Dict[str, str] = {}
        for peer in self.master.raft.peers:
            out[peer] = "master"
        with self.master.topo.lock:
            for url in self.master.topo.nodes:
                out.setdefault(url, "volume")
        for (typ, addr) in list(self.master._members):
            out.setdefault(addr, typ)
        return out

    def _priority_families(self) -> set:
        fams = {slo_mod.LIVENESS_FAMILY}
        for rule in self.slo.rules():
            fams.add(rule.family)
        return fams

    def scrape_round(self) -> dict:
        """One pass: scrape every target under its own deadline, feed
        the TSDB, fold remote journals in, evaluate SLO rules."""
        t0 = time.perf_counter()
        ts = self.now()
        targets = self.targets()
        budget = target_deadline()
        priority = self._priority_families()
        # a reaped/deregistered target must stop exporting liveness:
        # its stale gauge series would otherwise read as a permanent 0
        for gone in set(self._up) - set(targets):
            del self._up[gone]
            _stats.ClusterTargetUpGauge.remove(gone)
            self._evt_cursor.pop(gone, None)
            self._evt_skip.discard(gone)
        for addr, kind in targets.items():
            up = 0
            try:
                with policy.deadline_scope(timeout=budget):
                    text = policy.call_policy(
                        addr, "/metrics", timeout=budget, parse=False,
                        retries=0, breaker=False)
                if isinstance(text, bytes):
                    text = text.decode("utf-8", "replace")
                self.tsdb.ingest(addr, text, ts=ts, priority=priority)
                up = 1
            except Exception:
                _stats.ClusterScrapeErrorsCounter.labels(addr).inc()
            self.tsdb.put(slo_mod.LIVENESS_FAMILY,
                          {"target": addr, "kind": kind}, float(up),
                          tsdb_mod.GAUGE, ts=ts)
            _stats.ClusterTargetUpGauge.labels(addr, kind).set(float(up))
            prev = self._up.get(addr)
            if prev is not None and prev != up:
                self.journal.emit(
                    events_mod.NODE_UP if up else events_mod.NODE_DOWN,
                    service=kind, node=addr)
            self._up[addr] = up
            if up:
                self._pull_events(addr, budget)
                if kind != "volume":
                    # filer / S3 summaries come over the scrape loop;
                    # volume servers' ride their heartbeat (below)
                    self._pull_access(addr, budget)
        with self.master.topo.lock:
            beats = {url: dict(node.access)
                     for url, node in self.master.topo.nodes.items()
                     if getattr(node, "access", None)}
        for url, summary in beats.items():
            self.usage.ingest(url, summary)
        # the hot-key check merges every part's sketches — do it every
        # few rounds, not per-scrape (usage_view also checks on demand)
        if self.rounds % 5 == 0:
            try:
                self.usage.maybe_emit_hot_key(node=self.master.address)
            except Exception as e:
                glog.warning(f"hot-key check failed: {e}")
        self._last_slo = self.slo.evaluate()
        self.rounds += 1
        busy = time.perf_counter() - t0
        self.busy_seconds += busy
        self._duty = 0.7 * self._duty + 0.3 * (busy / scrape_interval())
        _stats.ClusterScrapeRoundsCounter.inc()
        _stats.ClusterScrapeDutyGauge.set(round(self._duty, 6))
        return self._last_slo

    def _pull_events(self, addr: str, budget: float):
        """Merge a remote daemon's journal (per-target cursor; a target
        sharing this process's global journal is detected by its token
        and skipped forever)."""
        if addr in self._evt_skip:
            return
        try:
            with policy.deadline_scope(timeout=budget):
                resp = policy.call_policy(
                    addr,
                    f"/cluster/events?since={self._evt_cursor.get(addr, 0)}",
                    timeout=budget, retries=0, breaker=False)
        except Exception:
            return
        if not isinstance(resp, dict):
            return
        if resp.get("journal") == self.journal.token:
            self._evt_skip.add(addr)
            return
        self.journal.merge(resp.get("events") or [])
        self._evt_cursor[addr] = int(resp.get("seq") or 0)

    def _pull_access(self, addr: str, budget: float):
        """Fetch a non-heartbeating daemon's access-sketch summary
        (GET /debug/access) into the usage aggregator.  Daemons
        without the route (older builds, masters) are just skipped."""
        try:
            with policy.deadline_scope(timeout=budget):
                resp = policy.call_policy(addr, "/debug/access",
                                          timeout=budget, retries=0,
                                          breaker=False)
        except Exception:
            return
        if isinstance(resp, dict) and "hot" in resp:
            self.usage.ingest(addr, resp)

    # -- alert push-downs ----------------------------------------------------
    def firing(self) -> List[str]:
        """Names of firing alerts — the curator passes these into
        scan_scale() as the opt-in WEED_SCALE_ON_ALERT trigger."""
        return self.slo.firing()

    def _on_transition(self, rule, alert, firing: bool):
        """An availability alert is actionable now, not on the next
        curator interval: run the repair detectors immediately and
        push their specs (fix.replication / ec.rebuild / deep.scrub of
        volumes on down servers) into the maintenance queue."""
        if not firing or rule.kind != "availability":
            return
        curator = getattr(self.master, "curator", None)
        if curator is None or not curator.enabled:
            return
        try:
            snap = detectors.snapshot(self.master.topo)
            specs = [s for s in detectors.scan(
                snap, now=self.now(), last_scrub=curator.last_scrub,
                vacuum_enabled=False, scale_enabled=False)
                if s["type"] in (TYPE_FIX_REPLICATION, TYPE_EC_REBUILD)]
            if alert.get("detail", {}).get("down"):
                # a down server may hold any shard: verify EC parity
                # now, bounded — the periodic sweep owns the long tail
                for e in snap.get("ec", [])[:8]:
                    specs.append({"type": TYPE_DEEP_SCRUB,
                                  "volume": e["id"],
                                  "collection": e["collection"],
                                  "params": {"from": rule.name}})
            for spec in specs:
                jid = curator.queue.enqueue(
                    spec["type"], spec["volume"], spec["collection"],
                    dict(spec["params"], alert=rule.name))
                if jid is not None:
                    self.journal.emit(events_mod.JOB_ENQUEUED,
                                      service="master",
                                      node=spec["type"],
                                      detail={"volume": spec["volume"],
                                              "alert": rule.name})
        except Exception as e:
            glog.warning(f"alert push to curator failed: {e}")

    # -- HTTP surface --------------------------------------------------------
    def health(self) -> dict:
        """The single JSON rollup behind GET /cluster/health."""
        targets = self.targets()
        liveness = {addr: bool(self._up.get(addr, 1))
                    for addr in targets}
        alerts = [a for a in self._last_slo.values() if a.get("firing")]
        status = "ok"
        if any(not up for up in liveness.values()) or alerts:
            status = "degraded"
        if any(a.get("kind") == "availability" for a in alerts):
            status = "critical"
        return {
            "status": status,
            "is_leader": self.master.raft.is_leader,
            "leader": self.master.raft.leader or "",
            "now": round(self.now(), 3),
            "nodes": {addr: {"kind": targets[addr], "up": liveness[addr]}
                      for addr in targets},
            "slo": self._last_slo,
            "alerts": alerts,
            "events": self.journal.since(limit=20),
            "scrape": {"interval_ms": scrape_interval() * 1000,
                       "deadline_ms": target_deadline() * 1000,
                       "rounds": self.rounds,
                       "duty": round(self._duty, 6)},
            "tsdb": self.tsdb.stats(),
        }

    def alerts(self) -> dict:
        return {"alerts": [a for a in self._last_slo.values()
                           if a.get("firing")],
                "rules": self._last_slo,
                "firing": self.firing()}

    def usage_view(self, req) -> dict:
        try:
            topk = int(req.param("topk", 0) or 0)
        except (TypeError, ValueError):
            topk = 0
        usage = self.usage.usage(topk=topk or None)
        try:
            self.usage.maybe_emit_hot_key(usage=usage,
                                          node=self.master.address)
        except Exception as e:
            glog.warning(f"hot-key check failed: {e}")
        return usage

    def mount(self, server):
        server.add("GET", "/cluster/health", lambda r: self.health())
        server.add("GET", "/cluster/alerts", lambda r: self.alerts())
        server.add("GET", "/cluster/usage", self.usage_view)
        events_mod.mount(server, self.journal)
