"""Liveness and readiness probes for every daemon.

``GET /healthz`` answers 200 the moment the RpcServer accepts
connections — process liveness, nothing else.  ``GET /readyz`` runs the
daemon's registered readiness checks (raft leader known, store mounted,
admission gates not saturated, not draining) and answers 503 with the
failing checks listed until all pass, so load balancers and
``weed.py top``/``cluster.check`` can tell "up" from "able to serve".
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable, Optional, Tuple

Check = Tuple[str, bool, str]  # (name, ok, detail)


def _gate_saturation() -> float:
    try:
        return float(os.environ.get("WEED_READY_GATE_OCC", "") or 0.95)
    except ValueError:
        return 0.95


def gate_check(gate) -> Check:
    """Shared readiness check: the QoS admission gate still has
    headroom (a saturated gate means new requests only queue)."""
    if gate is None:
        return ("gate", True, "no gate")
    occ = gate.occupancy()
    limit = _gate_saturation()
    return ("gate", occ < limit, f"occupancy={occ:.2f} limit={limit:.2f}")


def mount_health(server, ready: Optional[Callable[[], Iterable[Check]]]
                 = None):
    """Register /healthz + /readyz on an RpcServer (the qos.mount /
    faults.mount pattern).  ``ready`` returns the daemon's check
    tuples; omitted means always ready once serving."""

    def h_healthz(req):
        return {"ok": True, "service": server.service_name}

    def h_readyz(req):
        from ..rpc.http_rpc import Response

        checks: list = []
        if ready is not None:
            try:
                checks = list(ready())
            except Exception as e:  # a probe must never raise a 500
                checks = [("ready", False, f"{type(e).__name__}: {e}")]
        ok = all(c[1] for c in checks)
        body = {"ready": ok, "service": server.service_name,
                "checks": [{"name": n, "ok": good, "detail": d}
                           for n, good, d in checks]}
        if ok:
            return body
        return Response(json.dumps(body).encode(), status=503,
                        content_type="application/json")

    server.add("GET", "/healthz", h_healthz)
    server.add("GET", "/readyz", h_readyz)
