"""Per-daemon access recorder + master-side usage aggregation.

Every data-path daemon (volume server needle read/write, filer chunk
fetch, S3 GET/PUT) feeds its own :class:`AccessRecorder` instance.
The recorder keeps *sketches*, not keys: a Space-Saving top-K of hot
fids, HyperLogLogs for distinct-key counts, log-bucketed latency
quantiles per QoS class, and bounded per-collection / per-tenant
ops+bytes accounting.  Memory is bounded by ``WEED_HEAT_MAX_KEYS``
regardless of how many objects the workload touches.

Heat is *recency-weighted*: every ``WEED_HEAT_EPOCH_S`` the whole
state decays by ``WEED_HEAT_DECAY``, so a fid hot yesterday but idle
today drains out instead of pinning the sketch (epoch-windowed
exponential decay — the same shape as the QoS token buckets).

Summaries travel as canonical JSON (``summary()``): volume servers
attach theirs to the heartbeat they already send, and the master
health plane's scrape loop pulls ``GET /debug/access`` from filer /
S3 targets.  The leader folds them in a :class:`UsageAggregator`
(sketch merge, never raw key shipping) and serves the cluster view at
``GET /cluster/usage``; when one fid exceeds ``WEED_HEAT_HOT_SHARE``
of fleet reads it fires an ``access.hotkey`` journal event.

Knobs: ``WEED_HEAT`` (record at all, default on),
``WEED_HEAT_MAX_KEYS``, ``WEED_HEAT_EPOCH_S``, ``WEED_HEAT_DECAY``,
``WEED_HEAT_HOT_SHARE``, ``WEED_HEAT_MIN_READS``,
``WEED_USAGE_TOPK``, ``WEED_USAGE_MAX_AGE_S``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from . import metrics as _stats
from .sketch import HyperLogLog, LogQuantile, SpaceSaving
from .sketch import _hash64 as _sketch_hash

OTHER = "~other"       # overflow bucket once entity maps hit capacity

READ_OPS = ("read", "chunk")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Entity:
    """Per-collection / per-tenant accounting cell."""

    __slots__ = ("ops", "bytes", "hll")

    def __init__(self):
        self.ops: Dict[str, float] = {}
        self.bytes: Dict[str, float] = {}
        self.hll = HyperLogLog()

    def scale(self, factor: float) -> None:
        for d in (self.ops, self.bytes):
            for k in d:
                d[k] *= factor
        # the HLL is a high-water mark; distinct-key decay happens by
        # epoch-dropping at the aggregator (max-age), not in place

    def to_dict(self) -> dict:
        return {"ops": {k: round(v, 3) for k, v in sorted(self.ops.items())},
                "bytes": {k: round(v, 3)
                          for k, v in sorted(self.bytes.items())},
                "distinct": self.hll.to_dict()}


class AccessRecorder:
    """Bounded-memory access accounting for one daemon.

    Each server object (volume server, filer, S3 gateway) owns an
    instance — all-in-one processes then still report one summary per
    daemon role, the shape the leader's merge expects."""

    def __init__(self, node: str = "",
                 now: Callable[[], float] = time.time):
        self.node = node
        self.now = now
        self.lock = threading.Lock()
        # op -> bound counter child, so the hot path skips the
        # registry's label-resolution lock
        self._op_counters: dict = {}
        # volume id -> str cache for the per-volume heat sketch
        self._volkeys: Dict[int, str] = {}
        self.reset()
        _RECORDERS.add(self)

    def reset(self) -> None:
        """(Re)read knobs and drop all state — test seam, and how the
        prefork workers start clean after fork."""
        with self.lock:
            self.enabled = os.environ.get("WEED_HEAT", "1") not in ("0", "")
            self.max_keys = max(16, _env_int("WEED_HEAT_MAX_KEYS", 4096))
            self.epoch_s = max(0.25, _env_float("WEED_HEAT_EPOCH_S", 60.0))
            self.decay = min(1.0, max(0.0,
                                      _env_float("WEED_HEAT_DECAY", 0.5)))
            self.epoch_start = self.now()
            self.hot = SpaceSaving(self.max_keys)
            # per-volume read heat, the temperature detector's input
            self.vol_hot = SpaceSaving(min(self.max_keys, 4096))
            self.tenants: Dict[str, _Entity] = {}
            self.collections: Dict[str, _Entity] = {}
            self.latency: Dict[str, LogQuantile] = {}
            self.sizes = LogQuantile()
            self.tiers: Dict[str, float] = {}
            self.distinct = HyperLogLog()
            self.reads = self.writes = 0.0
            self.bytes_read = self.bytes_written = 0.0
            self.records = 0   # monotonic, never decayed
            # HLL adds are idempotent, so a bounded seen-set makes
            # repeats (the whole point of a zipfian data path) skip
            # the hash-and-rank work; cleared wholesale when full —
            # purely a fast path, never a correctness dependency
            self._key_hash: Dict[str, int] = {}
            self._hll_seen: set = set()
            # metrics-counter increments batch under the recorder lock
            # and flush every 64 records (and on summary())
            self._pending_ops: Dict[str, int] = {}

    # -- recording ---------------------------------------------------

    def _maybe_roll(self, now: float) -> None:
        elapsed = now - self.epoch_start
        if elapsed < self.epoch_s:
            return
        epochs = int(elapsed // self.epoch_s)
        factor = self.decay ** min(epochs, 64)
        self.epoch_start += epochs * self.epoch_s
        self.hot.scale(factor)
        self.vol_hot.scale(factor)
        self.sizes.scale(factor)
        for lq in self.latency.values():
            lq.scale(factor)
        for ent in list(self.tenants.values()):
            ent.scale(factor)
        for ent in list(self.collections.values()):
            ent.scale(factor)
        for k in self.tiers:
            self.tiers[k] *= factor
        self.reads *= factor
        self.writes *= factor
        self.bytes_read *= factor
        self.bytes_written *= factor

    def _entity(self, table: Dict[str, _Entity], key: str) -> _Entity:
        ent = table.get(key)
        if ent is None:
            if len(table) >= min(self.max_keys, 1024) and key != OTHER:
                return self._entity(table, OTHER)
            ent = table[key] = _Entity()
        return ent

    def record(self, op: str, collection: str = "", tenant: str = "",
               volume: int = 0, fid: str = "", nbytes: int = 0,
               latency_s: float = 0.0, qos_class: str = "",
               cache_tier: str = "") -> None:
        """One data-path access.  ``op`` is read/write/delete/chunk;
        reads feed the hot-fid sketch, everything feeds usage."""
        if not self.enabled:
            return
        now = self.now()
        key = fid or (f"v{volume}" if volume else "")
        with self.lock:
            self._maybe_roll(now)
            self.records += 1
            seen = self._hll_seen
            if len(seen) > 65536:
                seen.clear()
            if key:
                # hash once per distinct key (bounded memo); the
                # distinct HLL and both entity HLLs share it
                khash = self._key_hash.get(key)
                if khash is None:
                    if len(self._key_hash) > 65536:
                        self._key_hash.clear()
                    khash = self._key_hash[key] = _sketch_hash(key)
                if khash not in seen:
                    seen.add(khash)
                    self.distinct.add_hash(khash)
            else:
                khash = 0
            is_read = op in READ_OPS
            if is_read:
                self.reads += 1.0
                self.bytes_read += nbytes
                if key:
                    self.hot.offer(key)
                if volume:
                    vkey = self._volkeys.get(volume)
                    if vkey is None:
                        if len(self._volkeys) > 65536:
                            self._volkeys.clear()
                        vkey = self._volkeys[volume] = str(volume)
                    self.vol_hot.offer(vkey)
            elif op == "write":
                self.writes += 1.0
                self.bytes_written += nbytes
            # the quantile sketches are statistical anyway: observe a
            # systematic 1-in-4 sample at 4x weight, trading a little
            # tail resolution for most of their data-path cost
            if not self.records & 3:
                if nbytes > 0:
                    self.sizes.observe(float(nbytes), 4.0)
                if latency_s > 0:
                    cls = qos_class or "default"
                    lq = self.latency.get(cls)
                    if lq is None:
                        if len(self.latency) < 64:
                            lq = self.latency[cls] = LogQuantile()
                        else:
                            lq = self.latency.setdefault("default",
                                                         LogQuantile())
                    lq.observe(latency_s, 4.0)
            if cache_tier:
                self.tiers[cache_tier] = self.tiers.get(cache_tier, 0) + 1.0
            for table, name in ((self.collections, collection or "default"),
                                (self.tenants, tenant or "anonymous")):
                ent = self._entity(table, name)
                ops = ent.ops
                ops[op] = ops.get(op, 0.0) + 1.0
                byt = ent.bytes
                byt[op] = byt.get(op, 0.0) + nbytes
                if key:
                    ek = (name, khash)
                    if ek not in seen:
                        seen.add(ek)
                        ent.hll.add_hash(khash)
            pending = self._pending_ops
            pending[op] = pending.get(op, 0) + 1
            if not self.records & 63:
                self._flush_ops()

    def _flush_ops(self) -> None:
        """Flush batched per-op counts to the registry counter.
        Caller holds ``self.lock``."""
        for op, n in self._pending_ops.items():
            counter = self._op_counters.get(op)
            if counter is None:
                counter = self._op_counters[op] = \
                    _stats.AccessRecordsCounter.labels(op)
            counter.inc(n)
        self._pending_ops.clear()

    # -- queries -----------------------------------------------------

    def heat(self, fid: str) -> float:
        """Decayed read count for one fid (read cache promotion)."""
        with self.lock:
            return self.hot.estimate(fid)

    def tracked_keys(self) -> int:
        with self.lock:
            return len(self.hot)

    def memory_bytes(self) -> int:
        """Rough in-memory footprint of the sketch state (bench +
        metrics; the point is the bound, not byte accuracy)."""
        with self.lock:
            n = (len(self.hot) + len(self.vol_hot)) * 96 + self.distinct.m
            n += sum(len(lq.buckets) * 48 + 64
                     for lq in self.latency.values())
            n += len(self.sizes.buckets) * 48
            for table in (self.tenants, self.collections):
                for ent in table.values():
                    n += ent.hll.m + 128
            return n

    def summary(self) -> dict:
        """Canonical mergeable wire form of this daemon's view."""
        with self.lock:
            self._maybe_roll(self.now())
            self._flush_ops()
            return {
                "node": self.node, "ts": round(self.now(), 3),
                "records": self.records,
                "reads": round(self.reads, 3),
                "writes": round(self.writes, 3),
                "bytes_read": round(self.bytes_read, 3),
                "bytes_written": round(self.bytes_written, 3),
                "hot": self.hot.to_dict(),
                "volumes": self.vol_hot.to_dict(),
                "distinct": self.distinct.to_dict(),
                "sizes": self.sizes.to_dict(),
                "latency": {cls: lq.to_dict()
                            for cls, lq in sorted(self.latency.items())},
                "tiers": {k: round(v, 3)
                          for k, v in sorted(self.tiers.items())},
                "collections": {k: ent.to_dict()
                                for k, ent in
                                sorted(self.collections.items())},
                "tenants": {k: ent.to_dict()
                            for k, ent in sorted(self.tenants.items())},
            }


# every live recorder, for the process-wide self-metrics gauges
_RECORDERS: "weakref.WeakSet[AccessRecorder]" = weakref.WeakSet()

# default recorder for callers without a server-scoped instance
RECORDER = AccessRecorder()


def record(op: str, **kw) -> None:
    """Module-level convenience mirroring ``events.emit``."""
    RECORDER.record(op, **kw)


def reset() -> None:
    RECORDER.reset()


def tracked_keys_total() -> int:
    return sum(r.tracked_keys() for r in list(_RECORDERS))


def memory_bytes_total() -> int:
    return sum(r.memory_bytes() for r in list(_RECORDERS))


def access_handler(req, recorder: Optional[AccessRecorder] = None):
    rec = recorder or RECORDER
    return rec.summary()


def mount(server, recorder: Optional[AccessRecorder] = None) -> None:
    """Register ``GET /debug/access`` (the qos.mount/faults.mount
    pattern) so the leader scrape loop can pull non-heartbeat daemons
    (filer, S3 gateway) into the fleet view."""
    server.add("GET", "/debug/access",
               lambda req: access_handler(req, recorder))


# ---------------------------------------------------------------------------
# master-side aggregation


def merge_summaries(parts: List[dict],
                    capacity: Optional[int] = None) -> dict:
    """Fold per-daemon summaries into one fleet summary — pure sketch
    merge (Space-Saving union, HLL register max, bucket adds), exactly
    the ``merge_expositions`` posture: daemons ship summaries, never
    raw key streams."""
    cap = capacity or max(16, _env_int("WEED_HEAT_MAX_KEYS", 4096))
    hot = SpaceSaving(cap)
    vol_hot = SpaceSaving(min(cap, 4096))
    distinct = HyperLogLog()
    sizes = LogQuantile()
    latency: Dict[str, LogQuantile] = {}
    tiers: Dict[str, float] = {}
    collections: Dict[str, dict] = {}
    tenants: Dict[str, dict] = {}
    totals = {"reads": 0.0, "writes": 0.0, "bytes_read": 0.0,
              "bytes_written": 0.0, "records": 0}

    def _fold_entities(dst: Dict[str, dict], src: Dict[str, dict]):
        for name, ent in (src or {}).items():
            cell = dst.get(name)
            if cell is None:
                cell = dst[name] = {"ops": {}, "bytes": {},
                                    "hll": HyperLogLog()}
            for k, v in (ent.get("ops") or {}).items():
                cell["ops"][k] = cell["ops"].get(k, 0.0) + float(v)
            for k, v in (ent.get("bytes") or {}).items():
                cell["bytes"][k] = cell["bytes"].get(k, 0.0) + float(v)
            d = ent.get("distinct")
            if d:
                cell["hll"].merge(HyperLogLog.from_dict(d))

    for part in parts:
        if not part:
            continue
        for k in ("reads", "writes", "bytes_read", "bytes_written"):
            totals[k] += float(part.get(k, 0) or 0)
        totals["records"] += int(part.get("records", 0) or 0)
        if part.get("hot"):
            hot.merge(SpaceSaving.from_dict(part["hot"]))
        if part.get("volumes"):
            vol_hot.merge(SpaceSaving.from_dict(part["volumes"]))
        if part.get("distinct"):
            distinct.merge(HyperLogLog.from_dict(part["distinct"]))
        if part.get("sizes"):
            sizes.merge(LogQuantile.from_dict(part["sizes"]))
        for cls, d in (part.get("latency") or {}).items():
            lq = latency.get(cls)
            if lq is None:
                latency[cls] = LogQuantile.from_dict(d)
            else:
                lq.merge(LogQuantile.from_dict(d))
        for k, v in (part.get("tiers") or {}).items():
            tiers[k] = tiers.get(k, 0.0) + float(v)
        _fold_entities(collections, part.get("collections") or {})
        _fold_entities(tenants, part.get("tenants") or {})

    return {"totals": totals, "hot": hot, "vol_hot": vol_hot,
            "distinct": distinct, "sizes": sizes, "latency": latency,
            "tiers": tiers, "collections": collections,
            "tenants": tenants}


def _quantile_view(lq: LogQuantile) -> dict:
    return {"count": round(lq.count, 3), "mean": round(lq.mean(), 6),
            "p50": round(lq.quantile(0.5), 6),
            "p90": round(lq.quantile(0.9), 6),
            "p99": round(lq.quantile(0.99), 6)}


class UsageAggregator:
    """Leader-resident fold of every daemon's latest access summary.

    Each daemon's summary is a decayed *snapshot*, so the aggregator
    keeps exactly one per node (replace, don't accumulate) and merges
    across nodes on demand — double counting is structurally
    impossible.  Nodes silent for ``WEED_USAGE_MAX_AGE_S`` age out.
    """

    def __init__(self, now: Callable[[], float] = time.time):
        self.now = now
        self.lock = threading.Lock()
        self.parts: Dict[str, dict] = {}     # node -> summary
        self._hot_emitted: Dict[str, float] = {}

    def ingest(self, node: str, summary: Optional[dict]) -> None:
        if not node or not isinstance(summary, dict):
            return
        with self.lock:
            self.parts[node] = summary

    def _fresh_parts(self) -> Dict[str, dict]:
        max_age = max(1.0, _env_float("WEED_USAGE_MAX_AGE_S", 300.0))
        cutoff = self.now() - max_age
        with self.lock:
            self.parts = {n: s for n, s in self.parts.items()
                          if float(s.get("ts", 0) or 0) >= cutoff}
            return dict(self.parts)

    def usage(self, topk: Optional[int] = None) -> dict:
        """The ``GET /cluster/usage`` body."""
        k = topk or max(1, _env_int("WEED_USAGE_TOPK", 20))
        parts = self._fresh_parts()
        merged = merge_summaries(list(parts.values()))
        totals = merged["totals"]
        reads = totals["reads"] or 0.0
        top = [{"fid": fid, "reads": round(cnt, 3),
                "error": round(err, 3),
                "share": round(cnt / reads, 4) if reads else 0.0}
               for fid, cnt, err in merged["hot"].top(k)]
        out = {
            "ts": round(self.now(), 3),
            "nodes": sorted(parts),
            "totals": {"reads": round(totals["reads"], 3),
                       "writes": round(totals["writes"], 3),
                       "bytes_read": round(totals["bytes_read"], 3),
                       "bytes_written": round(totals["bytes_written"], 3),
                       "records": totals["records"],
                       "distinct_keys":
                           int(merged["distinct"].estimate())},
            "top_keys": top,
            "volumes": {vid: round(cnt, 3)
                        for vid, cnt, _ in merged["vol_hot"].top(0)},
            "tiers": {k2: round(v, 3)
                      for k2, v in sorted(merged["tiers"].items())},
            "sizes": _quantile_view(merged["sizes"]),
            "latency": {cls: _quantile_view(lq)
                        for cls, lq in sorted(merged["latency"].items())},
            "collections": {}, "tenants": {},
        }
        for name, table in (("collections", merged["collections"]),
                            ("tenants", merged["tenants"])):
            for ent_name, cell in sorted(table.items()):
                out[name][ent_name] = {
                    "ops": {k2: round(v, 3)
                            for k2, v in sorted(cell["ops"].items())},
                    "bytes": {k2: round(v, 3)
                              for k2, v in sorted(cell["bytes"].items())},
                    "distinct_keys": int(cell["hll"].estimate()),
                }
        self._export(out)
        return out

    def _export(self, usage: dict) -> None:
        """Mirror the assembled view into ``SeaweedFS_usage_*`` gauges
        so the TSDB / Grafana see what ``/cluster/usage`` serves."""
        t = usage["totals"]
        _stats.UsageReadsGauge.labels().set(t["reads"])
        _stats.UsageWritesGauge.labels().set(t["writes"])
        _stats.UsageBytesGauge.labels("read").set(t["bytes_read"])
        _stats.UsageBytesGauge.labels("write").set(t["bytes_written"])
        _stats.UsageDistinctKeysGauge.labels().set(t["distinct_keys"])
        _stats.UsageTenantsGauge.labels().set(len(usage["tenants"]))
        _stats.UsageCollectionsGauge.labels().set(len(usage["collections"]))
        top = usage["top_keys"]
        _stats.UsageHotShareGauge.labels().set(
            top[0]["share"] if top else 0.0)

    def maybe_emit_hot_key(self, usage: Optional[dict] = None,
                           node: str = "") -> Optional[dict]:
        """Fire an ``access.hotkey`` journal event when the hottest
        fid exceeds ``WEED_HEAT_HOT_SHARE`` of fleet reads (with
        enough reads to mean anything); deduped per fid per epoch so
        a steady hot key doesn't spam the journal."""
        from . import events

        share_gate = _env_float("WEED_HEAT_HOT_SHARE", 0.25)
        min_reads = _env_float("WEED_HEAT_MIN_READS", 100.0)
        if usage is None:
            usage = self.usage(topk=1)
        top = usage.get("top_keys") or []
        reads = float(usage.get("totals", {}).get("reads", 0) or 0)
        if not top or reads < min_reads:
            return None
        head = top[0]
        if head["share"] < share_gate:
            return None
        epoch = max(0.25, _env_float("WEED_HEAT_EPOCH_S", 60.0))
        now = self.now()
        with self.lock:
            last = self._hot_emitted.get(head["fid"], 0.0)
            if now - last < epoch:
                return None
            self._hot_emitted[head["fid"]] = now
            if len(self._hot_emitted) > 1024:
                cut = sorted(self._hot_emitted.values())[512]
                self._hot_emitted = {
                    f: t for f, t in self._hot_emitted.items() if t > cut}
        return events.emit(events.HOT_KEY, service="master", node=node,
                           detail={"fid": head["fid"],
                                   "share": head["share"],
                                   "reads": head["reads"],
                                   "fleet_reads": round(reads, 1)})
