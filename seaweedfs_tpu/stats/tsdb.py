"""Bounded in-memory ring TSDB for the master-resident health plane.

The leader's scrape loop (master/health.py) polls every registered
daemon's ``/metrics`` and feeds the text exposition here.  Each series
is a fixed-interval ring: slot ``i`` holds the sample whose timestamp
falls in ``[i*interval, (i+1)*interval)``, so retention is
``slots * interval`` seconds and memory is strictly bounded — there is
no per-sample allocation after warm-up.  Counters are delta-aware: the
ring stores the raw cumulative value and the query layer sums
monotone increases (a restart that resets a counter to zero contributes
nothing negative).

Knobs (read live, like every WEED_* knob in this tree):

* ``WEED_TSDB_RETENTION``  — seconds of history per series (default 900)
* ``WEED_TSDB_MAX_SERIES`` — cardinality cap; series past the cap are
  dropped and counted in ``SeaweedFS_cluster_tsdb_dropped_total``
"""

from __future__ import annotations

import os
import threading
import time
from array import array
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import metrics as _stats

GAUGE = "gauge"
COUNTER = "counter"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def retention_seconds() -> float:
    return max(10.0, _env_float("WEED_TSDB_RETENTION", 900.0))


def max_series() -> int:
    return max(16, int(_env_float("WEED_TSDB_MAX_SERIES", 4096)))


# -- text exposition parsing --------------------------------------------------
def _parse_labels(raw: str) -> Dict[str, str]:
    """``a="x",b="y"`` -> dict.  Handles escaped quotes/backslashes the
    way our own expose() emits them; a malformed pair is skipped rather
    than poisoning the whole scrape."""
    out: Dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0:
            break
        name = raw[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if i >= n or raw[i] != '"':
            break
        i += 1
        buf = []
        while i < n:
            c = raw[i]
            if c == "\\" and i + 1 < n:
                nxt = raw[i + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                break
            buf.append(c)
            i += 1
        out[name] = "".join(buf)
        i += 1  # closing quote
    return out


def parse_exposition(text: str):
    """Parse prometheus text format into ``(types, samples)`` where
    ``types`` maps family -> declared TYPE and ``samples`` is a list of
    ``(sample_name, labels_dict, value)``."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            words = line.split(None, 3)
            if len(words) >= 4 and words[1] == "TYPE":
                types[words[2]] = words[3].strip()
            continue
        sample, _, value = line.rpartition(" ")
        if not sample:
            continue
        try:
            val = float(value)
        except ValueError:
            continue
        if sample.endswith("}"):
            brace = sample.find("{")
            if brace < 0:
                continue
            name = sample[:brace]
            labels = _parse_labels(sample[brace + 1:-1])
        else:
            name, labels = sample, {}
        samples.append((name, labels, val))
    return types, samples


def kind_for(sample_name: str, types: Dict[str, str]) -> str:
    """Sample kind from the family TYPE declarations.  Histogram and
    summary components (`_bucket`/`_count`/`_sum`) are cumulative, so
    they are counters for delta purposes."""
    if sample_name in types:
        return COUNTER if types[sample_name] == "counter" else GAUGE
    for suffix in ("_bucket", "_count", "_sum", "_total"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in types or suffix == "_total":
                return COUNTER
    return GAUGE


# -- the ring -----------------------------------------------------------------
class _Ring:
    """Fixed-interval ring of (slot_index, value).  ``idx[p]`` records
    which absolute interval the slot currently holds, so stale laps are
    distinguishable without a sweep."""

    __slots__ = ("interval", "slots", "idx", "vals", "kind", "last")

    def __init__(self, interval: float, slots: int, kind: str):
        self.interval = interval
        self.slots = slots
        # array, not list: 16 bytes/slot keeps a full-cardinality TSDB
        # (WEED_TSDB_MAX_SERIES rings) in tens of MB, not hundreds
        self.idx = array("q", [-1]) * slots
        self.vals = array("d", [0.0]) * slots
        self.kind = kind
        self.last = 0.0  # most recent raw value (counters: cumulative)

    def put(self, ts: float, value: float):
        i = int(ts // self.interval)
        p = i % self.slots
        self.idx[p] = i
        self.vals[p] = value
        self.last = value

    def window(self, now: float, seconds: float) -> List[Tuple[float, float]]:
        """Samples with timestamps in ``[now - seconds, now]``, oldest
        first (timestamps reconstructed at slot start)."""
        lo = int((now - seconds) // self.interval)
        hi = int(now // self.interval)
        out = []
        # clamp at 0: negative absolute indices would collide with the
        # -1 empty-slot sentinel in ``idx``
        for i in range(max(lo, hi - self.slots + 1, 0), hi + 1):
            p = i % self.slots
            if self.idx[p] == i:
                out.append((i * self.interval, self.vals[p]))
        return out

    def delta(self, now: float, seconds: float) -> float:
        """Summed monotone increase over the window (counter reset
        contributes zero, not a negative swing)."""
        pts = self.window(now, seconds)
        total, prev = 0.0, None
        for _, v in pts:
            if prev is not None and v >= prev:
                total += v - prev
            prev = v
        return total


class Tsdb:
    """Bounded map of series key -> ring.  The series key is the sample
    name plus its sorted label items, so histogram buckets, _sum and
    _count each get their own ring."""

    def __init__(self, interval: float = 5.0,
                 now: Callable[[], float] = time.time):
        self.interval = max(0.05, float(interval))
        self.now = now  # fake-clock seam
        self.lock = threading.Lock()
        self.series: Dict[tuple, _Ring] = {}
        self.dropped = 0

    def _slots(self) -> int:
        return max(4, int(retention_seconds() / self.interval) + 1)

    def _ring(self, name: str, labels: Dict[str, str], kind: str):
        key = (name, tuple(sorted(labels.items())))
        ring = self.series.get(key)
        if ring is None:
            if len(self.series) >= max_series():
                self.dropped += 1
                _stats.ClusterTsdbDroppedCounter.inc()
                return None
            ring = self.series[key] = _Ring(self.interval, self._slots(),
                                            kind)
        return ring

    def put(self, name: str, labels: Dict[str, str], value: float,
            kind: str = GAUGE, ts: Optional[float] = None):
        with self.lock:
            ring = self._ring(name, labels, kind)
            if ring is not None:
                ring.put(self.now() if ts is None else ts, value)

    SELF_FAMILY_PREFIX = "SeaweedFS_cluster_"

    def ingest(self, target: str, text: str, ts: Optional[float] = None,
               priority: Optional[set] = None,
               skip_prefix: Optional[str] = SELF_FAMILY_PREFIX):
        """Parse one scrape and store every sample with a ``target``
        label stamped on (the scrape loop's equivalent of prometheus's
        ``instance``).  ``priority`` names sample families that must
        claim series slots before the rest of the scrape — the health
        plane passes the families its SLO rules reference, so a
        cardinality cap can never starve the alert evaluator.

        ``skip_prefix`` drops the health plane's OWN derived families
        from scraped text: the leader exports its liveness/SLO gauges
        on /metrics, and re-ingesting them would feed the evaluator its
        own output — a stale ``cluster_target_up 0`` series scraped
        back in can hold an availability alert firing forever."""
        types, samples = parse_exposition(text)
        stamp = self.now() if ts is None else ts
        if skip_prefix:
            samples = [s for s in samples
                       if not s[0].startswith(skip_prefix)]
        if priority:
            samples.sort(key=lambda s: 0 if s[0] in priority
                         or s[0].rsplit("_", 1)[0] in priority else 1)
        with self.lock:
            for name, labels, value in samples:
                labels = dict(labels)
                labels["target"] = target
                ring = self._ring(name, labels, kind_for(name, types))
                if ring is not None:
                    ring.put(stamp, value)
        _stats.ClusterTsdbSeriesGauge.set(float(len(self.series)))

    # -- queries -------------------------------------------------------------
    def _match(self, name: str, match: Optional[Dict[str, str]]):
        for (sname, items), ring in list(self.series.items()):
            if sname != name:
                continue
            if match:
                labels = dict(items)
                if any(labels.get(k) != v for k, v in match.items()):
                    continue
            yield items, ring

    def latest(self, name: str, match: Optional[Dict[str, str]] = None
               ) -> Dict[tuple, float]:
        with self.lock:
            return {items: ring.last
                    for items, ring in self._match(name, match)}

    def avg(self, name: str, seconds: float,
            match: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Mean of every matching sample in the window (gauges)."""
        now = self.now()
        total, count = 0.0, 0
        with self.lock:
            for _, ring in self._match(name, match):
                for _, v in ring.window(now, seconds):
                    total += v
                    count += 1
        return (total / count) if count else None

    def delta(self, name: str, seconds: float,
              match: Optional[Dict[str, str]] = None) -> float:
        """Summed counter increase across matching series."""
        now = self.now()
        with self.lock:
            return sum(ring.delta(now, seconds)
                       for _, ring in self._match(name, match))

    def histogram_window(self, family: str, seconds: float,
                         match: Optional[Dict[str, str]] = None):
        """Windowed delta of a histogram family, merged across targets
        and workers: ``(sorted [(le, cumulative_delta)], count_delta)``."""
        buckets: Dict[float, float] = {}
        now = self.now()
        with self.lock:
            for items, ring in self._match(family + "_bucket", match):
                labels = dict(items)
                try:
                    le = float(labels.get("le", "+Inf").replace(
                        "+Inf", "inf"))
                except ValueError:
                    continue
                d = ring.delta(now, seconds)
                buckets[le] = buckets.get(le, 0.0) + d
            count = sum(ring.delta(now, seconds)
                        for _, ring in self._match(family + "_count",
                                                   match))
        return sorted(buckets.items()), count

    def families(self) -> set:
        with self.lock:
            return {name for (name, _) in self.series}

    def stats(self) -> dict:
        with self.lock:
            return {"series": len(self.series), "dropped": self.dropped,
                    "interval": self.interval,
                    "retention": retention_seconds()}


def quantile(buckets: Iterable[Tuple[float, float]], count: float,
             q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over cumulative le-buckets
    (linear interpolation inside the straddling bucket)."""
    pts = sorted(buckets)
    if not pts or count <= 0:
        return None
    rank = q * count
    prev_le, prev_c = 0.0, 0.0
    for le, c in pts:
        if c >= rank:
            if le == float("inf"):
                return prev_le
            span = c - prev_c
            frac = ((rank - prev_c) / span) if span > 0 else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_c = le, c
    return prev_le
