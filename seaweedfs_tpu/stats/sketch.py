"""Bounded-memory mergeable workload sketches.

Three summaries, all with the same contract: bounded memory, an
associative/commutative ``merge()`` so per-daemon (and per-prefork-
worker) summaries compose into a fleet view the way
``metrics.merge_expositions`` / ``profiling.merge_folded`` already
compose text expositions, and a canonical JSON-able ``to_dict()`` /
``from_dict()`` wire form so summaries can ride heartbeats and scrape
responses without pickling.

- :class:`SpaceSaving` — top-K heavy hitters (Metwally et al.), used
  for hot fids / hot tenants.  Counts are floats so exponential decay
  is a single ``scale()``.
- :class:`HyperLogLog` — distinct-key cardinality with register-wise
  max merge (exactly associative).  Hashing is blake2b, so estimates
  are stable across processes regardless of ``PYTHONHASHSEED``.
- :class:`LogQuantile` — DDSketch-style log-bucketed histogram for
  latency / size quantiles with guaranteed relative error; merge is a
  bucket-wise add (exactly associative).
"""

from __future__ import annotations

import hashlib
import heapq
import math
import struct
from typing import Dict, List, Optional, Tuple


def _hash64(key: str) -> int:
    """Deterministic 64-bit hash (process/seed independent)."""
    digest = hashlib.blake2b(key.encode("utf-8", "surrogatepass"),
                             digest_size=8).digest()
    return struct.unpack(">Q", digest)[0]


# ---------------------------------------------------------------------------
# Space-Saving heavy hitters


class SpaceSaving:
    """Top-K heavy hitters over a weighted key stream.

    Keeps at most ``capacity`` counters.  When a new key arrives at a
    full table it replaces the minimum counter and inherits its count
    as overestimation ``error`` (the classic Space-Saving move), so
    ``estimate(key) - error(key)`` is a guaranteed lower bound and
    keys whose weight exceeds total/capacity are never lost.

    ``merge`` is the Misra-Gries-style union: sum counts and errors
    over the key union, then truncate back to capacity dropping the
    smallest counters (deterministic ``(-count, key)`` order, so merge
    is commutative; it is associative up to the usual truncation error
    bound, and exact whenever the union fits in ``capacity``).
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        # key -> [count, error]; floats so decay composes
        self.counts: Dict[str, List[float]] = {}
        self.total = 0.0       # total offered weight (decays too)
        # lazy min-heap of (count, key): entries go stale when a key is
        # incremented (count too low) or evicted, and are repaired on
        # pop — keeps eviction O(log n) instead of a full min() scan on
        # every miss, which dominates record() cost on a full table
        self._heap: List[Tuple[float, str]] = []

    def __len__(self) -> int:
        return len(self.counts)

    def _rebuild_heap(self) -> None:
        self._heap = [(slot[0], key)
                      for key, slot in self.counts.items()]
        heapq.heapify(self._heap)

    def offer(self, key: str, weight: float = 1.0) -> None:
        if weight <= 0:
            return
        self.total += weight
        slot = self.counts.get(key)
        if slot is not None:
            slot[0] += weight
            return
        if len(self.counts) < self.capacity:
            self.counts[key] = [weight, 0.0]
            heapq.heappush(self._heap, (weight, key))
            return
        # repair the heap top until it names the true minimum counter
        # (ties break toward the smaller key, matching top()'s order)
        heap = self._heap
        while True:
            vcount, vkey = heap[0]
            cur = self.counts.get(vkey)
            if cur is None:
                heapq.heappop(heap)
            elif cur[0] != vcount:
                heapq.heapreplace(heap, (cur[0], vkey))
            else:
                break
        del self.counts[vkey]
        self.counts[key] = [vcount + weight, vcount]
        heapq.heapreplace(heap, (vcount + weight, key))

    def estimate(self, key: str) -> float:
        slot = self.counts.get(key)
        return slot[0] if slot is not None else 0.0

    def error(self, key: str) -> float:
        slot = self.counts.get(key)
        return slot[1] if slot is not None else 0.0

    def top(self, k: int = 0) -> List[Tuple[str, float, float]]:
        """``[(key, count, error)]`` best-first, deterministic order."""
        items = sorted(self.counts.items(),
                       key=lambda kv: (-kv[1][0], kv[0]))
        if k:
            items = items[:k]
        return [(key, slot[0], slot[1]) for key, slot in items]

    def scale(self, factor: float, floor: float = 1e-3) -> None:
        """Exponential decay: multiply every counter (and the total)
        by ``factor``, dropping counters that decayed below ``floor``
        so an idle sketch drains to empty instead of pinning stale
        keys forever."""
        if factor >= 1.0:
            return
        self.total *= factor
        dead = []
        for key, slot in self.counts.items():
            slot[0] *= factor
            slot[1] *= factor
            if slot[0] < floor:
                dead.append(key)
        for key in dead:
            del self.counts[key]
        self._rebuild_heap()

    def merge(self, other: "SpaceSaving") -> "SpaceSaving":
        self.total += other.total
        for key, (count, err) in other.counts.items():
            slot = self.counts.get(key)
            if slot is not None:
                slot[0] += count
                slot[1] += err
            else:
                self.counts[key] = [count, err]
        if len(self.counts) > self.capacity:
            keep = sorted(self.counts.items(),
                          key=lambda kv: (-kv[1][0], kv[0]))
            self.counts = {k: v for k, v in keep[:self.capacity]}
        self._rebuild_heap()
        return self

    def to_dict(self) -> dict:
        return {"kind": "space_saving", "capacity": self.capacity,
                "total": round(self.total, 6),
                "counts": {k: [round(v[0], 6), round(v[1], 6)]
                           for k, v in sorted(self.counts.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "SpaceSaving":
        sk = cls(int(d.get("capacity", 256) or 256))
        sk.total = float(d.get("total", 0.0) or 0.0)
        for key, slot in (d.get("counts") or {}).items():
            sk.counts[str(key)] = [float(slot[0]), float(slot[1])]
        sk._rebuild_heap()
        return sk


# ---------------------------------------------------------------------------
# HyperLogLog cardinality


class HyperLogLog:
    """Distinct-count sketch with ``2**p`` 6-bit registers.

    Standard-error ~= 1.04 / sqrt(2**p); the default p=10 (1 KiB of
    registers) gives ~3.2% which is plenty for "how many distinct fids
    did this collection touch".  ``merge`` is a register-wise max —
    exactly associative and commutative, and idempotent, so re-merging
    a summary is harmless.
    """

    def __init__(self, p: int = 10):
        self.p = min(18, max(4, int(p)))
        self.m = 1 << self.p
        self.registers = bytearray(self.m)
        self._shift = 64 - self.p
        self._mask = (1 << self._shift) - 1

    def add(self, key: str) -> None:
        self.add_hash(_hash64(key))

    def add_hash(self, h: int) -> None:
        """Add a pre-computed ``_hash64`` value — callers feeding the
        same key to several sketches hash once and share it."""
        idx = h >> self._shift
        # rank = leading zeros of the remaining bits, + 1
        rank = self._shift - (h & self._mask).bit_length() + 1
        if rank > self.registers[idx]:
            self.registers[idx] = rank

    def estimate(self) -> float:
        m = self.m
        inv_sum = 0.0
        zeros = 0
        for r in self.registers:
            inv_sum += 2.0 ** -r
            if r == 0:
                zeros += 1
        alpha = 0.7213 / (1.0 + 1.079 / m)
        raw = alpha * m * m / inv_sum
        if raw <= 2.5 * m and zeros:
            return m * math.log(m / zeros)   # linear counting
        return raw

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        if other.p != self.p:
            raise ValueError(f"HLL precision mismatch: {self.p} vs {other.p}")
        for i, r in enumerate(other.registers):
            if r > self.registers[i]:
                self.registers[i] = r
        return self

    def to_dict(self) -> dict:
        # hex-pack the registers: canonical, compact, JSON-safe
        return {"kind": "hll", "p": self.p,
                "registers": bytes(self.registers).hex()}

    @classmethod
    def from_dict(cls, d: dict) -> "HyperLogLog":
        hll = cls(int(d.get("p", 10) or 10))
        raw = bytes.fromhex(d.get("registers") or "")
        if len(raw) == hll.m:
            hll.registers = bytearray(raw)
        return hll


# ---------------------------------------------------------------------------
# Log-bucketed quantiles


class LogQuantile:
    """Mergeable quantile sketch over positive values (latency, size).

    Values land in geometric buckets ``gamma**i`` with
    ``gamma = (1+alpha)/(1-alpha)``, bounding the relative error of
    any reported quantile by ``alpha`` (DDSketch's guarantee).
    Bucket counts are floats so the access plane's exponential decay
    applies uniformly; merge adds bucket-wise and is exact.
    """

    def __init__(self, alpha: float = 0.01):
        self.alpha = min(0.5, max(1e-4, float(alpha)))
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._lg = math.log(self.gamma)
        self._inv_lg = 1.0 / self._lg
        self.buckets: Dict[int, float] = {}
        self.zeros = 0.0
        self.count = 0.0
        self.sum = 0.0

    def observe(self, value: float, weight: float = 1.0) -> None:
        if weight <= 0:
            return
        self.count += weight
        self.sum += value * weight
        if value <= 0:
            self.zeros += weight
            return
        idx = math.ceil(math.log(value) * self._inv_lg)
        self.buckets[idx] = self.buckets.get(idx, 0.0) + weight

    def quantile(self, q: float) -> float:
        if self.count <= 0:
            return 0.0
        q = min(1.0, max(0.0, q))
        target = q * self.count
        seen = self.zeros
        if seen >= target and self.zeros > 0:
            return 0.0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen >= target:
                # bucket midpoint in log space: gamma**idx is the
                # upper edge, divide by (1+alpha)-ish for the center
                return (self.gamma ** idx) * 2.0 / (1.0 + self.gamma)
        top = max(self.buckets) if self.buckets else 0
        return self.gamma ** top

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def scale(self, factor: float, floor: float = 1e-3) -> None:
        if factor >= 1.0:
            return
        self.count *= factor
        self.sum *= factor
        self.zeros *= factor
        dead = []
        for idx in self.buckets:
            self.buckets[idx] *= factor
            if self.buckets[idx] < floor:
                dead.append(idx)
        for idx in dead:
            del self.buckets[idx]

    def merge(self, other: "LogQuantile") -> "LogQuantile":
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError("LogQuantile alpha mismatch")
        self.count += other.count
        self.sum += other.sum
        self.zeros += other.zeros
        for idx, w in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0.0) + w
        return self

    def to_dict(self) -> dict:
        return {"kind": "log_quantile", "alpha": self.alpha,
                "count": round(self.count, 6), "sum": round(self.sum, 6),
                "zeros": round(self.zeros, 6),
                "buckets": {str(i): round(w, 6)
                            for i, w in sorted(self.buckets.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "LogQuantile":
        lq = cls(float(d.get("alpha", 0.01) or 0.01))
        lq.count = float(d.get("count", 0.0) or 0.0)
        lq.sum = float(d.get("sum", 0.0) or 0.0)
        lq.zeros = float(d.get("zeros", 0.0) or 0.0)
        for idx, w in (d.get("buckets") or {}).items():
            lq.buckets[int(idx)] = float(w)
        return lq


_KINDS = {"space_saving": SpaceSaving, "hll": HyperLogLog,
          "log_quantile": LogQuantile}


def from_dict(d: Optional[dict]):
    """Polymorphic loader keyed on the wire form's ``kind`` tag."""
    if not d:
        return None
    cls = _KINDS.get(d.get("kind", ""))
    return cls.from_dict(d) if cls else None
