"""Dashboard / SLO-rule lint: every observability artefact must
reference metric families the registry actually exports.

``weed.py lint-dashboards`` (and the perf_smoke test that wraps it)
runs two checks:

* every ``SeaweedFS_*`` token in every Grafana panel query resolves to
  a registered family (histogram ``_bucket``/``_sum``/``_count``
  components resolve to their base family);
* every active SLO rule (stats/slo.py) references a registered family,
  and a latency rule's family is really a histogram — a typo in
  ``WEED_SLO_RULES`` would otherwise silently evaluate to "no traffic,
  no burn" forever.

Returns problem strings instead of raising, so the CLI can print them
all and exit non-zero once.
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

from . import metrics as _stats
from . import slo as slo_mod


def default_dashboard_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "grafana", "grafana_seaweedfs_tpu.json")


# Rows the shipped dashboard must keep, with family tokens each row's
# panels must query — deleting a row (or renaming a family out from
# under it) fails the lint, not just a human eyeball pass.  Applied
# only to the repo's own dashboard; ad-hoc dashboards passed by path
# are checked for dangling references only.
PINNED_ROWS = {
    "Workload analytics": (
        "SeaweedFS_access_records_total",
        "SeaweedFS_access_tracked_keys",
        "SeaweedFS_access_sketch_bytes",
        "SeaweedFS_usage_reads",
        "SeaweedFS_usage_bytes",
        "SeaweedFS_usage_distinct_keys",
        "SeaweedFS_usage_hot_share",
    ),
}


def lint_dashboard(path: Optional[str] = None) -> List[str]:
    pin = path is None or \
        os.path.abspath(path) == default_dashboard_path()
    path = path or default_dashboard_path()
    problems: List[str] = []
    try:
        with open(path) as f:
            dashboard = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable dashboard: {e}"]
    panels = dashboard.get("panels", [])
    exprs = [(p.get("title", "?"), t.get("expr", ""))
             for p in panels for t in p.get("targets", [])]
    if not exprs:
        return [f"{path}: dashboard has no queries"]
    registered = set(_stats.REGISTRY._metrics)
    for title, expr in exprs:
        for token in re.findall(r"SeaweedFS_\w+", expr):
            base = re.sub(r"_(bucket|sum|count)$", "", token)
            if base not in registered and token not in registered:
                problems.append(
                    f"panel {title!r} references unknown metric {token}")
    if pin:
        titles = {p.get("title") for p in panels
                  if p.get("type") == "row"}
        joined = "\n".join(e for _, e in exprs)
        for row, families in PINNED_ROWS.items():
            if row not in titles:
                problems.append(f"pinned row {row!r} missing")
            for fam in families:
                if fam not in joined:
                    problems.append(
                        f"no panel queries pinned family {fam}")
    return problems


def lint_slo_rules(rules=None) -> List[str]:
    problems: List[str] = []
    rules = rules if rules is not None else slo_mod.active_rules()
    if not rules:
        return ["no SLO rules active (WEED_SLO_RULES parsed to nothing)"]
    registered = _stats.REGISTRY._metrics
    for rule in rules:
        fam = rule.family
        if rule.kind == "availability":
            # the liveness pseudo-family is fed by the scrape loop and
            # also registered as a real gauge on the leader
            if fam not in registered:
                problems.append(
                    f"rule {rule.name!r}: unknown family {fam}")
            continue
        metric = registered.get(fam)
        if metric is None:
            problems.append(f"rule {rule.name!r}: unknown family {fam}")
        elif getattr(metric, "kind", "") != "histogram":
            problems.append(
                f"rule {rule.name!r}: latency rule needs a histogram, "
                f"{fam} is a {getattr(metric, 'kind', '?')}")
    return problems


def run(path: Optional[str] = None) -> List[str]:
    """Full lint pass; empty list means clean."""
    return lint_dashboard(path) + lint_slo_rules()
