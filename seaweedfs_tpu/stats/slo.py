"""SLO engine: availability + per-QoS-class p99-latency burn rates.

Runs on the master leader against the health plane's ring TSDB
(stats/tsdb.py).  Each rule defines a service-level objective; the
engine computes the fraction of "bad" events over a fast (5 min) and a
slow (1 h) window, converts them to error-budget **burn rates**
(bad_fraction / allowed_fraction — the Google SRE formulation), and
fires an alert only when BOTH windows burn hot (multi-window
multi-burn-rate: the fast window gives reaction speed, the slow window
suppresses blips).  Alerts clear once the fast window drops back under
a burn of 1.0.

Rule kinds:

* ``availability`` — over the scrape loop's liveness series
  (``SeaweedFS_cluster_target_up``): bad fraction is the time-averaged
  share of down targets in the window.
* ``latency`` — over any request histogram: bad fraction is the share
  of requests slower than the rule's threshold (``le`` seconds), from
  windowed le-bucket deltas.  The defaults watch the per-QoS-class
  queue-wait histogram, one rule per class.

Rules come from ``WEED_SLO_RULES`` (fs.configure-style compact spec:
rules split on ``;``, fields on ``,``, first bare field is the name,
e.g. ``p99-get,kind=latency,family=SeaweedFS_volumeServer_request_seconds,match.type=get,le=0.1,objective=0.99``)
or fall back to the built-in defaults below.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

from . import metrics as _stats
from . import events as _events
from . import tsdb as _tsdb

LIVENESS_FAMILY = "SeaweedFS_cluster_target_up"
DEFAULT_LATENCY_FAMILY = "SeaweedFS_qos_queue_wait_seconds"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def fast_window() -> float:
    return max(1.0, _env_float("WEED_SLO_FAST_S", 300.0))


def slow_window() -> float:
    return max(1.0, _env_float("WEED_SLO_SLOW_S", 3600.0))


class Rule:
    __slots__ = ("name", "kind", "family", "match", "objective", "le",
                 "burn_fast", "burn_slow")

    def __init__(self, name: str, kind: str, family: str,
                 match: Optional[Dict[str, str]] = None,
                 objective: float = 0.999, le: float = 0.1,
                 burn_fast: Optional[float] = None,
                 burn_slow: Optional[float] = None):
        self.name = name
        self.kind = kind  # availability | latency
        self.family = family
        self.match = dict(match or {})
        self.objective = min(max(objective, 0.0), 0.999999)
        self.le = le
        self.burn_fast = burn_fast
        self.burn_slow = burn_slow

    @property
    def budget(self) -> float:
        return max(1e-6, 1.0 - self.objective)

    def thresholds(self) -> tuple:
        bf = self.burn_fast if self.burn_fast is not None \
            else _env_float("WEED_SLO_BURN_FAST", 14.4)
        bs = self.burn_slow if self.burn_slow is not None \
            else _env_float("WEED_SLO_BURN_SLOW", 6.0)
        return bf, bs

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "family": self.family, "match": self.match,
                "objective": self.objective,
                "le": self.le if self.kind == "latency" else None}


def parse_rules(spec: str) -> List[Rule]:
    """Compact rule spec -> rules; malformed entries are skipped (a bad
    knob must never take the health plane down)."""
    rules: List[Rule] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, kind, family = "", "availability", LIVENESS_FAMILY
        match: Dict[str, str] = {}
        kw: Dict[str, float] = {}
        ok = True
        for field in part.split(","):
            field = field.strip()
            if not field:
                continue
            if "=" not in field:
                name = field
                continue
            k, _, v = field.partition("=")
            k, v = k.strip(), v.strip()
            if k == "kind":
                kind = v
            elif k == "family":
                family = v
            elif k.startswith("match."):
                match[k[len("match."):]] = v
            elif k == "name":
                name = v
            elif k in ("objective", "le", "burn_fast", "burn_slow"):
                try:
                    kw[k] = float(v)
                except ValueError:
                    ok = False
            else:
                ok = False
        if not name or kind not in ("availability", "latency") or not ok:
            continue
        rules.append(Rule(name, kind, family, match=match, **kw))
    return rules


def default_rules() -> List[Rule]:
    avail_obj = _env_float("WEED_SLO_AVAILABILITY", 0.999)
    inter_s = _env_float("WEED_SLO_INTERACTIVE_MS", 100.0) / 1000.0
    std_s = _env_float("WEED_SLO_STANDARD_MS", 500.0) / 1000.0
    return [
        Rule("availability", "availability", LIVENESS_FAMILY,
             objective=avail_obj),
        Rule("p99-interactive", "latency", DEFAULT_LATENCY_FAMILY,
             match={"class": "interactive"}, objective=0.99, le=inter_s),
        Rule("p99-standard", "latency", DEFAULT_LATENCY_FAMILY,
             match={"class": "standard"}, objective=0.99, le=std_s),
    ]


def active_rules() -> List[Rule]:
    spec = os.environ.get("WEED_SLO_RULES", "")
    return parse_rules(spec) if spec.strip() else default_rules()


class SloEngine:
    """Evaluates the active rules against a Tsdb.  Pure apart from the
    registry gauges and journal events it feeds — ``now`` is injectable
    so the multi-window evaluator unit-tests under a fake clock."""

    def __init__(self, tsdb: "_tsdb.Tsdb",
                 rules: Optional[List[Rule]] = None,
                 now: Callable[[], float] = time.time,
                 on_transition: Optional[Callable] = None,
                 journal: Optional["_events.EventJournal"] = None):
        self.tsdb = tsdb
        self._rules = rules
        self.now = now  # fake-clock seam
        self.on_transition = on_transition  # fn(rule, alert, firing)
        self.journal = journal or _events.JOURNAL
        self.state: Dict[str, dict] = {}  # name -> {firing, since}

    def rules(self) -> List[Rule]:
        return self._rules if self._rules is not None else active_rules()

    # -- per-rule SLI --------------------------------------------------------
    def _bad_fraction(self, rule: Rule, seconds: float):
        """(bad_fraction, detail) over the window."""
        if rule.kind == "availability":
            up = self.tsdb.avg(rule.family, seconds, rule.match)
            if up is None:
                return 0.0, {}
            down = sorted(
                dict(items).get("target", "?")
                for items, v in self.tsdb.latest(rule.family,
                                                 rule.match).items()
                if v < 1.0)
            return max(0.0, 1.0 - up), {"down": down}
        buckets, count = self.tsdb.histogram_window(rule.family, seconds,
                                                    rule.match)
        if count <= 0:
            return 0.0, {"requests": 0}
        good = 0.0
        for le, c in buckets:
            if le >= rule.le - 1e-12:
                good = c
                break
        else:
            good = count
        p99 = _tsdb.quantile(buckets, count, 0.99)
        return (max(0.0, 1.0 - good / count),
                {"requests": int(count),
                 "p99_ms": round(p99 * 1000, 2) if p99 is not None
                 else None})

    def evaluate(self) -> dict:
        """One evaluator pass: burn rates per window, transition logic,
        gauges, events.  Returns the full SLO status rollup."""
        out: Dict[str, dict] = {}
        fast_s, slow_s = fast_window(), slow_window()
        for rule in self.rules():
            bad_fast, detail = self._bad_fraction(rule, fast_s)
            bad_slow, _ = self._bad_fraction(rule, slow_s)
            burn_fast = bad_fast / rule.budget
            burn_slow = bad_slow / rule.budget
            _stats.ClusterSloBurnRateGauge.labels(rule.name, "fast").set(
                round(burn_fast, 4))
            _stats.ClusterSloBurnRateGauge.labels(rule.name, "slow").set(
                round(burn_slow, 4))
            st = self.state.setdefault(rule.name,
                                       {"firing": False, "since": 0.0})
            bf_thr, bs_thr = rule.thresholds()
            alert = {"rule": rule.name, "kind": rule.kind,
                     "objective": rule.objective,
                     "burn_fast": round(burn_fast, 4),
                     "burn_slow": round(burn_slow, 4),
                     "thresholds": {"fast": bf_thr, "slow": bs_thr},
                     "detail": detail}
            if not st["firing"] and burn_fast >= bf_thr \
                    and burn_slow >= bs_thr:
                st["firing"], st["since"] = True, self.now()
                self._transition(rule, alert, True)
            elif st["firing"] and burn_fast < 1.0:
                st["firing"] = False
                self._transition(rule, alert, False)
            alert["firing"] = st["firing"]
            alert["since"] = round(st["since"], 3) if st["firing"] else None
            _stats.ClusterSloAlertGauge.labels(rule.name).set(
                1.0 if st["firing"] else 0.0)
            out[rule.name] = alert
        return out

    def _transition(self, rule: Rule, alert: dict, firing: bool):
        to = "fire" if firing else "clear"
        _stats.ClusterSloTransitionsCounter.labels(rule.name, to).inc()
        self.journal.emit(
            _events.ALERT_FIRE if firing else _events.ALERT_CLEAR,
            service="master", node=rule.name,
            detail={"kind": rule.kind,
                    "burn_fast": alert["burn_fast"],
                    "burn_slow": alert["burn_slow"],
                    "detail": alert["detail"]})
        if self.on_transition is not None:
            try:
                self.on_transition(rule, alert, firing)
            except Exception:
                pass  # a push hook must never kill the evaluator

    def firing(self) -> List[str]:
        return sorted(n for n, st in self.state.items() if st["firing"])
