"""Structured cluster event journal.

Every daemon appends noteworthy transitions — leader elections,
scale.up/drain, curator job transitions, fault-injection activations,
prefork worker respawns, read-only demotions — to a process-global
bounded ring.  Each event carries the active trace id when one is
live, so an operator can pivot from "what happened" straight into
``/debug/traces``.

The ring is queryable at ``GET /cluster/events?since=<seq>`` and
streamable (``follow=<seconds>``) over the existing chunked-HTTP
machinery.  The master leader's scrape loop pulls remote daemons'
journals with a per-origin cursor and merges them, so the leader's
journal is the cluster view; every journal carries a random ``origin``
token so a merge never re-ingests its own events (all-in-one processes
share this module's global JOURNAL).

Knob: ``WEED_EVENTS_MAX`` — ring capacity per process (default 2048).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

from . import metrics as _stats

# event kinds emitted around the tree (free-form, these are the core set)
LEADER_ELECTED = "raft.leader"
LEADER_STEPDOWN = "raft.stepdown"
MEMBERSHIP = "raft.membership"
NODE_DOWN = "node.down"
NODE_UP = "node.up"
SCRAPE_ERROR = "scrape.error"
ALERT_FIRE = "alert.fire"
ALERT_CLEAR = "alert.clear"
JOB_ENQUEUED = "job.enqueued"
JOB_DONE = "job.done"
SCALE_UP = "scale.up"
SCALE_DRAIN = "scale.drain"
SHARD_SPLIT = "filer.shard_split"
SHARD_MERGE = "filer.shard_merge"
DRAIN = "vs.drain"
READONLY_DEMOTION = "vs.readonly"
WORKER_RESPAWN = "worker.respawn"
FAULTS_ACTIVE = "faults.active"
HOT_KEY = "access.hotkey"
TIER_MOVE = "tier.move"


def _cap() -> int:
    try:
        return max(16, int(os.environ.get("WEED_EVENTS_MAX", "") or 2048))
    except ValueError:
        return 2048


class EventJournal:
    def __init__(self, now: Callable[[], float] = time.time):
        self.token = uuid.uuid4().hex[:12]
        self.now = now  # fake-clock seam
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.events: deque = deque()
        self.seq = 0

    def emit(self, kind: str, service: str = "", node: str = "",
             detail: Optional[dict] = None,
             trace_id: Optional[str] = None,
             origin: Optional[str] = None,
             origin_seq: Optional[int] = None) -> dict:
        if trace_id is None:
            from .. import tracing

            span = tracing.current()
            trace_id = span.trace_id if span is not None else ""
        with self.cond:
            self.seq += 1
            ev = {"seq": self.seq, "ts": round(self.now(), 3),
                  "kind": kind, "service": service, "node": node,
                  "detail": detail or {}, "trace": trace_id or "",
                  "origin": origin or self.token,
                  "origin_seq": origin_seq if origin_seq is not None
                  else self.seq}
            self.events.append(ev)
            cap = _cap()
            while len(self.events) > cap:
                self.events.popleft()
            self.cond.notify_all()
        _stats.ClusterEventsCounter.labels(kind).inc()
        return ev

    def since(self, seq: int = 0, limit: int = 0) -> List[dict]:
        with self.lock:
            out = [e for e in self.events if e["seq"] > seq]
        return out[-limit:] if limit else out

    def wait(self, seq: int, timeout: float) -> List[dict]:
        """Block until an event newer than ``seq`` lands (or timeout);
        the chunked streaming handler's long-poll primitive."""
        deadline = time.time() + timeout
        with self.cond:
            while self.seq <= seq:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return []
                self.cond.wait(min(remaining, 0.5))
            return [e for e in self.events if e["seq"] > seq]

    def merge(self, events: List[dict]) -> int:
        """Fold a remote journal's events in (preserving their origin
        token + seq so cursors stay exact); returns how many landed.
        Events whose origin is this journal are skipped — in-process
        daemons all share the global JOURNAL and would echo forever."""
        n = 0
        cursors = self._origin_cursors()
        for e in events:
            origin = e.get("origin") or ""
            if not origin or origin == self.token:
                continue
            if e.get("origin_seq", 0) <= cursors.get(origin, 0):
                continue
            self.emit(e.get("kind", "event"), service=e.get("service", ""),
                      node=e.get("node", ""), detail=e.get("detail"),
                      trace_id=e.get("trace", ""), origin=origin,
                      origin_seq=e.get("origin_seq"))
            cursors[origin] = e.get("origin_seq", 0)
            n += 1
        return n

    def _origin_cursors(self) -> Dict[str, int]:
        with self.lock:
            out: Dict[str, int] = {}
            for e in self.events:
                o = e.get("origin", "")
                if e.get("origin_seq", 0) > out.get(o, 0):
                    out[o] = e["origin_seq"]
            return out

    def cursor_for(self, origin: str) -> int:
        return self._origin_cursors().get(origin, 0)


JOURNAL = EventJournal()


def emit(kind: str, service: str = "", node: str = "",
         detail: Optional[dict] = None, **kw) -> dict:
    """Module-level convenience: append to the process journal."""
    return JOURNAL.emit(kind, service=service, node=node, detail=detail,
                        **kw)


def events_handler(req, journal: Optional[EventJournal] = None):
    """``GET /cluster/events?since=N[&limit=M][&follow=seconds]``.

    Plain mode returns a JSON snapshot; ``follow`` streams newline-
    delimited JSON events over chunked transfer-encoding until the
    window elapses (Response iterator bodies already stream)."""
    from ..rpc.http_rpc import Response

    j = journal or JOURNAL
    try:
        since = int(req.param("since", 0) or 0)
        limit = int(req.param("limit", 0) or 0)
        follow = float(req.param("follow", 0) or 0)
    except (TypeError, ValueError):
        return Response(b'{"error": "bad cursor"}', status=400,
                        content_type="application/json")
    if follow <= 0:
        return {"journal": j.token, "seq": j.seq,
                "events": j.since(since, limit)}

    def stream():
        cursor = since
        deadline = time.time() + min(follow, 300.0)
        # first line identifies the journal so pollers learn the token
        yield (json.dumps({"journal": j.token, "seq": j.seq})
               + "\n").encode()
        while time.time() < deadline:
            fresh = j.wait(cursor, min(1.0, deadline - time.time()))
            for e in fresh:
                cursor = max(cursor, e["seq"])
                yield (json.dumps(e) + "\n").encode()

    return Response(stream(), content_type="application/x-ndjson")


def mount(server, journal: Optional[EventJournal] = None):
    """Register GET /cluster/events on an RpcServer (the faults.mount /
    qos.mount pattern) — every daemon serves its local journal; the
    master leader additionally serves the merged cluster view."""
    server.add("GET", "/cluster/events",
               lambda req: events_handler(req, journal))
