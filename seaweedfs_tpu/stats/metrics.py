"""Prometheus-style metrics registry with text exposition.

The reference registers ~25 metric vectors (counters, gauges, histograms)
covering master/filer/volume/s3 request counts, sizes and latencies
(/root/reference/weed/stats/metrics.go:31-196) and serves them on a
metrics port or pushes to a gateway.  This is a dependency-free registry
producing the same text exposition format, served by ``metrics_handler``
mounted at /metrics on every daemon.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

_DEFAULT_BUCKETS = (
    .0001, .0003, .001, .003, .01, .03, .1, .3, 1, 3, 10, 30, 100)


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
        for n, v in zip(names, values))
    return "{%s}" % inner


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def labels(self, *values) -> "_CounterChild":
        return _CounterChild(self, tuple(str(v) for v in values))

    def inc(self, amount: float = 1.0, labels: tuple = ()):
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def set_cumulative(self, value: float, labels: tuple = ()):
        """Adopt an externally-maintained cumulative count (e.g. the
        C++ engine's off-GIL counters) while keeping counter semantics:
        the stored value never goes backwards, so rate()/increase()
        stay correct."""
        with self._lock:
            if value >= self._values.get(labels, 0.0):
                self._values[labels] = float(value)

    def expose(self) -> list[str]:
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s counter" % self.name]
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items or [((), 0.0)] if not self.label_names else items:
            lines.append("%s%s %s" % (
                self.name, _fmt_labels(self.label_names, labels),
                _fmt_value(v)))
        return lines


class _CounterChild:
    __slots__ = ("_parent", "_labels")

    def __init__(self, parent, labels):
        self._parent, self._labels = parent, labels

    def inc(self, amount: float = 1.0):
        self._parent.inc(amount, self._labels)

    def set_cumulative(self, value: float):
        self._parent.set_cumulative(value, self._labels)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_="", label_names=(), fn=None):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}
        self._fn = fn  # callable -> float, for self-sampling gauges

    def labels(self, *values) -> "_GaugeChild":
        return _GaugeChild(self, tuple(str(v) for v in values))

    def set(self, value: float, labels: tuple = ()):
        with self._lock:
            self._values[labels] = float(value)

    def add(self, amount: float, labels: tuple = ()):
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def remove(self, *values):
        """Drop every label series whose leading label values match —
        a departed scrape target must not export a stale series
        forever (and get re-ingested as a live signal)."""
        prefix = tuple(str(v) for v in values)
        with self._lock:
            for k in [k for k in self._values
                      if k[:len(prefix)] == prefix]:
                del self._values[k]

    def expose(self) -> list[str]:
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s gauge" % self.name]
        if self._fn is not None:
            lines.append("%s %s" % (self.name, _fmt_value(self._fn())))
            return lines
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for labels, v in items:
            lines.append("%s%s %s" % (
                self.name, _fmt_labels(self.label_names, labels),
                _fmt_value(v)))
        return lines


class _GaugeChild:
    __slots__ = ("_parent", "_labels")

    def __init__(self, parent, labels):
        self._parent, self._labels = parent, labels

    def set(self, value: float):
        self._parent.set(value, self._labels)

    def add(self, amount: float):
        self._parent.add(amount, self._labels)

    def inc(self, amount: float = 1.0):
        self._parent.add(amount, self._labels)

    def dec(self, amount: float = 1.0):
        self._parent.add(-amount, self._labels)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_="", label_names=(),
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def labels(self, *values) -> "_HistogramChild":
        return _HistogramChild(self, tuple(str(v) for v in values))

    def observe(self, value: float, labels: tuple = ()):
        with self._lock:
            counts = self._counts.setdefault(
                labels, [0] * (len(self.buckets) + 1))
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def time(self, labels: tuple = ()):
        return _Timer(self, labels)

    def expose(self) -> list[str]:
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s histogram" % self.name]
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
        for labels, counts in items:
            cumulative = 0
            for b, c in zip(self.buckets, counts):
                cumulative += c
                lines.append('%s_bucket%s %d' % (
                    self.name,
                    _fmt_labels(self.label_names + ("le",),
                                labels + (_fmt_value(b),)),
                    cumulative))
            cumulative += counts[-1]
            lines.append('%s_bucket%s %d' % (
                self.name,
                _fmt_labels(self.label_names + ("le",), labels + ("+Inf",)),
                cumulative))
            lines.append("%s_sum%s %s" % (
                self.name, _fmt_labels(self.label_names, labels),
                _fmt_value(sums[labels])))
            lines.append("%s_count%s %d" % (
                self.name, _fmt_labels(self.label_names, labels), cumulative))
        return lines


class _HistogramChild:
    __slots__ = ("_parent", "_labels")

    def __init__(self, parent, labels):
        self._parent, self._labels = parent, labels

    def observe(self, value: float):
        self._parent.observe(value, self._labels)

    def time(self):
        return _Timer(self._parent, self._labels)


class _Timer:
    def __init__(self, hist, labels):
        self._hist, self._labels = hist, labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, self._labels)
        return False


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self.register(Counter(name, help_, label_names))

    def gauge(self, name, help_="", label_names=(), fn=None) -> Gauge:
        return self.register(Gauge(name, help_, label_names, fn=fn))

    def histogram(self, name, help_="", label_names=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, label_names, buckets))

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# The standard vectors the reference registers (stats/metrics.go:31-196),
# shared by every daemon in-process.
MasterReceivedHeartbeatCounter = REGISTRY.counter(
    "SeaweedFS_master_received_heartbeats", "master received heartbeats",
    ("type",))
MasterVolumeLayoutWritable = REGISTRY.gauge(
    "SeaweedFS_master_volume_layout_writable",
    "writable volumes per layout", ("collection", "rp", "ttl"))
MasterPickForWriteErrorCounter = REGISTRY.counter(
    "SeaweedFS_master_pick_for_write_error", "pick-for-write errors")
VolumeServerRequestCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_request_total", "volume server requests",
    ("type",))
VolumeServerRequestHistogram = REGISTRY.histogram(
    "SeaweedFS_volumeServer_request_seconds", "volume server request latency",
    ("type",))
# requests served entirely by the native engine (off-GIL; adopted from
# the C++ cumulative counters right before each exposition — a counter,
# so Prometheus rate()/increase() type-check)
VolumeServerNativeRequestCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_native_request_total",
    "native fast-path requests", ("type",))
VolumeServerVolumeCounter = REGISTRY.gauge(
    "SeaweedFS_volumeServer_volumes", "volumes managed", ("collection", "type"))
VolumeServerReadOnlyVolumeGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_read_only_volumes", "read-only volumes")
VolumeServerProxiedReadCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_proxied_read_total",
    "non-local reads served per readMode outcome", ("mode",))
VolumeServerThrottleRejects = REGISTRY.counter(
    "SeaweedFS_volumeServer_throttle_rejects_total",
    "requests rejected (429) by the in-flight byte throttles",
    ("direction",))
VolumeFsyncBatchCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_fsync_batches_total",
    "group-commit fsync batches flushed")
EcEncodeBytesCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_encode_bytes_total",
    "volume bytes pushed through the batched EC encode pipeline")
EcEncodeStageSeconds = REGISTRY.gauge(
    "SeaweedFS_volumeServer_ec_encode_stage_seconds",
    "busy seconds per host EC encode stage, last encode run", ("stage",))
EcWritebackFlushCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_writeback_flushes_total",
    "sync_file_range writeback-pacing windows flushed by EC writers")
EcRecoverStageSeconds = REGISTRY.gauge(
    "SeaweedFS_volumeServer_ec_recover_stage_seconds",
    "cumulative busy seconds per degraded-read stage", ("stage",))
EcRecoverCacheCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_recover_cache_total",
    "recovered-block cache lookups by outcome "
    "(hit / miss / coalesced)", ("result",))
EcRecoverSpanCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_recover_spans_total",
    "spans reconstructed on the degraded-read path, by decode mode",
    ("mode",))
EcRecoverBytesCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_recover_bytes_total",
    "survivor bytes pushed through degraded-read decodes")
# inline write-path EC (storage/erasure_coding/inline.py): needles
# stream straight into striped shard logs, parity commits per stripe
EcInlineStripesCommitted = REGISTRY.counter(
    "SeaweedFS_ec_inline_stripes_committed_total",
    "stripe commit records appended by inline EC writers "
    "(full = a complete k-block row, tail = a zero-padded partial row)",
    ("kind",))
EcInlineTailBytes = REGISTRY.gauge(
    "SeaweedFS_ec_inline_tail_bytes",
    "bytes buffered in the partially-filled tail stripe, last writer")
EcInlineWriteAmp = REGISTRY.gauge(
    "SeaweedFS_ec_inline_write_amp",
    "physical bytes written / logical bytes ingested, last inline "
    "EC commit (the (k+p)/k floor is 1.4 for RS(10,4))")
EcInlineBytesCounter = REGISTRY.counter(
    "SeaweedFS_ec_inline_bytes_total",
    "inline EC writer traffic: logical = needle stream bytes acked, "
    "physical = extra parity + commit-record bytes", ("kind",))
EcInlineCommitSeconds = REGISTRY.histogram(
    "SeaweedFS_ec_inline_stripe_commit_seconds",
    "stripe commit latency: QoS background-lane wait + parity encode "
    "+ shard-log and commit-record writes")
# device pipeline: the HBM slab pool behind the batched EC dispatch
# path (ops/device_pool.py) and the host<->device transfer volume of
# the encode/rebuild/recover device paths
DevicePoolSlotsGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_device_pool_slots",
    "EC device-pool slabs by state (free / leased / resident)",
    ("state",))
DevicePoolBytesGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_device_pool_bytes",
    "total bytes retained or leased by the EC device slab pool")
DevicePoolEvictionsCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_device_pool_evictions_total",
    "idle EC device-pool slabs evicted by the WEED_EC_DEVICE_POOL_MB cap")
EcDeviceH2dBytesCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_device_h2d_bytes_total",
    "bytes staged host->device by the EC device dispatch paths, by "
    "target device (\"host\" = host staging, \"sharded:N\" = an N-way "
    "sharded mesh transfer)", ("device",))
EcDeviceD2hBytesCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_ec_device_d2h_bytes_total",
    "bytes fetched device->host by the EC device dispatch paths, by "
    "source device", ("device",))
DevicePoolDeviceBytesGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_device_pool_device_bytes",
    "EC device-pool slab bytes by placement (per-device free-lists "
    "never cross devices)", ("device",))
FilerChunkCacheCounter = REGISTRY.counter(
    "SeaweedFS_filer_chunk_cache_total",
    "filer chunk cache lookups", ("result",))
# unified HBM -> host RAM -> disk read-through cache (cache/ package)
ReadCacheRequestsCounter = REGISTRY.counter(
    "SeaweedFS_read_cache_requests_total",
    "unified read cache lookups by serving tier "
    "(hbm / ram / disk / miss)", ("tier",))
ReadCacheFillCounter = REGISTRY.counter(
    "SeaweedFS_read_cache_fill_total",
    "read cache fill admissions (admitted / qos_bypass — background "
    "traffic bypasses the fill path unless WEED_READ_CACHE_BG_FILL=1)",
    ("outcome",))
ReadCacheResidentBytesGauge = REGISTRY.gauge(
    "SeaweedFS_read_cache_resident_bytes",
    "bytes resident in the unified read cache, by tier", ("tier",))
ReadCacheInvalidationsCounter = REGISTRY.counter(
    "SeaweedFS_read_cache_invalidations_total",
    "read cache entries dropped by cause "
    "(delete / overwrite / vacuum / rebuild / stale)", ("reason",))
ChunkCacheOversizeDropsCounter = REGISTRY.counter(
    "SeaweedFS_chunk_cache_oversize_drops_total",
    "chunks too large for every segment of a disk cache layer, "
    "dropped at admission (historically a silent drop)")
# gateway fast-path vectors: fid leasing on the write path, streamed
# chunk prefetch on the read path, and the signature caches that keep
# per-request crypto off the hot path
FilerFidLeaseCounter = REGISTRY.counter(
    "SeaweedFS_filer_fid_lease_total",
    "fid lease cache outcomes on the filer assign path "
    "(hit / miss / refill / expired / invalidated / stale_retry)",
    ("event",))
FilerPrefetchWindowGauge = REGISTRY.gauge(
    "SeaweedFS_filer_read_prefetch_window",
    "chunk fetches in flight ahead of the streaming GET cursor")
FilerStreamedReadCounter = REGISTRY.counter(
    "SeaweedFS_filer_read_reply_total",
    "filer GET replies by delivery mode (streamed / buffered)",
    ("mode",))
JwtCacheCounter = REGISTRY.counter(
    "SeaweedFS_security_jwt_cache_total",
    "JWT signature-verification cache lookups (hit / miss)",
    ("result",))
S3SigV4KeyCacheCounter = REGISTRY.counter(
    "SeaweedFS_s3_sigv4_key_cache_total",
    "SigV4 derived signing-key cache lookups (hit / miss)",
    ("result",))
FilerRequestCounter = REGISTRY.counter(
    "SeaweedFS_filer_request_total", "filer requests", ("type",))
FilerRequestHistogram = REGISTRY.histogram(
    "SeaweedFS_filer_request_seconds", "filer request latency", ("type",))
S3RequestCounter = REGISTRY.counter(
    "SeaweedFS_s3_request_total", "s3 requests", ("action", "code"))
S3RequestHistogram = REGISTRY.histogram(
    "SeaweedFS_s3_request_seconds", "s3 request latency", ("action",))
# cross-hop tracing vectors: observed SERVER-side in RpcServer dispatch
# (src from the caller's X-Trace-Src header, dst = the serving daemon,
# route = the matched route prefix — bounded label sets, no addresses)
RpcHopHistogram = REGISTRY.histogram(
    "SeaweedFS_rpc_hop_seconds",
    "cross-daemon request hop latency by source/destination/route",
    ("src", "dst", "route"))
RpcInflightGauge = REGISTRY.gauge(
    "SeaweedFS_rpc_inflight_requests",
    "requests currently inside a daemon's dispatch", ("service",))
TraceRetentionCounter = REGISTRY.counter(
    "SeaweedFS_trace_traces_total",
    "root-span trace retention decisions (kept / dropped)", ("result",))
# fault-tolerance layer vectors: retries/hedges observed CLIENT-side in
# rpc/policy.py, breaker state per destination, injected faults from
# util/faults.py, and master-side dead-node reaps
RpcRetryCounter = REGISTRY.counter(
    "SeaweedFS_rpc_retries_total",
    "outbound retry decisions by route and reason "
    "(retry / budget_dry / deadline)", ("route", "reason"))
RpcHedgeCounter = REGISTRY.counter(
    "SeaweedFS_rpc_hedges_total",
    "hedged idempotent reads by route (fired / win)",
    ("route", "outcome"))
BreakerStateGauge = REGISTRY.gauge(
    "SeaweedFS_breaker_state",
    "per-destination circuit breaker state "
    "(0=closed 1=open 2=half-open)", ("dst",))
FaultsInjectedCounter = REGISTRY.counter(
    "SeaweedFS_faults_injected_total",
    "faults fired by the deterministic injection registry",
    ("kind", "rule"))
TopologyDeadNodesCounter = REGISTRY.counter(
    "SeaweedFS_topology_dead_nodes_total",
    "volume servers reaped by the master after missed heartbeats")
VolumeReadonlyDemotions = REGISTRY.counter(
    "SeaweedFS_volume_readonly_demotions_total",
    "volumes auto-demoted to read-only after disk write failures")


# -- continuous profiling (profiling.py): the always-on folded-stack
# sampler's self-measured duty cycle and per-route sample counts, plus
# the device-side kernel telemetry fed by the EC dispatch pipeline
def _profiler_overhead() -> float:
    from .. import profiling

    return profiling.overhead_ratio()


def _profiler_stacks() -> float:
    from .. import profiling

    return profiling.stack_count()


ProfilerOverheadGauge = REGISTRY.gauge(
    "SeaweedFS_profiler_overhead_ratio",
    "fraction of wall time the always-on stack sampler spends sampling",
    fn=_profiler_overhead)
ProfilerStacksGauge = REGISTRY.gauge(
    "SeaweedFS_profiler_stacks",
    "distinct folded stacks interned by the always-on sampler",
    fn=_profiler_stacks)
ProfilerRouteSamplesCounter = REGISTRY.counter(
    "SeaweedFS_profiler_route_samples_total",
    "always-on profiler samples attributed to an active RPC route",
    ("route",))
EcKernelDispatchHistogram = REGISTRY.histogram(
    "SeaweedFS_volumeServer_ec_kernel_dispatch_ready_seconds",
    "host-observed dispatch->ready latency per EC device batch, by the "
    "device count the batch was sharded over", ("devices",))
EcKernelFlopsGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_ec_kernel_flops",
    "XLA cost-analysis flops per compiled EC parity geometry",
    ("geometry",))
EcKernelBytesGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_ec_kernel_bytes_accessed",
    "XLA cost-analysis bytes accessed per compiled EC parity geometry",
    ("geometry",))
DevicePoolHwmBytesGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_device_pool_hwm_bytes",
    "high-watermark of bytes held by the EC device slab pool")
DevicePoolHwmSecondsGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_device_pool_hwm_seconds",
    "seconds the EC device slab pool spent at >=95% of its watermark")
# maintenance curator (seaweedfs_tpu/maintenance): the leader's job
# queue, the workers' execution outcomes, and the byte pacer that
# keeps background scrubs out of the foreground's way
MaintQueueJobsGauge = REGISTRY.gauge(
    "SeaweedFS_master_maintenance_queue_jobs",
    "live maintenance jobs in the curator queue, by state",
    ("state",))
MaintJobsCounter = REGISTRY.counter(
    "SeaweedFS_master_maintenance_jobs_total",
    "maintenance jobs finished, by type and outcome",
    ("type", "outcome"))
MaintJobSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_volumeServer_maintenance_job_seconds",
    "maintenance job execution latency on the worker, by type",
    ("type",))
MaintScrubbedBytesCounter = REGISTRY.counter(
    "SeaweedFS_volumeServer_maintenance_scrubbed_bytes_total",
    "shard bytes streamed through deep scrub")
MaintPacerRateGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_maintenance_pacer_bytes_per_second",
    "effective maintenance byte rate after foreground-load backoff")
# repair-efficient coding tier (storage/erasure_coding/codes): rebuild
# traffic by code family — read_bytes counts survivor bytes CONSUMED by
# the rebuilder (post-projection for regenerating codes, i.e. what a
# distributed rebuild moves over the network)
MaintEcRebuildReadBytes = REGISTRY.counter(
    "SeaweedFS_volumeServer_maintenance_ec_rebuild_read_bytes_total",
    "survivor bytes consumed by EC rebuilds, by code family",
    ("family",))
MaintEcRebuildRebuiltBytes = REGISTRY.counter(
    "SeaweedFS_volumeServer_maintenance_ec_rebuild_rebuilt_bytes_total",
    "shard bytes written by EC rebuilds, by code family",
    ("family",))
MaintEcRebuildReadAmpGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_maintenance_ec_rebuild_read_amp",
    "bytes read per rebuilt byte across this process's EC rebuilds, "
    "by code family",
    ("family",))
# control-plane raft (seaweedfs_tpu/master/raft.py): one series per
# local raft node, labeled by its advertised address, so a 3-master
# deployment shows term agreement and replication lag at a glance
RaftTermGauge = REGISTRY.gauge(
    "SeaweedFS_raft_term",
    "current raft term on this master", ("node",))
RaftCommitIndexGauge = REGISTRY.gauge(
    "SeaweedFS_raft_commit_index",
    "highest quorum-committed raft log index on this master", ("node",))
RaftAppliedLagGauge = REGISTRY.gauge(
    "SeaweedFS_raft_applied_lag",
    "raft log entries appended but not yet applied to the FSM "
    "(last_index - applied_index)", ("node",))


# -- cluster QoS: tenant-aware admission, weighted-fair queues, and the
# foreground/background device lanes ----------------------------------------
QosRequestsCounter = REGISTRY.counter(
    "SeaweedFS_qos_requests_total",
    "front-end requests by QoS class and admission outcome",
    ("service", "class", "outcome"))
QosInflightGauge = REGISTRY.gauge(
    "SeaweedFS_qos_inflight",
    "admitted in-flight requests per QoS class",
    ("service", "class"))
QosQueueDepthGauge = REGISTRY.gauge(
    "SeaweedFS_qos_queue_depth",
    "requests parked in the weighted-fair queues per QoS class",
    ("service", "class"))
QosQueueWaitHistogram = REGISTRY.histogram(
    "SeaweedFS_qos_queue_wait_seconds",
    "time a request spent queued before dispatch or shed",
    ("class",))
QosTenantThrottledCounter = REGISTRY.counter(
    "SeaweedFS_qos_tenant_throttled_total",
    "requests denied by per-tenant token buckets",
    ("service", "class"))
QosQuotaRejectsCounter = REGISTRY.counter(
    "SeaweedFS_qos_quota_rejects_total",
    "assigns/uploads denied by per-collection quotas, by resource kind",
    ("kind",))
QosLaneActiveGauge = REGISTRY.gauge(
    "SeaweedFS_qos_lane_active",
    "device-lane work items currently active, by lane",
    ("lane",))
QosLaneBatchesCounter = REGISTRY.counter(
    "SeaweedFS_qos_lane_batches_total",
    "device batches dispatched, by lane",
    ("lane",))
QosLanePreemptionsCounter = REGISTRY.counter(
    "SeaweedFS_qos_lane_preemptions_total",
    "background device batches stalled behind foreground decodes")
QosLaneWaitSecondsCounter = REGISTRY.counter(
    "SeaweedFS_qos_lane_wait_seconds_total",
    "cumulative seconds background batches waited on the foreground lane")
QosSharedGateOccupancyGauge = REGISTRY.gauge(
    "SeaweedFS_qos_shared_gate_occupancy",
    "fleet-wide admission occupancy ((inflight+queued)/limit) read from "
    "the cross-worker shared-memory gate rows",
    ("service",))


# -- prefork gateway workers (rpc/prefork.py): worker-fleet health and
# the zero-copy writeback path ----------------------------------------------
GatewayWorkersGauge = REGISTRY.gauge(
    "SeaweedFS_gateway_workers",
    "configured prefork worker processes sharding this gateway's port",
    ("service",))
GatewayWorkerRespawnsCounter = REGISTRY.counter(
    "SeaweedFS_gateway_worker_respawns_total",
    "crashed gateway workers respawned by the prefork supervisor",
    ("service",))
GatewaySendfileBytesCounter = REGISTRY.counter(
    "SeaweedFS_gateway_sendfile_bytes_total",
    "response bytes spliced to client sockets with os.sendfile "
    "(zero-copy writeback), by service",
    ("service",))


# -- cluster elasticity: per-node load telemetry the autoscale
# detectors consume, and the scale events they emit -------------------------
ScaleNodeOccupancyGauge = REGISTRY.gauge(
    "SeaweedFS_master_scale_node_occupancy",
    "admission-gate occupancy ((inflight+queued)/limit) last "
    "heartbeated by each volume server", ("node",))
ScaleNodeRpsGauge = REGISTRY.gauge(
    "SeaweedFS_master_scale_node_rps",
    "object requests per second last heartbeated by each volume server",
    ("node",))
ScaleClusterSizeGauge = REGISTRY.gauge(
    "SeaweedFS_master_scale_cluster_volume_servers",
    "volume servers currently registered in the topology")
ScaleEventsCounter = REGISTRY.counter(
    "SeaweedFS_master_scale_events_total",
    "autoscale jobs enqueued by the curator, by action (up|drain)",
    ("action",))
VolumeServerDrainingGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_draining",
    "1 while this volume server is draining (read-only, being "
    "evacuated before deregistration)")


# -- cluster health plane (master/health.py): the leader-resident scrape
# loop, the ring TSDB it fills, the SLO burn-rate evaluator, and the
# structured event journal ---------------------------------------------------
ClusterTargetUpGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_target_up",
    "1 when the leader's last /metrics scrape of this daemon "
    "succeeded, 0 when it failed or timed out", ("target", "kind"))
ClusterScrapeErrorsCounter = REGISTRY.counter(
    "SeaweedFS_cluster_scrape_errors_total",
    "scrape attempts that failed or blew their per-target deadline",
    ("target",))
ClusterScrapeRoundsCounter = REGISTRY.counter(
    "SeaweedFS_cluster_scrape_rounds_total",
    "scrape rounds completed by the leader's health plane")
ClusterScrapeDutyGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_scrape_duty_ratio",
    "scrape-loop busy seconds per second of wall clock at the "
    "configured WEED_HEALTH_SCRAPE_MS cadence (self-measured)")
ClusterTsdbSeriesGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_tsdb_series",
    "live series held by the in-memory ring TSDB")
ClusterTsdbDroppedCounter = REGISTRY.counter(
    "SeaweedFS_cluster_tsdb_dropped_total",
    "samples dropped because the WEED_TSDB_MAX_SERIES cap was hit")
ClusterSloBurnRateGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_slo_burn_rate",
    "error-budget burn rate per SLO rule and window (1.0 = burning "
    "exactly the budget; >1 exhausts it early)", ("rule", "window"))
ClusterSloAlertGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_slo_alert_firing",
    "1 while this SLO rule's multi-window burn-rate alert is firing",
    ("rule",))
ClusterSloTransitionsCounter = REGISTRY.counter(
    "SeaweedFS_cluster_slo_alert_transitions_total",
    "alert state transitions per SLO rule (fire|clear)",
    ("rule", "to"))
ClusterEventsCounter = REGISTRY.counter(
    "SeaweedFS_cluster_events_total",
    "structured events appended to this process's journal, by kind",
    ("kind",))


# -- workload analytics plane (stats/access.py + stats/sketch.py): the
# per-daemon access recorder's own health, and the leader's assembled
# cluster usage view -----------------------------------------------------


def _access_tracked_keys() -> float:
    from . import access

    return float(access.tracked_keys_total())


def _access_sketch_bytes() -> float:
    from . import access

    return float(access.memory_bytes_total())


AccessRecordsCounter = REGISTRY.counter(
    "SeaweedFS_access_records_total",
    "data-path accesses fed to this daemon's access recorder, by op "
    "(read|write|delete|chunk)", ("op",))
AccessTrackedKeysGauge = REGISTRY.gauge(
    "SeaweedFS_access_tracked_keys",
    "fids currently tracked by the hot-key Space-Saving sketch "
    "(bounded by WEED_HEAT_MAX_KEYS)", fn=_access_tracked_keys)
AccessSketchBytesGauge = REGISTRY.gauge(
    "SeaweedFS_access_sketch_bytes",
    "approximate resident footprint of this daemon's access sketches",
    fn=_access_sketch_bytes)
UsageReadsGauge = REGISTRY.gauge(
    "SeaweedFS_usage_reads",
    "decay-weighted fleet read ops in the leader's merged usage view")
UsageWritesGauge = REGISTRY.gauge(
    "SeaweedFS_usage_writes",
    "decay-weighted fleet write ops in the leader's merged usage view")
UsageBytesGauge = REGISTRY.gauge(
    "SeaweedFS_usage_bytes",
    "decay-weighted fleet bytes moved in the merged usage view, by "
    "direction (read|write)", ("op",))
UsageDistinctKeysGauge = REGISTRY.gauge(
    "SeaweedFS_usage_distinct_keys",
    "HyperLogLog distinct-fid estimate across all reporting daemons")
UsageTenantsGauge = REGISTRY.gauge(
    "SeaweedFS_usage_tenants",
    "tenants present in the leader's merged usage view")
UsageCollectionsGauge = REGISTRY.gauge(
    "SeaweedFS_usage_collections",
    "collections present in the leader's merged usage view")
UsageHotShareGauge = REGISTRY.gauge(
    "SeaweedFS_usage_hot_share",
    "share of fleet reads hitting the single hottest fid (the "
    "access.hotkey journal event fires past WEED_HEAT_HOT_SHARE)")


# -- process self-metrics (the reference's Go runtime collectors:
# prometheus.NewGoCollector/NewProcessCollector) -----------------------------
_PROCESS_START = time.time()
try:
    import resource as _resource
except ImportError:  # non-POSIX fallback
    _resource = None


def _proc_rss_bytes() -> float:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os as _os

        return float(pages * _os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        if _resource is not None:
            # ru_maxrss is KiB on Linux (peak, not current — still
            # better than nothing where /proc is unavailable)
            return float(
                _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss * 1024)
        return 0.0


def _proc_open_fds() -> float:
    try:
        import os as _os

        return float(len(_os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


def _proc_gc_collections() -> float:
    import gc

    return float(sum(s.get("collections", 0) for s in gc.get_stats()))


ProcessResidentMemoryGauge = REGISTRY.gauge(
    "SeaweedFS_process_resident_memory_bytes",
    "resident set size of this process", fn=_proc_rss_bytes)
ProcessOpenFdsGauge = REGISTRY.gauge(
    "SeaweedFS_process_open_fds",
    "open file descriptors in this process", fn=_proc_open_fds)
ProcessThreadsGauge = REGISTRY.gauge(
    "SeaweedFS_process_threads",
    "live Python threads in this process",
    fn=lambda: float(threading.active_count()))
ProcessGcCollectionsGauge = REGISTRY.gauge(
    "SeaweedFS_process_gc_collections",
    "cumulative GC collections across generations",
    fn=_proc_gc_collections)
ProcessUptimeGauge = REGISTRY.gauge(
    "SeaweedFS_process_uptime_seconds",
    "seconds since this process registered its metrics",
    fn=lambda: time.time() - _PROCESS_START)
ProcessStartTimeGauge = REGISTRY.gauge(
    "SeaweedFS_process_start_time_seconds",
    "unix time the process registered its metrics",
    fn=lambda: _PROCESS_START)


def metrics_handler(req):
    """RpcServer route serving the registry in text exposition format."""
    from ..rpc.http_rpc import Response

    return Response(REGISTRY.expose().encode(),
                    content_type="text/plain; version=0.0.4")


def _label_sample(line: str, worker: str) -> str:
    """Inject worker="<id>" into one exposition sample line.  Split on
    the LAST space (label values may contain escaped spaces/braces, the
    value never does)."""
    sample, _, value = line.rpartition(" ")
    if not sample:
        return line
    if sample.endswith("}"):
        return f'{sample[:-1]},worker="{worker}"}} {value}'
    return f'{sample}{{worker="{worker}"}} {value}'


def merge_expositions(parts: "list[tuple[str, str]]") -> str:
    """Merge per-worker /metrics scrapes into one exposition: every
    sample gains a worker="<id>" label, and each family's # HELP/# TYPE
    header appears exactly once with ALL workers' samples grouped under
    it (prometheus parsers reject duplicate family blocks).  `parts` is
    [(worker_id, exposition_text), ...]; the prefork aggregation route
    (rpc/prefork.py) feeds it the local registry plus sideband scrapes."""
    meta: dict = {}          # family -> [help/type lines]
    samples: dict = {}       # family -> [labeled sample lines]
    order: list = []         # family first-seen order
    for worker, text in parts:
        family = ""
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                words = line.split(None, 3)
                if len(words) >= 3 and words[1] in ("HELP", "TYPE"):
                    family = words[2]
                    if family not in meta:
                        meta[family] = []
                        samples[family] = []
                        order.append(family)
                    if len(meta[family]) < 2:  # HELP + TYPE, once
                        meta[family].append(line)
                continue
            if family not in samples:  # headerless stray sample
                meta[family] = []
                samples[family] = []
                order.append(family)
            samples[family].append(_label_sample(line, worker))
    out = []
    for family in order:
        out.extend(meta[family])
        out.extend(samples[family])
    return "\n".join(out) + "\n"


def start_metrics_server(host: str = "127.0.0.1",
                         port: int = 0):
    """Dedicated metrics endpoint on its own port (the reference's
    -metricsPort; stats/metrics.go StartMetricsServer).  Daemons whose
    main port serves a user namespace (filer paths, s3 buckets) cannot
    mount /metrics there without shadowing user data."""
    from .. import profiling, qos, tracing
    from ..rpc.http_rpc import RpcServer
    from ..util import faults

    server = RpcServer(host, port, service_name="metrics")
    server.add("GET", "/metrics", metrics_handler)
    server.add("GET", "/debug/traces", tracing.traces_handler)
    faults.mount(server)
    profiling.mount(server)
    qos.mount(server)
    server.start()
    return server
