from .metrics import (Counter, Gauge, Histogram, Registry, REGISTRY,
                      metrics_handler)

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "metrics_handler"]
