"""CRC32C (Castagnoli) — the needle checksum.

The reference computes needle checksums with Go's hash/crc32 Castagnoli
table and stores the raw uint32 (write path) while accepting the legacy
rotated `Value()` form on read (/root/reference/weed/storage/needle/
crc.go:12-33, needle_read.go:73-80).  `value()` reproduces that legacy
transform for read-compat.

Dispatch: native SSE4.2/table C++ (ops/native.py) with a pure-Python
slicing-by-8 fallback.
"""

from __future__ import annotations

import numpy as np

from . import native

_POLY = 0x82F63B78  # reflected Castagnoli


def _make_tables() -> np.ndarray:
    tables = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (_POLY if crc & 1 else 0)
        tables[0, i] = crc
    for s in range(1, 8):
        for i in range(256):
            crc = int(tables[s - 1, i])
            tables[s, i] = tables[0, crc & 0xFF] ^ (crc >> 8)
    return tables


_TABLES: np.ndarray | None = None


def _crc32c_py(crc: int, data: bytes) -> int:
    global _TABLES
    if _TABLES is None:
        _TABLES = _make_tables()
    t = _TABLES
    crc = ~crc & 0xFFFFFFFF
    mv = memoryview(data)
    n8 = len(mv) - (len(mv) % 8)
    for k in range(0, n8, 8):
        word = int.from_bytes(mv[k : k + 8], "little") ^ crc
        crc = (
            int(t[7, word & 0xFF])
            ^ int(t[6, (word >> 8) & 0xFF])
            ^ int(t[5, (word >> 16) & 0xFF])
            ^ int(t[4, (word >> 24) & 0xFF])
            ^ int(t[3, (word >> 32) & 0xFF])
            ^ int(t[2, (word >> 40) & 0xFF])
            ^ int(t[1, (word >> 48) & 0xFF])
            ^ int(t[0, (word >> 56) & 0xFF])
        )
    for b in mv[n8:]:
        crc = int(t[0, (crc ^ b) & 0xFF]) ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of `data` (bytes-like or uint8 ndarray), seeded with `crc`."""
    cdll = native.lib()
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data.reshape(-1).view(np.uint8))
        if cdll is not None:
            import ctypes

            return cdll.sw_crc32c(
                crc, data.ctypes.data_as(ctypes.c_char_p), data.nbytes)
        return _crc32c_py(crc, data.tobytes())
    if not isinstance(data, (bytes, bytearray)):
        data = bytes(data)
    if cdll is not None:
        return cdll.sw_crc32c(crc, bytes(data), len(data))
    return _crc32c_py(crc, bytes(data))


def value(crc: int) -> int:
    """Legacy CRC.Value(): rotate + magic, kept for read-compat with old data."""
    crc &= 0xFFFFFFFF
    rotated = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rotated + 0xA282EAD8) & 0xFFFFFFFF
