"""CRC32C (Castagnoli) — the needle checksum.

The reference computes needle checksums with Go's hash/crc32 Castagnoli
table and stores the raw uint32 (write path) while accepting the legacy
rotated `Value()` form on read (/root/reference/weed/storage/needle/
crc.go:12-33, needle_read.go:73-80).  `value()` reproduces that legacy
transform for read-compat.

Dispatch: native SSE4.2/table C++ (ops/native.py) with a pure-Python
slicing-by-8 fallback.
"""

from __future__ import annotations

import numpy as np

from . import native

_POLY = 0x82F63B78  # reflected Castagnoli


def _make_tables() -> np.ndarray:
    tables = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (_POLY if crc & 1 else 0)
        tables[0, i] = crc
    for s in range(1, 8):
        for i in range(256):
            crc = int(tables[s - 1, i])
            tables[s, i] = tables[0, crc & 0xFF] ^ (crc >> 8)
    return tables


_TABLES: np.ndarray | None = None


def _crc32c_py(crc: int, data: bytes) -> int:
    global _TABLES
    if _TABLES is None:
        _TABLES = _make_tables()
    t = _TABLES
    crc = ~crc & 0xFFFFFFFF
    mv = memoryview(data)
    n8 = len(mv) - (len(mv) % 8)
    for k in range(0, n8, 8):
        word = int.from_bytes(mv[k : k + 8], "little") ^ crc
        crc = (
            int(t[7, word & 0xFF])
            ^ int(t[6, (word >> 8) & 0xFF])
            ^ int(t[5, (word >> 16) & 0xFF])
            ^ int(t[4, (word >> 24) & 0xFF])
            ^ int(t[3, (word >> 32) & 0xFF])
            ^ int(t[2, (word >> 40) & 0xFF])
            ^ int(t[1, (word >> 48) & 0xFF])
            ^ int(t[0, (word >> 56) & 0xFF])
        )
    for b in mv[n8:]:
        crc = int(t[0, (crc ^ b) & 0xFF]) ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of `data` (bytes-like or uint8 ndarray), seeded with `crc`."""
    cdll = native.lib()
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data.reshape(-1).view(np.uint8))
        if cdll is not None:
            import ctypes

            return cdll.sw_crc32c(
                crc, data.ctypes.data_as(ctypes.c_char_p), data.nbytes)
        return _crc32c_py(crc, data.tobytes())
    if not isinstance(data, (bytes, bytearray)):
        data = bytes(data)
    if cdll is not None:
        return cdll.sw_crc32c(crc, bytes(data), len(data))
    return _crc32c_py(crc, bytes(data))


def value(crc: int) -> int:
    """Legacy CRC.Value(): rotate + magic, kept for read-compat with old data."""
    crc &= 0xFFFFFFFF
    rotated = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rotated + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# GF(2) linear-algebra view of CRC32C: combine / zeros / advance matrices.
#
# The CRC state update s' = (s >> 8) ^ T[(s ^ byte) & 0xFF] is jointly linear
# over GF(2) in (state, byte), so "advance the state over n zero bytes" is a
# 32x32 bit matrix Adv_n = A1^n.  These power the device-fused CRC kernel
# (ops/crc_device.py) and crc32c_combine (zlib crc32_combine semantics).
# ---------------------------------------------------------------------------

import functools


def _table0() -> np.ndarray:
    global _TABLES
    if _TABLES is None:
        _TABLES = _make_tables()
    return _TABLES[0]


def raw_update(state: int, data: bytes) -> int:
    """CRC state machine with NO init/final inversion (the linear core)."""
    t0 = _table0()
    state &= 0xFFFFFFFF
    for b in data:
        state = int(t0[(state ^ b) & 0xFF]) ^ (state >> 8)
    return state


_BIT32 = np.arange(32, dtype=np.uint64)


def _bits_of(x: int) -> np.ndarray:
    return ((np.uint64(x) >> _BIT32) & np.uint64(1)).astype(np.uint8)


def _pack_bits(bits: np.ndarray) -> int:
    return int((bits.astype(np.uint64) << _BIT32).sum() & np.uint64(0xFFFFFFFF))


def _gf2_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


@functools.lru_cache(maxsize=1)
def _advance_one() -> np.ndarray:
    """A1[:, i] = bits of raw_update(1 << i, b"\\x00") — one-zero-byte step."""
    cols = [_bits_of(raw_update(1 << i, b"\x00")) for i in range(32)]
    return np.stack(cols, axis=1)


@functools.lru_cache(maxsize=128)
def _advance_pow2(k: int) -> np.ndarray:
    """A1^(2^k) via repeated squaring."""
    if k == 0:
        return _advance_one()
    m = _advance_pow2(k - 1)
    return _gf2_matmul(m, m)


@functools.lru_cache(maxsize=4096)
def advance_matrix(n: int) -> np.ndarray:
    """Adv_n: 32x32 GF(2) matrix advancing the raw CRC state over n zero
    bytes.  raw_update(s, 0^n) == Adv_n @ bits(s)."""
    m = np.eye(32, dtype=np.uint8)
    k = 0
    while n:
        if n & 1:
            m = _gf2_matmul(_advance_pow2(k), m)
        n >>= 1
        k += 1
    return m


def advance(state: int, n: int) -> int:
    """raw_update(state, b"\\x00" * n) without touching the data bytes."""
    return _pack_bits(_gf2_matmul(advance_matrix(n), _bits_of(state)[:, None])
                      .reshape(-1))


@functools.lru_cache(maxsize=4096)
def crc32c_zeros(n: int) -> int:
    """crc32c of n zero bytes (standard init/final inversion applied)."""
    return advance(0xFFFFFFFF, n) ^ 0xFFFFFFFF


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """CRC32C of A||B from crc32c(A), crc32c(B), len(B) — zlib
    crc32_combine: the init/final inversions cancel, leaving
    Adv_{len_b}(crc_a) ^ crc_b."""
    return advance(crc_a, len_b) ^ (crc_b & 0xFFFFFFFF)


def finalize_raw(raw: int, length: int) -> int:
    """Standard crc32c of an n-byte chunk from its raw linear image
    g(M) = raw_update(0, M): crc32c(M) = g(M) ^ crc32c(0^n)."""
    return (raw & 0xFFFFFFFF) ^ crc32c_zeros(length)
