"""ctypes bindings for the native C++ library (native/ec_native.cpp).

Builds the shared library on first import if missing (make in native/);
callers must tolerate `lib() is None` when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libseaweedec.so")


@functools.lru_cache(maxsize=1)
def lib() -> ctypes.CDLL | None:
    # run make unconditionally: it is a no-op when the .so is fresh and
    # rebuilds after ec_native.cpp edits (a missing toolchain only matters
    # when there is no prebuilt library at all)
    try:
        subprocess.run(
            ["make", "-s"], cwd=_NATIVE_DIR, check=True,
            capture_output=True, timeout=120,
        )
    except Exception:
        if not os.path.exists(_LIB_PATH):
            return None
    try:
        cdll = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    cdll.sw_crc32c.restype = ctypes.c_uint32
    cdll.sw_crc32c.argtypes = [
        ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
    ]
    cdll.sw_gf_apply_matrix.restype = None
    cdll.sw_gf_apply_matrix.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    cdll.sw_has_avx2.restype = ctypes.c_int
    cdll.sw_has_avx2.argtypes = []
    cdll.sw_cpu_level.restype = ctypes.c_int
    cdll.sw_cpu_level.argtypes = []
    cdll.sw_gf_apply_matrix_force.restype = None
    cdll.sw_gf_apply_matrix_force.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_int,
    ]
    cdll.sw_encode_rows.restype = None
    cdll.sw_encode_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    if hasattr(cdll, "sw_inline_scatter"):  # absent in stale prebuilt libs
        cdll.sw_inline_scatter.restype = ctypes.c_int
        cdll.sw_inline_scatter.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64,
        ]
    return cdll


def has_avx2() -> bool:
    cdll = lib()
    return bool(cdll and cdll.sw_has_avx2())


def cpu_level() -> int:
    """Best GF kernel level: 0 scalar, 1 AVX2-PSHUFB, 2 GFNI+AVX2,
    3 GFNI+AVX-512 (see native/ec_native.cpp kernel ladder)."""
    cdll = lib()
    return int(cdll.sw_cpu_level()) if cdll else 0
