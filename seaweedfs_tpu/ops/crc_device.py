"""Device-fused CRC32C: batched checksums as GF(2) bit-matmuls.

The reference computes needle CRC32C on the CPU at write time only
(/root/reference/weed/storage/needle/crc.go:12-33).  The TPU build fuses
integrity checksums into the batched encode pass (BASELINE config 5): while
a (B, S, L) block batch is HBM-resident for parity generation, per-chunk
CRCs ride the same MXU machinery.

Formulation — CRC32C's state update is jointly GF(2)-linear in
(state, byte), so for a chunk M the "raw" image g(M) = raw_update(0, M)
decomposes:

  1. split M into 2^k segments; per-segment g = bit-matmul of the segment's
     bits with a precomputed (8*seg, 32) GF(2) matrix W, where
     W[8j+b] = Adv_{seg-1-j}(T[1<<b]) — one MXU dot per batch;
  2. combine adjacent segments with a log-tree of 32x32 advance-matrix
     multiplies: g(A||B) = Adv_{|B|}(g(A)) ^ g(B);
  3. host finalizes: crc32c(M) = g(M) ^ crc32c_zeros(len(M))
     (ops/crc32c.finalize_raw).

Front zero-padding leaves g unchanged (state 0 is a fixed point of zero
bytes), so chunks pad to 2^k * seg for free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import crc32c as crc_host


def _plan_segments(length: int) -> tuple[int, int]:
    """(nseg, seg) with nseg a power of two and nseg * seg >= length.

    Targets kiB-scale segments (deep contraction dim for the MXU) with at
    most 2^8 segments (shallow combine tree, small compiled graph).
    """
    if length <= 0:
        raise ValueError(f"chunk length must be positive, got {length}")
    nseg = 1
    while nseg < 256 and (length + nseg - 1) // nseg > 1024:
        nseg *= 2
    seg = (length + nseg - 1) // nseg
    return nseg, seg


@functools.lru_cache(maxsize=32)
def _segment_matrix(seg: int) -> np.ndarray:
    """W (8*seg, 32) int8 in bit-PLANE-major row order (row b*seg + j =
    bits of g(byte (1<<b) at offset j of a seg-byte segment) =
    Adv_{seg-1-j} @ bits(T[1<<b])), matching the relayout-free bit
    expansion in batched_crc32c_raw."""
    t0 = crc_host._table0()
    # images of the 8 byte-bits when the byte is last in the segment (d = 0)
    rows = np.stack([crc_host._bits_of(int(t0[1 << b])) for b in range(8)])
    a1t = crc_host._advance_one().T.astype(np.int64)
    out = np.zeros((seg, 8, 32), dtype=np.uint8)
    cur = rows.astype(np.int64)
    for d in range(seg):
        out[seg - 1 - d] = cur
        if d + 1 < seg:
            cur = cur @ a1t % 2
    return np.ascontiguousarray(
        out.transpose(1, 0, 2).reshape(8 * seg, 32)).astype(np.int8)


@functools.lru_cache(maxsize=32)
def _tree_matrices(seg: int, nseg: int) -> tuple[np.ndarray, ...]:
    """Transposed advance matrices for each combine level: level k merges
    nodes of seg * 2^k bytes, applying Adv_{seg * 2^k} to the left node."""
    mats = []
    m = nseg
    width = seg
    while m > 1:
        mats.append(crc_host.advance_matrix(width).T.astype(np.int8))
        width *= 2
        m //= 2
    return tuple(mats)


def batched_crc32c_raw(data: jax.Array) -> jax.Array:
    """Raw CRC images g(M) for a batch of chunks.

    data: (..., L) uint8 on device -> (...,) uint32 raw values.  Finalize on
    host with crc32c.finalize_raw(raw, L) to get standard CRC32C.
    Traceable under jit; L is static.
    """
    length = data.shape[-1]
    nseg, seg = _plan_segments(length)
    pad = nseg * seg - length
    if pad:
        data = jnp.pad(data, [(0, 0)] * (data.ndim - 1) + [(pad, 0)])
    lead = data.shape[:-1]
    x = data.reshape(*lead, nseg, seg)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # bit-PLANE-major expansion: (.., nseg, 8, seg) keeps seg minormost, so
    # the merge into (.., nseg, 8*seg) is relayout-free (byte-major order
    # would interleave bit and byte axes and force a full copy of the 8x
    # expanded tensor — measured 6x slower on TPU v5e)
    bits = ((x[..., None, :] >> shifts[:, None]) & 1).astype(jnp.int8)
    bits = bits.reshape(*lead, nseg, 8 * seg)
    w = jnp.asarray(_segment_matrix(seg))  # (8*seg, 32) plane-major rows
    state = jnp.matmul(bits, w, preferred_element_type=jnp.int32) & 1
    return combine_tree(state, seg, nseg)


def combine_tree(state, seg: int, nseg: int):
    """Fold per-segment raw-CRC bit images into whole-chunk values:
    state (..., nseg, 32) 0/1 -> (...,) uint32.  Level k merges nodes of
    seg * 2^k bytes by advancing the LEFT image over the right's span
    (g(A||B) = Adv_{|B|}(g(A)) ^ g(B)) — shared by the XLA formulation
    above and the fused Pallas kernel (ops/rs_pallas.py)."""
    for advt in _tree_matrices(seg, nseg):
        left = state[..., 0::2, :]
        right = state[..., 1::2, :]
        state = (jnp.matmul(left.astype(jnp.int8), jnp.asarray(advt),
                            preferred_element_type=jnp.int32) & 1) ^ right
    state = state[..., 0, :].astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (state * weights).sum(axis=-1, dtype=jnp.uint32)


def finalize(raw, length: int):
    """Vectorised host finalize: standard CRC32C from raw device values."""
    z = np.uint32(crc_host.crc32c_zeros(length))
    return (np.asarray(raw, dtype=np.uint32) ^ z)
