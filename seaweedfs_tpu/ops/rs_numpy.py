"""Reed-Solomon codec base + pure-NumPy reference backend.

Mirrors the `reedsolomon.Encoder` interface the reference storage engine
consumes (Encode / Verify / Reconstruct / ReconstructData — the three methods
called from /root/reference/weed/storage/erasure_coding/ec_encoder.go:198,235
and /root/reference/weed/storage/store_ec.go:331).  All backends (NumPy here,
JAX in rs_jax.py, native C++ in codec.py) share the control flow in
`RSCodecBase` and differ only in `_apply`, the hot GF matrix kernel — so
fixes to the bookkeeping cannot diverge between backends.

Shard convention (same as klauspost): `shards` is a list of length
total_shards; each element is a byte buffer of equal length, or None when the
shard is missing.  Shards 0..data-1 are systematic data, the rest parity.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


class ReconstructError(Exception):
    pass


@functools.lru_cache(maxsize=4096)
def _decode_rows_cached(data_shards: int, total_shards: int,
                        survivors: tuple, targets: tuple) -> np.ndarray:
    """The decode-plan cache.  One entry per (survivor-set, target-set):
    the rows of the decode matrix mapping the chosen survivors directly
    to the target shards, so a degraded read is ONE (t, d) x (d, L) GF
    mat-vec instead of a full matrix inversion + Reconstruct per span.

    Keyed on the ordered survivor tuple: rows[i] pairs with the input
    stacked from survivors[i].  When the survivors are exactly the data
    shards (0..d-1) the submatrix is the identity and no inversion
    happens at all — parity targets read their encode rows straight from
    the encoding matrix."""
    if len(survivors) != data_shards:
        raise ReconstructError(
            f"decode plan needs exactly {data_shards} survivors, "
            f"got {len(survivors)}")
    full = gf256.build_matrix(data_shards, total_shards)
    if list(survivors) == list(range(data_shards)):
        inv = None  # identity submatrix: skip the O(d^3) inversion
    else:
        inv = gf256.gf_invert(full[list(survivors)])
    rows = []
    for t in targets:
        if not 0 <= t < total_shards:
            raise ReconstructError(f"target shard {t} out of range")
        if inv is None:
            rows.append(np.eye(data_shards, dtype=np.uint8)[t]
                        if t < data_shards else full[t])
        elif t < data_shards:
            rows.append(inv[t])
        else:
            rows.append(gf256.gf_matmul(full[t:t + 1], inv)[0])
    out = np.stack(rows).astype(np.uint8)
    out.setflags(write=False)  # cached: callers must not mutate
    return out


def decode_rows(data_shards: int, total_shards: int,
                survivors, targets) -> np.ndarray:
    """(len(targets), data_shards) decode matrix for reconstructing
    `targets` from inputs stacked in `survivors` order.  Cached per
    (survivor-set, target-set); the returned array is read-only."""
    return _decode_rows_cached(data_shards, total_shards,
                               tuple(int(s) for s in survivors),
                               tuple(int(t) for t in targets))


def decode_plan_cache_info():
    """lru cache statistics for the decode-plan cache (hits/misses)."""
    return _decode_rows_cached.cache_info()


def gf_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j mul(matrix[i, j], inputs[j]) over byte vectors.

    matrix: (m, k) uint8; inputs: (k, L) uint8 -> (m, L) uint8.
    """
    mt = gf256.mul_table()
    m, k = matrix.shape
    out = np.zeros((m, inputs.shape[1]), dtype=np.uint8)
    for j in range(k):
        rows = mt[matrix[:, j]]  # (m, 256) lookup rows
        out ^= np.take_along_axis(
            rows, np.broadcast_to(inputs[j], (m, inputs.shape[1])), axis=1
        )
    return out


class RSCodecBase:
    """RS(data, parity) codec over GF(2^8), klauspost-compatible semantics."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("too many shards for GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_matrix(data_shards, self.total_shards)

    # -- the one backend-specific hook --------------------------------------
    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        """out[i] = XOR_j gf_mul(matrix[i,j], inputs[j]); returns host uint8."""
        raise NotImplementedError

    # -- Encode ------------------------------------------------------------
    def encode(self, shards: list) -> list:
        """Fill parity shards from data shards; returns the full shard list."""
        arrs = self._as_arrays(shards)
        self._check_shape(arrs, need_all_data=True)
        data = np.stack(arrs[: self.data_shards])
        parity = self._apply(self.matrix[self.data_shards :], data)
        return list(data) + [parity[i] for i in range(self.parity_shards)]

    def verify(self, shards: list) -> bool:
        arrs = self._as_arrays(shards)
        self._check_shape(arrs, need_all=True)
        data = np.stack(arrs[: self.data_shards])
        parity = self._apply(self.matrix[self.data_shards :], data)
        for i in range(self.parity_shards):
            if not np.array_equal(parity[i], arrs[self.data_shards + i]):
                return False
        return True

    # -- Reconstruct -------------------------------------------------------
    def reconstruct(self, shards: list) -> list:
        """Fill every missing (None) shard in place; returns the shard list."""
        return self._reconstruct(shards, data_only=False)

    def reconstruct_data(self, shards: list) -> list:
        """Fill only missing data shards (parity stays None), like
        klauspost's ReconstructData used by the EC read path."""
        return self._reconstruct(shards, data_only=True)

    def _reconstruct(self, shards: list, data_only: bool) -> list:
        arrs = self._as_arrays(shards)
        self._check_shape(arrs)
        present = [i for i, s in enumerate(arrs) if s is not None]
        if len(present) == self.total_shards:
            return arrs
        if len(present) < self.data_shards:
            raise ReconstructError(
                f"too few shards: {len(present)} < {self.data_shards}"
            )

        # Decode matrix: rows of the encoding matrix for the first data_shards
        # present shards (klauspost picks the same subset), inverted.  When
        # only parity is missing every data shard is present, the submatrix
        # is the identity, and the inversion is skipped entirely — parity
        # regenerates below from the encoding matrix and the data shards.
        missing_data = [i for i in range(self.data_shards) if arrs[i] is None]
        if missing_data:
            sub_rows = present[: self.data_shards]
            inv = gf256.gf_invert(self.matrix[sub_rows])
            inputs = np.stack([arrs[i] for i in sub_rows])
            regenerated = self._apply(inv[missing_data], inputs)
            for out_i, i in enumerate(missing_data):
                arrs[i] = regenerated[out_i]

        if not data_only:
            missing_parity = [
                i
                for i in range(self.data_shards, self.total_shards)
                if arrs[i] is None
            ]
            if missing_parity:
                data = np.stack(arrs[: self.data_shards])
                regenerated = self._apply(self.matrix[missing_parity], data)
                for out_i, i in enumerate(missing_parity):
                    arrs[i] = regenerated[out_i]
        return arrs

    def reconstruct_one(self, shards: list, target: int) -> np.ndarray:
        """Reconstruct ONLY shard `target` from a klauspost-style shard
        list (None = missing) — the degraded-read primitive.  Unlike
        `reconstruct` this never regenerates shards it will not serve:
        one cached decode row, one 1xd GF mat-vec."""
        arrs = self._as_arrays(shards)
        self._check_shape(arrs)
        if arrs[target] is not None:
            return arrs[target]
        present = [i for i, s in enumerate(arrs) if s is not None]
        if len(present) < self.data_shards:
            raise ReconstructError(
                f"too few shards: {len(present)} < {self.data_shards}")
        survivors = tuple(present[: self.data_shards])
        rows = decode_rows(self.data_shards, self.total_shards,
                           survivors, (target,))
        inputs = np.stack([arrs[i] for i in survivors])
        return self._apply(rows, inputs)[0]

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _as_arrays(shards: list) -> list:
        out = []
        for s in shards:
            if s is None:
                out.append(None)
            elif isinstance(s, np.ndarray):
                out.append(s.astype(np.uint8, copy=False))
            else:
                out.append(np.frombuffer(s, dtype=np.uint8))
        return out

    def _check_shape(
        self, arrs: list, need_all: bool = False, need_all_data: bool = False
    ):
        if len(arrs) != self.total_shards:
            raise ValueError(
                f"expected {self.total_shards} shards, got {len(arrs)}"
            )
        length = None
        for i, s in enumerate(arrs):
            if s is None:
                if need_all or (need_all_data and i < self.data_shards):
                    raise ValueError(f"shard {i} missing")
                continue
            if length is None:
                length = len(s)
            elif len(s) != length:
                raise ValueError("shards have differing lengths")
        if length is None:
            raise ValueError("no shards present")


class NumpyEncoder(RSCodecBase):
    """Pure-NumPy reference backend (table-lookup GF math)."""

    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        return gf_apply_matrix(matrix, inputs)
