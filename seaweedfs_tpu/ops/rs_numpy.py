"""Reed-Solomon codec base + pure-NumPy reference backend.

Mirrors the `reedsolomon.Encoder` interface the reference storage engine
consumes (Encode / Verify / Reconstruct / ReconstructData — the three methods
called from /root/reference/weed/storage/erasure_coding/ec_encoder.go:198,235
and /root/reference/weed/storage/store_ec.go:331).  All backends (NumPy here,
JAX in rs_jax.py, native C++ in codec.py) share the control flow in
`RSCodecBase` and differ only in `_apply`, the hot GF matrix kernel — so
fixes to the bookkeeping cannot diverge between backends.

Shard convention (same as klauspost): `shards` is a list of length
total_shards; each element is a byte buffer of equal length, or None when the
shard is missing.  Shards 0..data-1 are systematic data, the rest parity.
"""

from __future__ import annotations

import numpy as np

from . import gf256


class ReconstructError(Exception):
    pass


def gf_apply_matrix(matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j mul(matrix[i, j], inputs[j]) over byte vectors.

    matrix: (m, k) uint8; inputs: (k, L) uint8 -> (m, L) uint8.
    """
    mt = gf256.mul_table()
    m, k = matrix.shape
    out = np.zeros((m, inputs.shape[1]), dtype=np.uint8)
    for j in range(k):
        rows = mt[matrix[:, j]]  # (m, 256) lookup rows
        out ^= np.take_along_axis(
            rows, np.broadcast_to(inputs[j], (m, inputs.shape[1])), axis=1
        )
    return out


class RSCodecBase:
    """RS(data, parity) codec over GF(2^8), klauspost-compatible semantics."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("too many shards for GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.build_matrix(data_shards, self.total_shards)

    # -- the one backend-specific hook --------------------------------------
    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        """out[i] = XOR_j gf_mul(matrix[i,j], inputs[j]); returns host uint8."""
        raise NotImplementedError

    # -- Encode ------------------------------------------------------------
    def encode(self, shards: list) -> list:
        """Fill parity shards from data shards; returns the full shard list."""
        arrs = self._as_arrays(shards)
        self._check_shape(arrs, need_all_data=True)
        data = np.stack(arrs[: self.data_shards])
        parity = self._apply(self.matrix[self.data_shards :], data)
        return list(data) + [parity[i] for i in range(self.parity_shards)]

    def verify(self, shards: list) -> bool:
        arrs = self._as_arrays(shards)
        self._check_shape(arrs, need_all=True)
        data = np.stack(arrs[: self.data_shards])
        parity = self._apply(self.matrix[self.data_shards :], data)
        for i in range(self.parity_shards):
            if not np.array_equal(parity[i], arrs[self.data_shards + i]):
                return False
        return True

    # -- Reconstruct -------------------------------------------------------
    def reconstruct(self, shards: list) -> list:
        """Fill every missing (None) shard in place; returns the shard list."""
        return self._reconstruct(shards, data_only=False)

    def reconstruct_data(self, shards: list) -> list:
        """Fill only missing data shards (parity stays None), like
        klauspost's ReconstructData used by the EC read path."""
        return self._reconstruct(shards, data_only=True)

    def _reconstruct(self, shards: list, data_only: bool) -> list:
        arrs = self._as_arrays(shards)
        self._check_shape(arrs)
        present = [i for i, s in enumerate(arrs) if s is not None]
        if len(present) == self.total_shards:
            return arrs
        if len(present) < self.data_shards:
            raise ReconstructError(
                f"too few shards: {len(present)} < {self.data_shards}"
            )

        # Decode matrix: rows of the encoding matrix for the first data_shards
        # present shards (klauspost picks the same subset), inverted.
        sub_rows = present[: self.data_shards]
        inv = gf256.gf_invert(self.matrix[sub_rows])
        inputs = np.stack([arrs[i] for i in sub_rows])

        missing_data = [i for i in range(self.data_shards) if arrs[i] is None]
        if missing_data:
            regenerated = self._apply(inv[missing_data], inputs)
            for out_i, i in enumerate(missing_data):
                arrs[i] = regenerated[out_i]

        if not data_only:
            missing_parity = [
                i
                for i in range(self.data_shards, self.total_shards)
                if arrs[i] is None
            ]
            if missing_parity:
                data = np.stack(arrs[: self.data_shards])
                regenerated = self._apply(self.matrix[missing_parity], data)
                for out_i, i in enumerate(missing_parity):
                    arrs[i] = regenerated[out_i]
        return arrs

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _as_arrays(shards: list) -> list:
        out = []
        for s in shards:
            if s is None:
                out.append(None)
            elif isinstance(s, np.ndarray):
                out.append(s.astype(np.uint8, copy=False))
            else:
                out.append(np.frombuffer(s, dtype=np.uint8))
        return out

    def _check_shape(
        self, arrs: list, need_all: bool = False, need_all_data: bool = False
    ):
        if len(arrs) != self.total_shards:
            raise ValueError(
                f"expected {self.total_shards} shards, got {len(arrs)}"
            )
        length = None
        for i, s in enumerate(arrs):
            if s is None:
                if need_all or (need_all_data and i < self.data_shards):
                    raise ValueError(f"shard {i} missing")
                continue
            if length is None:
                length = len(s)
            elif len(s) != length:
                raise ValueError("shards have differing lengths")
        if length is None:
            raise ValueError("no shards present")


class NumpyEncoder(RSCodecBase):
    """Pure-NumPy reference backend (table-lookup GF math)."""

    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        return gf_apply_matrix(matrix, inputs)
