"""GF(2^8) arithmetic and matrix algebra for Reed-Solomon coding.

Field: GF(2^8) with the generating polynomial x^8+x^4+x^3+x^2+1 (0x11D) and
generator element 2 — the same field used by klauspost/reedsolomon (the codec
the reference delegates to at /root/reference/weed/storage/erasure_coding/
ec_encoder.go:198) and by Backblaze's JavaReedSolomon, which it is
wire-compatible with.  Parity produced with matrices built here is therefore
bit-identical to the reference's shards.

Everything in this module is host-side (NumPy); the TPU kernels in
rs_jax.py / rs_pallas.py consume the small matrices produced here.
"""

from __future__ import annotations

import functools

import numpy as np

FIELD_SIZE = 256
GENERATING_POLYNOMIAL = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
GENERATOR = 2


def _generate_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build exp/log tables for the field.

    exp is doubled (510 entries) so mul can skip the mod-255 reduction.
    """
    exp = np.zeros(510, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GENERATING_POLYNOMIAL
    exp[255:510] = exp[0:255]
    log[0] = 0  # log(0) undefined; callers must special-case zero
    return exp, log


EXP_TABLE, LOG_TABLE = _generate_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[LOG_TABLE[a] + LOG_TABLE[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inverse(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("0 has no inverse in GF(2^8)")
    return int(EXP_TABLE[(255 - LOG_TABLE[a]) % 255])


def gf_exp(a: int, n: int) -> int:
    """a**n in the field — matches klauspost's galExp (n==0 -> 1, a==0 -> 0)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table (64 KB), used by the NumPy codec."""
    log_a = LOG_TABLE[:, None]
    log_b = LOG_TABLE[None, :]
    table = EXP_TABLE[(log_a + log_b) % 255].astype(np.uint8)
    table[0, :] = 0
    table[:, 0] = 0
    return table


@functools.lru_cache(maxsize=1)
def nibble_tables() -> tuple[np.ndarray, np.ndarray]:
    """(low, high) nibble product tables: low[c, x] = c*x, high[c, x] = c*(x<<4).

    mul(c, d) == low[c, d & 0xF] ^ high[c, d >> 4].  Shape (256, 16) each.
    This is the same decomposition klauspost's SIMD kernels use (PSHUFB on
    16-entry tables); our Pallas kernels use the bit-matrix form instead but
    the tables are handy for host-side vectorised math.
    """
    mt = mul_table()
    low = mt[:, np.arange(16)]
    high = mt[:, np.arange(16) << 4]
    return low, high


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8) (small host-side matrices, NumPy uint8)
# ---------------------------------------------------------------------------


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8). a: (m, k) uint8, b: (k, n) uint8."""
    mt = mul_table()
    # products[m, k, n] then XOR-reduce over k
    products = mt[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(products, axis=1)


def gf_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def gf_invert(m: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(2^8) by Gauss-Jordan elimination."""
    n = m.shape[0]
    if m.shape[1] != n:
        raise ValueError(f"cannot invert non-square matrix {m.shape}")
    work = np.concatenate([m.astype(np.uint8), gf_identity(n)], axis=1)
    mt = mul_table()
    for r in range(n):
        if work[r, r] == 0:
            for below in range(r + 1, n):
                if work[below, r] != 0:
                    work[[r, below]] = work[[below, r]]
                    break
            else:
                raise np.linalg.LinAlgError("matrix is singular over GF(2^8)")
        inv_pivot = gf_inverse(int(work[r, r]))
        work[r] = mt[inv_pivot, work[r]]
        for other in range(n):
            if other != r and work[other, r] != 0:
                work[other] ^= mt[int(work[other, r]), work[r]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """vm[r, c] = r**c in GF(2^8) — klauspost/Backblaze construction."""
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


@functools.lru_cache(maxsize=32)
def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic encoding matrix, identical to klauspost's buildMatrix.

    Vandermonde (total x data), normalised so the top (data x data) block is
    the identity: matrix = vm @ inv(vm[:data]).  Rows 0..data-1 reproduce the
    data unchanged; rows data..total-1 generate parity.
    """
    vm = vandermonde(total_shards, data_shards)
    top_inv = gf_invert(vm[:data_shards])
    m = gf_matmul(vm, top_inv)
    m.setflags(write=False)
    return m


def parity_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """The parity rows of the systematic encoding matrix ((total-data) x data)."""
    return build_matrix(data_shards, total_shards)[data_shards:]


def cauchy_matrix(xs: tuple[int, ...], ys: tuple[int, ...]) -> np.ndarray:
    """Cauchy matrix C[i, j] = 1 / (xs[i] + ys[j]) over GF(2^8).

    Requires xs and ys to be disjoint (so no denominator is zero); any square
    submatrix of a Cauchy matrix is then invertible, which makes [I; C] an MDS
    generator matrix.
    """
    if set(xs) & set(ys):
        raise ValueError("cauchy_matrix: xs and ys must be disjoint")
    c = np.zeros((len(xs), len(ys)), dtype=np.uint8)
    for i, x in enumerate(xs):
        for j, y in enumerate(ys):
            c[i, j] = gf_inverse(x ^ y)
    return c


@functools.lru_cache(maxsize=32)
def build_cauchy_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic [I; C] generator with ys = 0..data-1, xs = data..total-1."""
    ys = tuple(range(data_shards))
    xs = tuple(range(data_shards, total_shards))
    m = np.concatenate([gf_identity(data_shards), cauchy_matrix(xs, ys)])
    m.setflags(write=False)
    return m


def cauchy_inverse(xs: tuple[int, ...], ys: tuple[int, ...]) -> np.ndarray:
    """Closed-form inverse of the square Cauchy matrix C[i, j] = 1/(xs[i]+ys[j]).

    B[j, i] = prod_k(xs[i]+ys[k]) * prod_k(xs[k]+ys[j])
              / ((xs[i]+ys[j]) * prod_{k!=i}(xs[i]+xs[k]) * prod_{k!=j}(ys[j]+ys[k]))

    O(e^2) per matrix after O(e^2) prefix products — no Gauss-Jordan sweep.
    """
    e = len(xs)
    if len(ys) != e:
        raise ValueError("cauchy_inverse: needs a square system")
    inv = np.zeros((e, e), dtype=np.uint8)
    for i in range(e):
        for j in range(e):
            num = 1
            for k in range(e):
                num = gf_mul(num, xs[i] ^ ys[k])
                num = gf_mul(num, xs[k] ^ ys[j])
            den = xs[i] ^ ys[j]
            for k in range(e):
                if k != i:
                    den = gf_mul(den, xs[i] ^ xs[k])
                if k != j:
                    den = gf_mul(den, ys[j] ^ ys[k])
            inv[j, i] = gf_div(num, den)
    return inv


# ---------------------------------------------------------------------------
# GF(2) bit-matrix form: every GF(2^8) linear map is linear over GF(2).
# Used by the TPU MXU kernel (XOR == addition mod 2 == int matmul + mod 2).
# ---------------------------------------------------------------------------


def coeff_bit_matrix(coeffs: np.ndarray) -> np.ndarray:
    """Expand a (p, d) GF(2^8) coefficient matrix to a (p*8, d*8) GF(2) matrix.

    out_bits = B @ in_bits (mod 2), where byte j of the input contributes bits
    [j*8, j*8+8) (bit b = (byte >> b) & 1) and likewise for outputs.
    B[i*8+r, j*8+s] = bit r of gf_mul(coeffs[i, j], 1 << s).
    """
    p, d = coeffs.shape
    bits = np.zeros((p * 8, d * 8), dtype=np.uint8)
    for i in range(p):
        for j in range(d):
            c = int(coeffs[i, j])
            for s in range(8):
                prod = gf_mul(c, 1 << s)
                for r in range(8):
                    bits[i * 8 + r, j * 8 + s] = (prod >> r) & 1
    return bits
