"""JAX/XLA Reed-Solomon GF(2^8) kernels for TPU.

Two formulations, both gather-free (TPU VPU/MXU have no fast byte gather):

1. SWAR bitplane (`apply_matrix_swar`): bytes packed 4-per-int32 lane.
   mul-by-constant c decomposes over input bits: d*c = XOR_b ((d>>b)&1) * (c*x^b).
   Per-byte 0/1 masks times a <256 constant never carry across packed bytes,
   so the whole computation is int32 shifts/ands/mults/xors — native VPU ops.

2. MXU bit-matmul (`apply_matrix_mxu`): every GF(2^8) linear map is linear
   over GF(2).  Expand the (p, d) coefficient matrix to a (8p, 8d) 0/1 bit
   matrix (gf256.coeff_bit_matrix), bit-slice the data to (8d, L) int8, and
   compute parity bits as an integer matmul on the MXU followed by mod-2:
   XOR == addition mod 2.  This keeps the FLOPs on the systolic array.

Replaces the reference's CPU codec calls (klauspost enc.Encode /
enc.Reconstruct at /root/reference/weed/storage/erasure_coding/
ec_encoder.go:198,235 and store_ec.go:331).  Matrix-agnostic: encode, decode
and rebuild are all `apply_matrix` with different small host-built matrices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256
from .rs_numpy import RSCodecBase

_SPREAD = 0x01010101  # one set bit per packed byte


# The caches hold host (NumPy) arrays: caching jnp arrays would capture a
# tracer if the first call happened under a jit trace.
@functools.lru_cache(maxsize=64)
def _bit_constants_cached(matrix_bytes: bytes, p: int, d: int) -> np.ndarray:
    """K[i, j, b] = gf_mul(matrix[i, j], 1 << b), shape (p, d, 8) int32."""
    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(p, d)
    mt = gf256.mul_table()
    powers = (1 << np.arange(8)).astype(np.uint8)
    return mt[matrix[:, :, None], powers[None, None, :]].astype(np.int32)


@functools.lru_cache(maxsize=64)
def _bit_matrix_cached(matrix_bytes: bytes, p: int, d: int) -> np.ndarray:
    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(p, d)
    return gf256.coeff_bit_matrix(matrix).astype(np.int8)


def _matrix_key(matrix: np.ndarray) -> tuple[bytes, int, int]:
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    return m.tobytes(), m.shape[0], m.shape[1]


@functools.partial(jax.jit, static_argnames=("out_rows",))
def _apply_swar(consts: jax.Array, data32: jax.Array, out_rows: int) -> jax.Array:
    """consts: (p, d, 8) int32; data32: (d, W) int32 packed bytes -> (p, W)."""
    d = data32.shape[0]
    acc = jnp.zeros((out_rows, data32.shape[1]), dtype=jnp.int32)
    for j in range(d):
        x = data32[j]
        for b in range(8):
            t = jax.lax.shift_right_logical(x, b) & _SPREAD  # (W,)
            # t has one 0/1 bit per byte; t * K (K < 256) stays per-byte.
            acc = acc ^ (t[None, :] * consts[:, j, b][:, None])
    return acc


def apply_matrix_swar(matrix: np.ndarray, data: jax.Array) -> jax.Array:
    """out[i] = XOR_j gf_mul(matrix[i,j], data[j]); data (d, L) uint8."""
    p, d = matrix.shape
    length = data.shape[-1]
    pad = (-length) % 4
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    consts = jnp.asarray(_bit_constants_cached(*_matrix_key(matrix)))
    data32 = jax.lax.bitcast_convert_type(
        data.reshape(d, (length + pad) // 4, 4), jnp.int32
    )
    out32 = _apply_swar(consts, data32, p)
    out = jax.lax.bitcast_convert_type(out32, jnp.uint8).reshape(p, length + pad)
    return out[:, :length] if pad else out


@jax.jit
def _apply_mxu(bit_matrix: jax.Array, data: jax.Array) -> jax.Array:
    """bit_matrix: (8p, 8d) int8; data: (d, L) uint8 -> (p, L) uint8."""
    d, length = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # bit-slice: (d, L) -> (d, 8, L) -> (8d, L); bit s of byte j at row j*8+s
    bits = ((data[:, None, :] >> shifts[None, :, None]) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 8, length)
    prod = jax.lax.dot(
        bit_matrix, bits, precision=None,
        preferred_element_type=jnp.int32,
    )
    out_bits = (prod & 1).astype(jnp.uint8).reshape(-1, 8, length)
    weights = (jnp.uint8(1) << shifts)[None, :, None]
    return (out_bits * weights).sum(axis=1, dtype=jnp.uint8)


def apply_matrix_mxu(matrix: np.ndarray, data: jax.Array) -> jax.Array:
    bm = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))
    return _apply_mxu(bm, data)


def apply_matrix(matrix: np.ndarray, data, method: str = "swar") -> jax.Array:
    """Dispatch: matrix (p, d) uint8 host array, data (d, L) uint8 device array."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    if method == "swar":
        return apply_matrix_swar(matrix, data)
    if method == "mxu":
        return apply_matrix_mxu(matrix, data)
    if method == "pallas":
        from . import rs_pallas

        return rs_pallas.apply_matrix_pallas(matrix, data)
    raise ValueError(f"unknown method {method!r}")


class JaxEncoder(RSCodecBase):
    """reedsolomon.Encoder-compatible codec running the GF math under XLA.

    Shard lists are host buffers; device round-trips happen per call.  For
    the high-throughput batched path use seaweedfs_tpu.parallel's batched
    codec, which keeps shards device-resident.
    """

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 method: str = "swar"):
        super().__init__(data_shards, parity_shards)
        self.method = method

    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        return np.asarray(apply_matrix(matrix, inputs, self.method))
