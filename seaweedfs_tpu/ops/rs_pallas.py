"""Pallas TPU kernel for GF(2^8) matrix application (RS encode/reconstruct).

Strategy: the GF(2) bit-matmul formulation (see rs_jax.py docstring) with the
bit-slice -> MXU matmul -> bit-pack pipeline fused inside one kernel, so the
8x-expanded bit-sliced intermediate lives only in VMEM and HBM traffic stays
at (d + p) * L bytes.  The grid walks the byte axis; each program handles a
(d, BLOCK) tile of packed bytes.

Replaces klauspost enc.Encode's SIMD inner loop
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:198) with an MXU
systolic-array contraction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

DEFAULT_BLOCK = 8192


def _gf_apply_kernel(bm_ref, x_ref, o_ref, *, d: int, p: int):
    x = x_ref[:].astype(jnp.int32)  # (d, BLOCK) bytes as int32
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    bits = ((x[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 8, x.shape[-1])
    # XOR == add mod 2: integer matmul on the MXU, then take the low bit.
    prod = jax.lax.dot(
        bm_ref[:], bits, preferred_element_type=jnp.int32
    )  # (p*8, BLOCK)
    out_bits = (prod & 1).reshape(p, 8, x.shape[-1])
    weights = jnp.left_shift(1, shifts)  # (1, 8, 1)
    o_ref[:] = (out_bits * weights).sum(axis=1).astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("out_rows", "block", "interpret")
)
def _apply_pallas(bit_matrix, data, out_rows: int, block: int,
                  interpret: bool):
    d, length = data.shape
    grid = (pl.cdiv(length, block),)
    kernel = functools.partial(_gf_apply_kernel, d=d, p=out_rows)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((out_rows, length), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (out_rows * 8, d * 8),
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (d, block), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (out_rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * out_rows * 8 * d * 8 * length,
            bytes_accessed=(d + out_rows) * length,
            transcendentals=0,
        ),
    )(bit_matrix, data)


def apply_matrix_pallas(matrix: np.ndarray, data, block: int = DEFAULT_BLOCK,
                        interpret: bool | None = None):
    """out[i] = XOR_j gf_mul(matrix[i,j], data[j]).  data: (d, L) uint8."""
    from ..util.platform import on_tpu
    from .rs_jax import _bit_matrix_cached, _matrix_key

    p, d = matrix.shape
    bm = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))
    data = jnp.asarray(data, dtype=jnp.uint8)
    if interpret is None:
        interpret = not on_tpu()
    return _apply_pallas(bm, data, p, block, interpret)


# ---------------------------------------------------------------------------
# Fused batched parity + CRC32C kernel (the production encode step).
#
# The XLA formulation (parallel/mesh.batched_encode_step) materializes the
# 8x bit expansion in HBM twice (parity matmul input + CRC matmul input).
# Here one VMEM-resident expansion feeds both, and the data rides the MXU
# in WORD layout: 4 packed bytes per int32 lane.  That makes the bit
# expansion rows (shard, byteidx, plane) = d*32 rows per W = BLOCK/4
# lanes, so
#
#   * the parity matmul is (p*32, d*32) @ (d*32, W) — a full 128-row MXU
#     tile at 4x fewer lane tiles than the byte layout, and
#   * the CRC matmul is (d*32, W) @ (W, 32) — W/128 weight tiles against
#     the plane-7 segment matrix restricted to word-anchor byte positions.
#
# Byte-position and bit-plane dependence of CRC32C folds into per-
# (byteidx, plane) 32x32 GF(2) advance corrections applied OUTSIDE the
# kernel on the tiny (B, nseg, 14*32)-word partials:
#
#   true[(s, bi, b)] = Bz^(7-b-8*bi) @ raw[(s, bi, b)]
#
# with Bz the one-zero-BIT CRC advance (all powers commute; verified
# against the byte-layout segment matrices).  Segments then combine into
# whole-chunk CRCs with the log-tree of 32x32 advance matrices from
# ops/crc_device.py.
#
# Parity stays in packed int32 words end-to-end: a device-side
# int32->uint8 bitcast is a byte-granular relayout on TPU (measured 10x
# the kernel's own cost), while host-side numpy views of the downloaded
# words are free.  Measured on TPU v5e at the shipped 32 KiB fused
# block: ~60 GiB/s fused vs ~60 GiB/s parity-only — CRC fusion is
# essentially free (the round-3 plane-partial byte-layout kernel ran
# 26 GiB/s; see DEFAULT_FUSED_BLOCK below for the block sweep).
# ---------------------------------------------------------------------------

_POLY_REFLECTED = 0x82F63B78


def _gf2_inv(m: np.ndarray) -> np.ndarray:
    """Inverse of a GF(2) matrix via Gaussian elimination."""
    n = m.shape[0]
    a = m.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = next(r for r in range(col, n) if a[r, col])
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


@functools.lru_cache(maxsize=1)
def _bit_advance() -> np.ndarray:
    """Bz: 32x32 GF(2) one-zero-BIT advance of the raw CRC32C state
    (s' = (s >> 1) ^ (POLY if s & 1)); Bz^8 equals the one-byte advance
    crc32c._advance_one()."""
    from . import crc32c as crc_host

    def col(i):
        s = 1 << i
        return crc_host._bits_of((s >> 1)
                                 ^ (_POLY_REFLECTED if s & 1 else 0))
    return np.stack([col(i) for i in range(32)], axis=1).astype(np.uint8)


@functools.lru_cache(maxsize=1)
def _word_corrections() -> np.ndarray:
    """CT (4, 8, 32, 32) int8: CT[bi, b] = (Bz^(7-b) Bz^(-8 bi))^T, the
    row-transform turning a raw word-anchor partial into the true
    (byteidx bi, plane b) contribution."""
    bz = _bit_advance().astype(np.int64)
    bzinv = _gf2_inv(_bit_advance()).astype(np.int64)
    out = np.zeros((4, 8, 32, 32), dtype=np.int8)
    for bi in range(4):
        for b in range(8):
            m = (np.linalg.matrix_power(bz, 7 - b)
                 @ np.linalg.matrix_power(bzinv, 8 * bi)) % 2
            out[bi, b] = m.T.astype(np.int8)
    return out


@functools.lru_cache(maxsize=8)
def _anchor_matrix(block: int) -> np.ndarray:
    """V (block//4, 32) int8: plane-7 segment-CRC images at the word
    anchor byte positions 4w of a block-byte segment."""
    from .crc_device import _segment_matrix

    w = _segment_matrix(block)  # (8*block, 32) plane-major rows
    return np.ascontiguousarray(w.reshape(8, block, 32)[7][::4])


@functools.lru_cache(maxsize=4)
def _bm_word_cached(matrix_bytes: bytes, p: int, d: int) -> np.ndarray:
    """The (p*32, d*32) word-layout GF(2) bit matrix: block-diagonal over
    byteidx (RS parity is per-byte, so word bit k=8*bi+b maps within its
    own byte group)."""
    from .rs_jax import _bit_matrix_cached

    bm = _bit_matrix_cached(matrix_bytes, p, d)
    bmr = bm.reshape(p, 8, d, 8)
    bmw = np.zeros((p, 4, 8, d, 4, 8), np.int8)
    for bi in range(4):
        bmw[:, bi, :, :, bi, :] = bmr
    return np.ascontiguousarray(bmw.reshape(p * 32, d * 32))


def _fused_words_kernel(bmw_ref, v_ref, x_ref, par_ref, crc_ref, *,
                        d: int, p: int):
    xw = x_ref[0]  # (d, W) int32 packed little-endian bytes
    w = xw.shape[-1]
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 32, 1), 1)
    bits = ((xw[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 32, w)  # rows (shard, byteidx, plane)
    prod = jax.lax.dot(bmw_ref[:], bits,
                       preferred_element_type=jnp.int32)  # (p*32, W)
    out_bits = prod & 1
    # pack parity bit rows back into int32 words (wrapping shifts leave
    # exactly the right bit pattern)
    wts = jnp.left_shift(jnp.int32(1), shifts)
    par_ref[0] = (out_bits.reshape(p, 32, w) * wts).sum(axis=1)
    # raw CRC partials: one narrow matmul against the anchor matrix; the
    # parity shards' partials follow algebraically through the same bit
    # matrix (parity bits are GF(2)-linear in data bits per position)
    yd = jax.lax.dot(bits, v_ref[:], preferred_element_type=jnp.int32)
    yd8 = (yd & 1).astype(jnp.int8)  # (d*32, 32)
    yp = jax.lax.dot(bmw_ref[:], yd8,
                     preferred_element_type=jnp.int32)  # (p*32, 32)
    y_all = jnp.concatenate([yd8.astype(jnp.int32), yp & 1], axis=0)
    # pack each row's 32 bits into an int32 word (Mosaic has no unsigned
    # reductions; bit 31 rides the sign bit with the right pattern)
    w32 = jnp.left_shift(
        jnp.int32(1), jax.lax.broadcasted_iota(jnp.int32, (1, 32), 1))
    packed = (y_all * w32).sum(axis=-1)  # ((d+p)*32,) int32
    # output tiles need (8, 128)-aligned trailing dims: (d+p)*32 = 448
    # raw words ride row 0 of an (8, 512) tile
    tile = jnp.pad(packed[None, :], ((0, 7), (0, 512 - (d + p) * 32)))
    crc_ref[0, 0] = jax.lax.bitcast_convert_type(tile, jnp.uint32)


@functools.partial(
    jax.jit, static_argnames=("d", "p", "block", "interpret"))
def _fused_encode_words(bmw, v, words, d: int, p: int, block: int,
                        interpret: bool):
    b, _, lw = words.shape
    wblk = block // 4
    nseg = (lw * 4) // block
    kernel = functools.partial(_fused_words_kernel, d=d, p=p)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, p, lw), jnp.int32),
            jax.ShapeDtypeStruct((b, nseg, 8, 512), jnp.uint32),
        ),
        grid=(b, nseg),
        in_specs=[
            pl.BlockSpec((p * 32, d * 32), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((wblk, 32), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d, wblk), lambda bi, i: (bi, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, p, wblk), lambda bi, i: (bi, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 8, 512), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * (p * 32 * d * 32 + d * 32 * 32) * lw * b,
            bytes_accessed=(d + p) * lw * 4 * b,
            transcendentals=0,
        ),
    )(bmw, v, words)


# The fused words kernel runs FASTER at larger in-kernel segments
# (fewer grid steps, better MXU amortisation): measured on TPU v5e at
# (6, 10, 1 MiB): 8192 -> 49.5, 16384 -> 58.2, 32768 -> 59.9 GiB/s
# (parity-only ceiling 60.2 — CRC fusion is essentially free at 32 KiB).
DEFAULT_FUSED_BLOCK = 32768


def fused_encode_block(length: int,
                       block: int = DEFAULT_FUSED_BLOCK) -> int:
    """Largest kernel block that divides length with a power-of-two
    segment count, or 0 when the fused kernel cannot handle this shape."""
    while block >= 512:
        nseg = length // block
        if length % block == 0 and nseg > 0 and nseg & (nseg - 1) == 0:
            return block
        block //= 2
    return 0


def fused_encode_words(matrix: np.ndarray, words,
                       block: int | None = None,
                       interpret: bool | None = None):
    """Batched parity + per-shard raw CRC32C, word-layout (the production
    encode step).

    words: (B, d, L//4) int32 — each lane is 4 consecutive shard bytes,
    little-endian (a free numpy .view(np.int32) of the (B, d, L) uint8
    host buffer).  Returns (parity_words (B, p, L//4) int32, crc_raw
    (B, d+p) uint32).  Parity words are the packed parity bytes — view
    the downloaded array as uint8 on the host; no device bitcast happens
    in either direction.  L must divide into a power-of-two count of
    `block`-byte segments (check with fused_encode_block first)."""
    from ..util.platform import on_tpu
    from .crc_device import combine_tree
    from .rs_jax import _matrix_key

    p, d = matrix.shape
    words = jnp.asarray(words, dtype=jnp.int32)
    length = words.shape[-1] * 4
    if block is None:
        block = fused_encode_block(length)
    if not block or block % 4:
        raise ValueError(f"length {length} unsupported by fused kernel")
    nseg = length // block
    bmw = jnp.asarray(_bm_word_cached(*_matrix_key(matrix)))
    v = jnp.asarray(_anchor_matrix(block))
    if interpret is None:
        interpret = not on_tpu()
    parity_w, tiles = _fused_encode_words(bmw, v, words, d, p, block,
                                          interpret)
    # per-(byteidx, plane) advance corrections + the shared combine fold:
    # tiny (B * nseg * 448 words) XLA work next to the kernel itself
    packed = tiles[:, :, 0, :(d + p) * 32]
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((packed[..., None] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(*packed.shape[:2], d + p, 4, 8, 32)
    ct = jnp.asarray(_word_corrections())
    corr = jnp.einsum("bnsiqc,iqcd->bnsd", bits, ct,
                      preferred_element_type=jnp.int32) & 1
    state = corr.astype(jnp.int8).transpose(0, 2, 1, 3)
    return parity_w, combine_tree(state, block, nseg)


def fused_encode_pallas(matrix: np.ndarray, data,
                        block: int | None = None,
                        interpret: bool | None = None):
    """Byte-layout convenience wrapper over fused_encode_words.

    data: (B, d, L) uint8 -> (parity (B, p, L) uint8, crc_raw (B, d+p)
    uint32), same contract as parallel.mesh.batched_encode_step.  The
    device-side uint8<->int32 bitcasts this needs are relayouts on TPU —
    production paths (parallel/batched_encode.py) upload int32 views and
    call fused_encode_words directly."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    b, d, length = data.shape
    words = jax.lax.bitcast_convert_type(
        data.reshape(b, d, length // 4, 4), jnp.int32)
    parity_w, crc_raw = fused_encode_words(matrix, words, block=block,
                                           interpret=interpret)
    parity = jax.lax.bitcast_convert_type(
        parity_w, jnp.uint8).reshape(b, matrix.shape[0], length)
    return parity, crc_raw
