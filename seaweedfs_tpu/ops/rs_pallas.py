"""Pallas TPU kernel for GF(2^8) matrix application (RS encode/reconstruct).

Strategy: the GF(2) bit-matmul formulation (see rs_jax.py docstring) with the
bit-slice -> MXU matmul -> bit-pack pipeline fused inside one kernel, so the
8x-expanded bit-sliced intermediate lives only in VMEM and HBM traffic stays
at (d + p) * L bytes.  The grid walks the byte axis; each program handles a
(d, BLOCK) tile of packed bytes.

Replaces klauspost enc.Encode's SIMD inner loop
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:198) with an MXU
systolic-array contraction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

DEFAULT_BLOCK = 8192


def _gf_apply_kernel(bm_ref, x_ref, o_ref, *, d: int, p: int):
    x = x_ref[:].astype(jnp.int32)  # (d, BLOCK) bytes as int32
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    bits = ((x[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 8, x.shape[-1])
    # XOR == add mod 2: integer matmul on the MXU, then take the low bit.
    prod = jax.lax.dot(
        bm_ref[:], bits, preferred_element_type=jnp.int32
    )  # (p*8, BLOCK)
    out_bits = (prod & 1).reshape(p, 8, x.shape[-1])
    weights = jnp.left_shift(1, shifts)  # (1, 8, 1)
    o_ref[:] = (out_bits * weights).sum(axis=1).astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("out_rows", "block", "interpret")
)
def _apply_pallas(bit_matrix, data, out_rows: int, block: int,
                  interpret: bool):
    d, length = data.shape
    grid = (pl.cdiv(length, block),)
    kernel = functools.partial(_gf_apply_kernel, d=d, p=out_rows)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((out_rows, length), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (out_rows * 8, d * 8),
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (d, block), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (out_rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * out_rows * 8 * d * 8 * length,
            bytes_accessed=(d + out_rows) * length,
            transcendentals=0,
        ),
    )(bit_matrix, data)


def apply_matrix_pallas(matrix: np.ndarray, data, block: int = DEFAULT_BLOCK,
                        interpret: bool | None = None):
    """out[i] = XOR_j gf_mul(matrix[i,j], data[j]).  data: (d, L) uint8."""
    from ..util.platform import on_tpu
    from .rs_jax import _bit_matrix_cached, _matrix_key

    p, d = matrix.shape
    bm = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))
    data = jnp.asarray(data, dtype=jnp.uint8)
    if interpret is None:
        interpret = not on_tpu()
    return _apply_pallas(bm, data, p, block, interpret)


# ---------------------------------------------------------------------------
# Fused batched parity + CRC32C kernel (the production encode step).
#
# The XLA formulation (parallel/mesh.batched_encode_step) materializes the
# 8x bit expansion in HBM twice (parity matmul input + CRC matmul input).
# Here one VMEM-resident expansion feeds both: each grid program computes a
# (d, BLOCK) tile's parity AND its CRC32C segment image (the per-segment
# raw CRC of all 14 shards), so HBM traffic stays at parity-kernel levels
# and only (B, nseg, 14) uint32 segment images are added.  Segments combine
# into whole-chunk CRCs with the log-tree of 32x32 advance matrices from
# ops/crc_device.py, outside the kernel (tiny).
# ---------------------------------------------------------------------------


def _fused_kernel(bm_ref, w3_ref, x_ref, par_ref, crc_ref, *, d: int,
                  p: int):
    x = x_ref[0].astype(jnp.int32)  # (d, BLOCK)
    block = x.shape[-1]
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    bits = ((x[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 8, block)
    prod = jax.lax.dot(
        bm_ref[:], bits, preferred_element_type=jnp.int32)  # (p*8, BLOCK)
    out_bits = (prod & 1)
    weights = jnp.left_shift(1, shifts)  # (1, 8, 1)
    par_ref[0] = (out_bits.reshape(p, 8, block) * weights).sum(
        axis=1).astype(jnp.uint8)
    # CRC via plane-partial images: one matmul of the SAME bit rows the
    # parity used (rows (shard, plane), no re-extraction or relayout)
    # against a widened (BLOCK, 8*32) matrix whose column group p8' holds
    # the segment matrix restricted to plane p8'.  Row (s, p8) x group
    # p8' is only meaningful on the diagonal p8 == p8'; the off-diagonal
    # 7/8 of the MXU work is the price of skipping a second 14-row bit
    # extraction, and measures ~1.6x faster end to end
    full_bits = jnp.concatenate(
        [bits, out_bits.astype(jnp.int8)], axis=0)  # ((d+p)*8, BLOCK)
    y2 = jax.lax.dot(
        full_bits, w3_ref[:],
        preferred_element_type=jnp.int32)  # ((d+p)*8, 256)
    # sublane-dim reshape only (Mosaic cannot split the 256 lane dim),
    # then 8 static diagonal slices accumulate the per-plane partials
    y3 = y2.reshape(d + p, 8, 256)
    acc = y3[:, 0, 0:32]
    for p8 in range(1, 8):
        acc = acc + y3[:, p8, p8 * 32:(p8 + 1) * 32]
    crc_bits = acc & 1  # (d+p, 32)
    # pack bits into words in int32 (Mosaic has no unsigned reductions;
    # bit 31 rides the sign bit with the right pattern) and bitcast out
    w32 = jnp.left_shift(
        jnp.int32(1), jax.lax.broadcasted_iota(jnp.int32, (1, 32), 1))
    packed = (crc_bits * w32).sum(axis=-1)  # (d+p,) int32
    # the CRC words ride an (8, 128) tile: TPU block shapes must be
    # (8, 128)-aligned in their last two dims, and d+p=14 is neither —
    # row 0 holds the real words, the rest is padding the host slices off
    tile = jnp.pad(packed[None, :], ((0, 7), (0, 128 - (d + p))))
    crc_ref[0, 0] = jax.lax.bitcast_convert_type(tile, jnp.uint32)


@functools.partial(
    jax.jit, static_argnames=("d", "p", "block", "interpret"))
def _fused_encode_pallas(bit_matrix, w3, data, d: int, p: int, block: int,
                         interpret: bool):
    b, _, length = data.shape
    nseg = length // block
    kernel = functools.partial(_fused_kernel, d=d, p=p)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, p, length), jnp.uint8),
            jax.ShapeDtypeStruct((b, nseg, 8, 128), jnp.uint32),
        ),
        grid=(b, nseg),
        in_specs=[
            pl.BlockSpec((p * 8, d * 8), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 256), lambda bi, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d, block), lambda bi, i: (bi, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, p, block), lambda bi, i: (bi, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 8, 128), lambda bi, i: (bi, i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * (p * 8 * d * 8 + (d + p) * 8 * 256) * length * b,
            bytes_accessed=(d + p) * length * b,
            transcendentals=0,
        ),
    )(bit_matrix, w3, data)


@functools.lru_cache(maxsize=8)
def _plane_partial_matrix(block: int) -> np.ndarray:
    """W3 (block, 256) int8: column group p8 (cols 32*p8..32*p8+31) is the
    segment CRC matrix restricted to bit-plane p8, so a (shard, plane) bit
    row contracted with group p8 yields that plane's partial CRC image."""
    from .crc_device import _segment_matrix

    w = _segment_matrix(block)  # (8*block, 32), rows (plane, byte)
    return np.ascontiguousarray(
        w.reshape(8, block, 32).transpose(1, 0, 2).reshape(block, 256))


def fused_encode_block(length: int, block: int = DEFAULT_BLOCK) -> int:
    """Largest kernel block that divides length with a power-of-two
    segment count, or 0 when the fused kernel cannot handle this shape."""
    while block >= 512:
        nseg = length // block
        if length % block == 0 and nseg > 0 and nseg & (nseg - 1) == 0:
            return block
        block //= 2
    return 0


def fused_encode_pallas(matrix: np.ndarray, data,
                        block: int | None = None,
                        interpret: bool | None = None):
    """Batched parity + per-shard raw CRC32C in one fused kernel.

    data: (B, d, L) uint8 -> (parity (B, p, L) uint8, crc_raw (B, d+p)
    uint32), same contract as parallel.mesh.batched_encode_step.  L must
    divide into a power-of-two count of `block`-byte segments (check
    with fused_encode_block first).
    """
    from ..util.platform import on_tpu
    from .crc_device import _segment_matrix, combine_tree
    from .rs_jax import _bit_matrix_cached, _matrix_key

    p, d = matrix.shape
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = data.shape[-1]
    if block is None:
        block = fused_encode_block(length)
    if not block:
        raise ValueError(f"length {length} unsupported by fused kernel")
    nseg = length // block
    bm = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))
    w3 = jnp.asarray(_plane_partial_matrix(block))
    if interpret is None:
        interpret = not on_tpu()
    parity, seg_tiles = _fused_encode_pallas(bm, w3, data, d, p, block,
                                             interpret)
    seg = seg_tiles[:, :, 0, :d + p]  # strip the (8, 128) tile padding
    # combine segment images left-to-right with the advance-matrix tree
    # (the shared fold from crc_device)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    state = ((seg[..., None] >> shifts) & 1).astype(jnp.int8)
    state = state.transpose(0, 2, 1, 3)  # (B, shards, nseg, 32)
    return parity, combine_tree(state, block, nseg)
