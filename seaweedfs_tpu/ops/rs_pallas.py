"""Pallas TPU kernel for GF(2^8) matrix application (RS encode/reconstruct).

Strategy: the GF(2) bit-matmul formulation (see rs_jax.py docstring) with the
bit-slice -> MXU matmul -> bit-pack pipeline fused inside one kernel, so the
8x-expanded bit-sliced intermediate lives only in VMEM and HBM traffic stays
at (d + p) * L bytes.  The grid walks the byte axis; each program handles a
(d, BLOCK) tile of packed bytes.

Replaces klauspost enc.Encode's SIMD inner loop
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:198) with an MXU
systolic-array contraction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

DEFAULT_BLOCK = 8192


def _gf_apply_kernel(bm_ref, x_ref, o_ref, *, d: int, p: int):
    x = x_ref[:].astype(jnp.int32)  # (d, BLOCK) bytes as int32
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    bits = ((x[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(d * 8, x.shape[-1])
    # XOR == add mod 2: integer matmul on the MXU, then take the low bit.
    prod = jax.lax.dot(
        bm_ref[:], bits, preferred_element_type=jnp.int32
    )  # (p*8, BLOCK)
    out_bits = (prod & 1).reshape(p, 8, x.shape[-1])
    weights = jnp.left_shift(1, shifts)  # (1, 8, 1)
    o_ref[:] = (out_bits * weights).sum(axis=1).astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("out_rows", "block", "interpret")
)
def _apply_pallas(bit_matrix, data, out_rows: int, block: int,
                  interpret: bool):
    d, length = data.shape
    grid = (pl.cdiv(length, block),)
    kernel = functools.partial(_gf_apply_kernel, d=d, p=out_rows)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((out_rows, length), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (out_rows * 8, d * 8),
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (d, block), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (out_rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * out_rows * 8 * d * 8 * length,
            bytes_accessed=(d + out_rows) * length,
            transcendentals=0,
        ),
    )(bit_matrix, data)


def apply_matrix_pallas(matrix: np.ndarray, data, block: int = DEFAULT_BLOCK,
                        interpret: bool | None = None):
    """out[i] = XOR_j gf_mul(matrix[i,j], data[j]).  data: (d, L) uint8."""
    from ..util.platform import on_tpu
    from .rs_jax import _bit_matrix_cached, _matrix_key

    p, d = matrix.shape
    bm = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))
    data = jnp.asarray(data, dtype=jnp.uint8)
    if interpret is None:
        interpret = not on_tpu()
    return _apply_pallas(bm, data, p, block, interpret)
