"""Device-memory slab pool for the EC pipeline (BASELINE config 4's
orchestration layer).

The raw kernels sustain tens of GiB/s once data is HBM-resident, but a
dispatch layer that allocates fresh buffers per batch never gets there:
BENCH_r05 measured the device dispatch path at 0.005 GiB/s — 12,000x
under the fused kernel — with the time going to per-batch `device_put`
allocations, undonated outputs and synchronous drains.  This module is
the fix's memory half: every buffer the dispatch path touches comes from
a pool of pre-allocated, fixed-shape slabs so the steady state performs
ZERO per-batch allocations.

Two kinds of slab, one accounting domain:

  leases    — fixed-shape transfer/compute slots keyed by an opaque
              caller key (shape, dtype, device/mesh).  `lease()` hands
              out a free slab of the key or materializes one via the
              caller's factory (host staging buffers, donated device
              output rings); `release()` returns it for reuse.  Repeat
              encodes with the same geometry re-lease the same slabs.
  residents — ref-counted content slabs (`acquire_resident`): device
              uploads that outlive one call so repeated degraded reads /
              rebuilds against the same survivor set hit HBM instead of
              re-uploading over the link.  A resident with refs == 0
              stays cached until the byte cap evicts it (LRU).

`WEED_EC_DEVICE_POOL_MB` caps the total bytes the pool retains for
*idle* slabs (free leases + unreferenced residents); actively leased or
referenced slabs are never evicted, so the cap is a retention bound,
not an admission control.  The pool never imports jax itself — factories
own the allocation, the pool owns identity, reuse and accounting — so
it is equally happy pooling pinned host staging buffers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

DEFAULT_POOL_MB = 256


def _cap_bytes() -> int:
    """Retention cap, re-read per operation (tests and daemons flip the
    knob without re-importing)."""
    mb = os.environ.get("WEED_EC_DEVICE_POOL_MB", "")
    try:
        return int(float(mb) * (1 << 20)) if mb else DEFAULT_POOL_MB << 20
    except ValueError:
        return DEFAULT_POOL_MB << 20


class Lease:
    """One leased slab: `payload` is whatever the factory built (numpy
    staging buffer or jax device array).  Callers may swap `payload`
    while holding the lease (donation returns a new handle aliasing the
    same device memory); the swap travels back into the pool on
    release.  `device` is the placement label the slab was leased for —
    part of the free-list identity, so a slab leased for one device is
    never handed to a caller staging for another."""

    __slots__ = ("key", "payload", "nbytes", "device")

    def __init__(self, key, payload, nbytes: int, device=None):
        self.key = key
        self.payload = payload
        self.nbytes = nbytes
        self.device = device


class _Resident:
    __slots__ = ("key", "payload", "nbytes", "refs", "last_used")

    def __init__(self, key, payload, nbytes: int):
        self.key = key
        self.payload = payload
        self.nbytes = nbytes
        self.refs = 0
        self.last_used = 0.0


class DevicePool:
    def __init__(self):
        self._lock = threading.Lock()
        self._free: dict[Any, list[Lease]] = {}   # key -> idle leases
        self._free_order: list[Lease] = []        # LRU over idle leases
        self._residents: dict[Any, _Resident] = {}
        self._leased_bytes = 0
        self._free_bytes = 0
        self._resident_bytes = 0
        self._leased_count = 0
        # counters (monotonic; mirrored into Prometheus vectors)
        self.allocs = 0
        self.lease_hits = 0
        self.resident_hits = 0
        self.resident_misses = 0
        self.evictions = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        # per-device breakdowns (device label -> bytes): slab residency
        # from the lease accounting, link traffic from note_h2d/note_d2h
        self._dev_bytes: dict[str, int] = {}
        self._dev_h2d: dict[str, int] = {}
        self._dev_d2h: dict[str, int] = {}
        self._evictions_published = 0
        # HBM occupancy telemetry: peak bytes ever held, plus wall time
        # accrued while occupancy sat at >=95% of that peak (a pool
        # pinned at its watermark is the signal to raise
        # WEED_EC_DEVICE_POOL_MB or shrink the batch geometry)
        self._hwm_bytes = 0
        self._hwm_seconds = 0.0
        self._occ_ts = time.monotonic()
        self._occ_bytes = 0

    # -- transfer/compute slots ---------------------------------------

    @staticmethod
    def _dev_label(device) -> str:
        return "host" if device is None else str(device)

    def lease(self, key, factory: Callable[[], Any], nbytes: int,
              device=None) -> Lease:
        """A slab for `(key, device)`: a previously released one, else
        `factory()`.  The factory runs outside the lock (jax allocation
        can be slow and reentrant).  `device` is part of the free-list
        identity: two callers leasing the same geometry for different
        devices never alias slabs (a payload materialized on device A
        handed to a dispatch against device B would silently re-upload
        — or worse, compute against stale memory)."""
        bucket_key = (key, self._dev_label(device))
        with self._lock:
            bucket = self._free.get(bucket_key)
            if bucket:
                ls = bucket.pop()
                self._free_order.remove(ls)
                self._free_bytes -= ls.nbytes
                self._leased_bytes += ls.nbytes
                self._leased_count += 1
                self.lease_hits += 1
                self._publish()
                return ls
        payload = factory()
        ls = Lease(bucket_key, payload, nbytes, self._dev_label(device))
        with self._lock:
            self.allocs += 1
            self._leased_bytes += nbytes
            self._dev_bytes[ls.device] = \
                self._dev_bytes.get(ls.device, 0) + nbytes
            self._leased_count += 1
            self._publish()
        return ls

    def release(self, lease: Lease):
        with self._lock:
            self._leased_bytes -= lease.nbytes
            self._leased_count -= 1
            self._free.setdefault(lease.key, []).append(lease)
            self._free_order.append(lease)
            self._free_bytes += lease.nbytes
            self._evict_locked()
            self._publish()

    def discard(self, lease: Lease):
        """Release without retaining (the slab's geometry won't recur)."""
        with self._lock:
            self._leased_bytes -= lease.nbytes
            self._leased_count -= 1
            self._drop_dev_bytes_locked(lease)
            self._publish()

    def _drop_dev_bytes_locked(self, lease: Lease):
        dev = getattr(lease, "device", None) or "host"
        left = self._dev_bytes.get(dev, 0) - lease.nbytes
        if left > 0:
            self._dev_bytes[dev] = left
        else:
            self._dev_bytes.pop(dev, None)

    # -- ref-counted resident content slabs ---------------------------

    def acquire_resident(self, key, factory: Callable[[], Any],
                         nbytes: int) -> Any:
        """The device-resident payload for `key`, uploading via
        `factory()` on miss.  Pairs with `release_resident`; the slab
        survives refs == 0 (that is the point — the NEXT degraded read
        against the same survivor set skips the upload) until the byte
        cap evicts it."""
        with self._lock:
            res = self._residents.get(key)
            if res is not None:
                res.refs += 1
                res.last_used = time.monotonic()
                self.resident_hits += 1
                self._publish()
                return res.payload
        payload = factory()
        with self._lock:
            res = self._residents.get(key)
            if res is None:  # single writer wins; duplicates discarded
                res = _Resident(key, payload, nbytes)
                self._residents[key] = res
                self._resident_bytes += nbytes
                self.resident_misses += 1
                self.allocs += 1
            else:
                self.resident_hits += 1
            res.refs += 1
            res.last_used = time.monotonic()
            self._evict_locked()
            self._publish()
            return res.payload

    def release_resident(self, key):
        with self._lock:
            res = self._residents.get(key)
            if res is not None and res.refs > 0:
                res.refs -= 1
            self._publish()

    # -- eviction / accounting ----------------------------------------

    def _evict_locked(self):
        """Drop idle bytes (free leases first, then refs==0 residents,
        LRU) until under the cap."""
        cap = _cap_bytes()

        def idle():
            return self._free_bytes + sum(
                r.nbytes for r in self._residents.values() if r.refs == 0)

        while self._free_order and idle() > cap:
            ls = self._free_order.pop(0)
            self._free[ls.key].remove(ls)
            if not self._free[ls.key]:
                del self._free[ls.key]
            self._free_bytes -= ls.nbytes
            self._drop_dev_bytes_locked(ls)
            self.evictions += 1
        while idle() > cap:
            victims = sorted(
                (r for r in self._residents.values() if r.refs == 0),
                key=lambda r: r.last_used)
            if not victims:
                break
            v = victims[0]
            del self._residents[v.key]
            self._resident_bytes -= v.nbytes
            self.evictions += 1

    def note_h2d(self, nbytes: int, device=None):
        dev = self._dev_label(device)
        with self._lock:
            self.h2d_bytes += nbytes
            self._dev_h2d[dev] = self._dev_h2d.get(dev, 0) + nbytes
        from ..stats import metrics as stats
        stats.EcDeviceH2dBytesCounter.labels(dev).inc(nbytes)

    def note_d2h(self, nbytes: int, device=None):
        dev = self._dev_label(device)
        with self._lock:
            self.d2h_bytes += nbytes
            self._dev_d2h[dev] = self._dev_d2h.get(dev, 0) + nbytes
        from ..stats import metrics as stats
        stats.EcDeviceD2hBytesCounter.labels(dev).inc(nbytes)

    def _note_occupancy_locked(self):
        """Advance the watermark clock (lock held).  Time since the last
        byte mutation is attributed to the PREVIOUS occupancy level, so
        `hwm_seconds` is exact piecewise accounting, not sampling."""
        now = time.monotonic()
        if self._hwm_bytes > 0 and \
                self._occ_bytes >= 0.95 * self._hwm_bytes:
            self._hwm_seconds += now - self._occ_ts
        self._occ_ts = now
        self._occ_bytes = (self._free_bytes + self._leased_bytes
                           + self._resident_bytes)
        if self._occ_bytes > self._hwm_bytes:
            self._hwm_bytes = self._occ_bytes

    def _publish(self):
        """Mirror state into the Prometheus vectors (lock held: the
        registry's own primitives are lock-free enough)."""
        self._note_occupancy_locked()
        try:
            from ..stats import metrics as stats
        except Exception:  # pragma: no cover - import cycles at teardown
            return
        stats.DevicePoolHwmBytesGauge.set(self._hwm_bytes)
        stats.DevicePoolHwmSecondsGauge.set(self._hwm_seconds)
        for dev, nbytes in self._dev_bytes.items():
            stats.DevicePoolDeviceBytesGauge.labels(dev).set(nbytes)
        stats.DevicePoolSlotsGauge.labels("free").set(
            len(self._free_order))
        stats.DevicePoolSlotsGauge.labels("leased").set(self._leased_count)
        stats.DevicePoolSlotsGauge.labels("resident").set(
            len(self._residents))
        stats.DevicePoolBytesGauge.set(
            self._free_bytes + self._leased_bytes + self._resident_bytes)
        if self.evictions > self._evictions_published:
            stats.DevicePoolEvictionsCounter.inc(
                self.evictions - self._evictions_published)
            self._evictions_published = self.evictions

    def snapshot(self) -> dict:
        # the QoS device lanes gate dispatch INTO this pool's slots, so
        # their state belongs in the same observability snapshot
        from ..qos.lanes import LANES

        with self._lock:
            self._note_occupancy_locked()
            return {
                "hwm_bytes": self._hwm_bytes,
                "hwm_seconds": round(self._hwm_seconds, 3),
                "free_slots": len(self._free_order),
                "leased_slots": self._leased_count,
                "resident_slabs": len(self._residents),
                "bytes": self._free_bytes + self._leased_bytes
                + self._resident_bytes,
                "allocs": self.allocs,
                "lease_hits": self.lease_hits,
                "resident_hits": self.resident_hits,
                "resident_misses": self.resident_misses,
                "evictions": self.evictions,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "devices": {
                    dev: {
                        "bytes": self._dev_bytes.get(dev, 0),
                        "h2d_bytes": self._dev_h2d.get(dev, 0),
                        "d2h_bytes": self._dev_d2h.get(dev, 0),
                    }
                    for dev in sorted(set(self._dev_bytes)
                                      | set(self._dev_h2d)
                                      | set(self._dev_d2h))
                },
                "lanes": LANES.snapshot(),
            }

    def clear(self):
        with self._lock:
            for ls in self._free_order:
                self._drop_dev_bytes_locked(ls)
            self._free.clear()
            self._free_order.clear()
            self._residents.clear()
            self._free_bytes = self._resident_bytes = 0
            self._publish()


_pool: Optional[DevicePool] = None
_pool_lock = threading.Lock()


def get_pool() -> DevicePool:
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = DevicePool()
    return _pool


def reset_pool():
    """Drop the process pool (tests; frees any retained device memory)."""
    global _pool
    with _pool_lock:
        _pool = None
