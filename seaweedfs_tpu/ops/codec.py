"""Backend-selectable Reed-Solomon codec — the `reedsolomon.Encoder` seam.

The reference's storage engine calls exactly three codec methods
(Encode / Reconstruct / ReconstructData; SURVEY.md §2) behind
`reedsolomon.New(10, 4)`.  `new_encoder(...)` is the equivalent factory,
selected by backend the way the north-star design selects `-ec.backend=tpu`:

  * "tpu"   — JAX kernels (Pallas MXU on TPU, SWAR on CPU), rs_jax.py
  * "cpu"   — native AVX2 C++ (klauspost-equivalent), this module
  * "numpy" — pure NumPy reference, rs_numpy.py
  * "auto"  — tpu when a TPU is attached, else cpu-native, else numpy
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from . import native
from ..util.platform import on_tpu
from .rs_numpy import (NumpyEncoder, ReconstructError,  # noqa: F401
                       RSCodecBase, decode_plan_cache_info, decode_rows,
                       gf_apply_matrix)


class NativeEncoder(RSCodecBase):
    """CPU codec backed by the C++ kernel ladder in native/ec_native.cpp
    (GFNI+AVX-512 > GFNI+AVX2 > AVX2-PSHUFB > scalar, runtime-dispatched).

    `level` pins a specific kernel (bench baselines): 1 = the AVX2 PSHUFB
    nibble-table kernel, the same algorithm class as the klauspost codec
    the reference vendors; -1 (default) = best available."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 level: int = -1):
        super().__init__(data_shards, parity_shards)
        self._lib = native.lib()
        self._level = level
        if self._lib is None:
            raise RuntimeError("native library unavailable")

    def _apply(self, matrix: np.ndarray, inputs: np.ndarray) -> np.ndarray:
        p, d = matrix.shape
        length = inputs.shape[1]
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
        out = np.zeros((p, length), dtype=np.uint8)
        self._lib.sw_gf_apply_matrix_force(
            matrix.ctypes.data_as(ctypes.c_char_p), p, d,
            inputs.ctypes.data_as(ctypes.c_char_p), length,
            out.ctypes.data_as(ctypes.c_char_p), self._level,
        )
        return out

    def encode_rows(self, parity_matrix: np.ndarray, data: np.ndarray,
                    parity_out: np.ndarray) -> list[int]:
        """Fused span encode: data (R, d, L) -> parity_out (R, p, L), one
        ctypes call; returns per-shard CRC32Cs chained across the R rows
        (= the rolling file CRC of the span's L*R-byte shard slice).

        Buffer ownership contract: the CALLER owns both buffers, and the
        kernel only touches them for the duration of this call — `data`
        is read-only, `parity_out` is fully overwritten before return.
        Nothing is retained, so a write-behind pipeline may hand either
        buffer to another thread (or recycle it through a slot pool) the
        moment this returns; conversely neither buffer may be mutated BY
        other threads while the call is in flight.  All three arrays
        must be C-contiguous uint8 — the kernel walks raw pointers with
        row strides computed from the shapes."""
        for name, arr in (("parity_matrix", parity_matrix),
                          ("data", data), ("parity_out", parity_out)):
            if arr.dtype != np.uint8 or not arr.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    f"encode_rows: {name} must be C-contiguous uint8 "
                    f"(got dtype={arr.dtype}, "
                    f"contiguous={arr.flags['C_CONTIGUOUS']})")
        p, d = parity_matrix.shape
        rows, _, length = data.shape
        if parity_out.shape != (rows, p, length):
            raise ValueError(
                f"encode_rows: parity_out shape {parity_out.shape} != "
                f"{(rows, p, length)}")
        crcs = (ctypes.c_uint32 * (d + p))()
        self._lib.sw_encode_rows(
            parity_matrix.ctypes.data_as(ctypes.c_char_p), p, d,
            data.ctypes.data_as(ctypes.c_char_p), length, rows,
            parity_out.ctypes.data_as(ctypes.c_char_p), crcs,
        )
        return list(crcs)


# Spans below this stay on the host codec: a device dispatch + two link
# round-trips cost more than the mat-vec itself for small recoveries.
_RECOVER_DEVICE_MIN_BYTES = int(
    os.environ.get("WEED_EC_RECOVER_DEVICE_MIN_KB", "512") or 0) << 10


def recover_device_min_bytes() -> int:
    """WEED_EC_RECOVER_DEVICE_MIN_KB re-read per call (daemons and tests
    flip it without reimporting); import-time value is the fallback."""
    kb = os.environ.get("WEED_EC_RECOVER_DEVICE_MIN_KB", "")
    if not kb:
        return _RECOVER_DEVICE_MIN_BYTES
    try:
        return int(kb) << 10
    except ValueError:
        return _RECOVER_DEVICE_MIN_BYTES


def recover_device_enabled() -> bool:
    """Whether reconstruct_span may dispatch to a device kernel.
    WEED_EC_RECOVER_DEVICE: unset/"auto" -> only on a real TPU; "1"
    forces it on (any jax backend — the CPU mesh harness and tests);
    "0" disables."""
    v = os.environ.get("WEED_EC_RECOVER_DEVICE", "auto").lower()
    if v in ("1", "true", "yes", "force"):
        return True
    if v in ("0", "false", "no"):
        return False
    return on_tpu()


def _apply_rows_host(rows: np.ndarray, inputs: np.ndarray) -> np.ndarray:
    """(t, d) decode rows x (d, L) survivor spans on the best host
    backend: the native kernel ladder when built, else NumPy tables."""
    lib = native.lib()
    if lib is None:
        return gf_apply_matrix(rows, inputs)
    t, d = rows.shape
    length = inputs.shape[1]
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
    out = np.zeros((t, length), dtype=np.uint8)
    lib.sw_gf_apply_matrix(
        rows.ctypes.data_as(ctypes.c_char_p), t, d,
        inputs.ctypes.data_as(ctypes.c_char_p), length,
        out.ctypes.data_as(ctypes.c_char_p))
    return out


def reconstruct_span(survivors, inputs: np.ndarray, target: int,
                     data_shards: int = 10,
                     total_shards: int = 14,
                     slab_key=None, family=None) -> np.ndarray:
    """Target-row reconstruction: rebuild ONE shard's span from the
    (d, L) survivor stack via the cached decode plan — one GF mat-vec,
    never a full Reconstruct.  `inputs[i]` must be the span read from
    `survivors[i]`.  L may be many coalesced spans laid end to end (the
    batched multi-span decode): the math is column-wise, so stacking is
    free.  Dispatch: fused JAX/Pallas kernel for large spans on a TPU,
    native/NumPy host kernel for small ones.

    slab_key: opaque content identity of `inputs` (the caller hashes the
    survivor stack).  When set, the device upload routes through the EC
    device slab pool (ops/device_pool.py) keyed by (survivors, content):
    consecutive decodes against the same survivor spans — a different
    missing target, or a block re-recovered after LRU eviction — hit the
    HBM-resident slab instead of re-uploading over the link.

    family: an erasure_coding.codes CodeFamily.  None (or the RS default)
    keeps the classic (total, data) path; other families supply their own
    cached decode plan (each family's cheap inversion), and vector codes
    (sub_shards > 1) run the same kernels over the lane-interleaved view
    of the survivor stack."""
    fam_name = getattr(family, "name", None)
    if family is not None and fam_name != "rs_vandermonde":
        rows = family.decode_rows(tuple(survivors), (target,))
        to_dev = family.to_lanes(np.ascontiguousarray(inputs))
        out_rows = len(rows)
    else:
        family = None
        rows = decode_rows(data_shards, total_shards, survivors, (target,))
        to_dev = inputs
        out_rows = 1

    def _finish(out: np.ndarray) -> np.ndarray:
        return out[0] if family is None else family.from_lanes(out)[0]

    if inputs.nbytes >= recover_device_min_bytes() \
            and recover_device_enabled():
        try:
            import jax.numpy as jnp

            from .device_pool import get_pool
            from .rs_jax import apply_matrix

            method = "pallas" if on_tpu() else "swar"
            if slab_key is not None:
                import jax

                pool = get_pool()
                # survivor slabs upload to the default device; labeling
                # the transfers/residency keeps the recover traffic
                # distinguishable from the sharded encode meshes'
                dev_label = str(jax.devices()[0])
                key = ("recover", fam_name, tuple(survivors), slab_key)

                def _upload():
                    dev = jnp.asarray(to_dev)
                    pool.note_h2d(to_dev.nbytes, device=dev_label)
                    return dev

                dev_in = pool.acquire_resident(key, _upload,
                                               to_dev.nbytes)
                try:
                    out = np.asarray(apply_matrix(
                        np.asarray(rows), dev_in,
                        method=method))[:out_rows]
                finally:
                    pool.release_resident(key)
                pool.note_d2h(out.nbytes, device=dev_label)
                return _finish(out)
            return _finish(np.asarray(apply_matrix(
                np.asarray(rows), to_dev, method=method))[:out_rows])
        except Exception:
            pass  # device hiccup mid-incident: the host path always works
    return _finish(_apply_rows_host(rows, to_dev)[:out_rows])


def new_host_encoder(data_shards: int = 10, parity_shards: int = 4):
    """Best HOST codec (native AVX2/SSE, else numpy) — never a device
    backend.  The link-throughput auto-selection falls back to this when
    the host<->device link would cap the device path below the host
    rate; resolving "auto" there would pick the device codec again."""
    if native.lib() is not None:
        return NativeEncoder(data_shards, parity_shards)
    return NumpyEncoder(data_shards, parity_shards)


def new_encoder(data_shards: int = 10, parity_shards: int = 4,
                backend: str = "auto"):
    if backend == "auto":
        if on_tpu():
            backend = "tpu"
        elif native.lib() is not None:
            backend = "cpu"
        else:
            backend = "numpy"
    if backend in ("tpu", "jax"):
        from .rs_jax import JaxEncoder

        method = "pallas" if backend == "tpu" and on_tpu() else "swar"
        return JaxEncoder(data_shards, parity_shards, method=method)
    if backend == "cpu":
        return NativeEncoder(data_shards, parity_shards)
    if backend == "numpy":
        return NumpyEncoder(data_shards, parity_shards)
    raise ValueError(f"unknown backend {backend!r}")
