"""Per-collection byte/ops quotas, enforced at master assign and S3 PUT.

WEED_QOS_QUOTA is a comma-separated spec of
``<collection>=<ops>ops[+<mb>mb]`` entries; ``*`` matches any
collection without its own entry:

    WEED_QOS_QUOTA="photos=200ops+64mb,logs=50ops,*=1000ops"

Ops quotas meter assigns (master) and object PUTs (S3); byte quotas
meter uploaded bytes at S3 PUT.  Both are token buckets with a burst
of one second's allowance (bursts scale with the rate), refilled on the
injectable clock so tests stay deterministic.  A drained bucket sheds
with 503 + jittered Retry-After (master) or SlowDown (S3).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from ..stats import metrics as _stats
from . import shm as _shm
from .admission import TokenBucket


def _parse_spec(spec: str) -> Dict[str, Tuple[float, float]]:
    """``{collection: (ops_per_s, bytes_per_s)}``; 0 = unlimited."""
    out: Dict[str, Tuple[float, float]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, limits = part.partition("=")
        ops = byts = 0.0
        for tok in limits.split("+"):
            tok = tok.strip().lower()
            try:
                if tok.endswith("ops"):
                    ops = float(tok[:-3])
                elif tok.endswith("mb"):
                    byts = float(tok[:-2]) * (1 << 20)
            except ValueError:
                pass
        out[name.strip()] = (ops, byts)
    return out


class CollectionQuotas:
    """Lazily-built buckets per (collection, kind), re-parsing the spec
    only when the env knob changes (live knob, near-zero steady cost)."""

    def __init__(self, now=time.monotonic):
        self.now = now
        self._lock = threading.Lock()
        self._spec_raw: Optional[str] = None
        self._spec: Dict[str, Tuple[float, float]] = {}
        self._buckets: Dict[Tuple[str, str], TokenBucket] = {}
        self.rejects = {"ops": 0, "bytes": 0}

    def _limits_for(self, collection: str) -> Tuple[float, float]:
        raw = os.environ.get("WEED_QOS_QUOTA", "")
        if raw != self._spec_raw:
            self._spec_raw = raw
            self._spec = _parse_spec(raw)
            self._buckets.clear()
        return self._spec.get(collection, self._spec.get("*", (0.0, 0.0)))

    def allow(self, collection: str, ops: float = 1.0,
              nbytes: float = 0.0) -> bool:
        """Charge one operation (and its bytes) against the collection's
        quota; False means shed."""
        with self._lock:
            ops_rate, byte_rate = self._limits_for(collection or "")
            if ops_rate > 0 and ops > 0:
                if not self._take(collection, "ops", ops_rate, ops):
                    self.rejects["ops"] += 1
                    _stats.QosQuotaRejectsCounter.labels("ops").inc()
                    return False
            if byte_rate > 0 and nbytes > 0:
                if not self._take(collection, "bytes", byte_rate,
                                  nbytes):
                    self.rejects["bytes"] += 1
                    _stats.QosQuotaRejectsCounter.labels("bytes").inc()
                    return False
        return True

    def _take(self, collection: str, kind: str, rate: float,
              n: float) -> bool:
        s = _shm.ACTIVE
        if s is not None:
            # prefork: one shared bucket per (collection, kind), so the
            # quota bounds the fleet rather than each worker
            return s.tenant_take(f"q:{collection}:{kind}", rate,
                                 max(rate, 1.0), n)
        return self._bucket(collection, kind, rate).try_take(n)

    def _bucket(self, collection: str, kind: str,
                rate: float) -> TokenBucket:
        key = (collection, kind)
        b = self._buckets.get(key)
        if b is None or b.rate != rate:
            b = TokenBucket(rate, burst=rate, now=self.now)
            self._buckets[key] = b
        return b

    def snapshot(self) -> dict:
        with self._lock:
            self._limits_for("")  # refresh the parsed spec
            return {"spec": {k: {"ops_per_s": v[0],
                                 "bytes_per_s": v[1]}
                             for k, v in self._spec.items()},
                    "rejects": dict(self.rejects),
                    "collections_metered":
                        len({c for c, _ in self._buckets})}


# process-wide singleton, shared by master assign and the s3 gateway
QUOTAS = CollectionQuotas()
