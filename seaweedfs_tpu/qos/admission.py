"""Weighted-fair admission control for the daemon front ends.

Replaces the flat ``WEED_VS_MAX_INFLIGHT`` shed gate with per-class
bounded queues drained by deficit-round-robin, per-tenant token
buckets, and class-aware load shedding:

* every request is admitted immediately while in-flight work is under
  the limit; beyond it, waiters park in their class queue and a DRR
  scheduler (quantum = class weight) picks the next one on each
  release — interactive drains ~weights[interactive] requests for
  every one background request under full backlog;
* queues are bounded per class, and classes additionally shed at a
  total-occupancy watermark — background sheds first (50 % of total
  queue capacity), standard at 85 %, interactive only when its own
  queue is full;
* per-tenant token buckets (WEED_QOS_TENANT_RPS/_BURST) bound any one
  access key / collection before it reaches the queues.

All time flows through injectable ``now`` seams (the repo's fake-clock
convention from rpc/policy.py), so the scheduler and buckets are
deterministic under test with zero sleeps.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..stats import metrics as _stats
from . import classify
from . import shm as _shm
from .classify import BACKGROUND, CLASSES, INTERACTIVE, STANDARD


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class TokenBucket:
    """Classic token bucket with an injectable clock.  ``rate <= 0``
    means unlimited (every take succeeds)."""

    __slots__ = ("rate", "burst", "tokens", "t_last", "denied", "taken",
                 "now")

    def __init__(self, rate: float, burst: float,
                 now=time.monotonic):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.t_last: Optional[float] = None
        self.denied = 0
        self.taken = 0
        self.now = now

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            self.taken += 1
            return True
        t = self.now()
        if self.t_last is None:
            self.t_last = t
        self.tokens = min(self.burst,
                          self.tokens + (t - self.t_last) * self.rate)
        self.t_last = t
        if self.tokens >= n:
            self.tokens -= n
            self.taken += 1
            return True
        self.denied += 1
        return False


class TenantBuckets:
    """Lazily-created per-tenant buckets, bounded to the most recently
    seen ``cap`` tenants so an access-key scan can't grow the map
    unboundedly."""

    def __init__(self, rate_env: str = "WEED_QOS_TENANT_RPS",
                 burst_env: str = "WEED_QOS_TENANT_BURST",
                 cap: int = 1024, now=time.monotonic):
        self.rate_env = rate_env
        self.burst_env = burst_env
        self.cap = cap
        self.now = now
        self._buckets: "Dict[str, TokenBucket]" = {}
        self._lock = threading.Lock()

    def try_take(self, tenant: str, n: float = 1.0) -> bool:
        if not tenant:
            return True  # unattributed traffic is bounded by the queues
        rate = _env_float(self.rate_env, 0.0)
        if rate <= 0:
            return True
        s = _shm.ACTIVE
        if s is not None:
            # fleet-wide bucket: every prefork worker draws from one
            # shared-memory slot, so the rate stays per-tenant rather
            # than silently becoming per-tenant-per-worker
            return s.tenant_take(
                "t:" + tenant, rate,
                _env_float(self.burst_env, max(rate, 1.0)), n)
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                if len(self._buckets) >= self.cap:
                    self._buckets.pop(next(iter(self._buckets)))
                b = TokenBucket(rate, _env_float(self.burst_env,
                                                 max(rate, 1.0)),
                                now=self.now)
                self._buckets[tenant] = b
            b.rate = rate  # live knob: tests flip it mid-process
            return b.try_take(n)

    def snapshot(self) -> dict:
        with self._lock:
            return {"tenants": len(self._buckets),
                    "denied": sum(b.denied
                                  for b in self._buckets.values()),
                    "taken": sum(b.taken
                                 for b in self._buckets.values())}


def class_weights() -> Dict[str, int]:
    """WEED_QOS_WEIGHTS="interactive=8,standard=3,background=1" —
    weights clamp to >= 1 so every class stays work-conserving."""
    weights = {INTERACTIVE: 8, STANDARD: 3, BACKGROUND: 1}
    spec = os.environ.get("WEED_QOS_WEIGHTS", "")
    for part in spec.split(",") if spec else ():
        k, _, v = part.partition("=")
        k = k.strip()
        if k in weights:
            try:
                weights[k] = max(1, int(v))
            except ValueError:
                pass
    return weights


class _ShmDeficit:
    """Mapping view over one service's shared DRR deficit slots.
    Caller holds that service's cross-process drr lock for the whole
    pop."""

    __slots__ = ("_s", "_svc")

    def __init__(self, s: "_shm.QosShm", service: str = ""):
        self._s = s
        self._svc = service

    def __getitem__(self, cls: str) -> float:
        return self._s.drr_get(cls, service=self._svc)

    def __setitem__(self, cls: str, value: float):
        self._s.drr_set(cls, value, service=self._svc)


class DrrQueue:
    """Deficit-round-robin over the per-class waiter queues.  Unit-cost
    items; each visit to a backlogged class tops its deficit up by the
    class quantum (= weight) and drains while the deficit lasts.  Not
    thread-safe — the owning gate serializes access under its lock."""

    def __init__(self, weights: Optional[Dict[str, int]] = None,
                 service: str = ""):
        self.queues: Dict[str, deque] = {c: deque() for c in CLASSES}
        self.weights = dict(weights) if weights else class_weights()
        self.deficit: Dict[str, float] = {c: 0.0 for c in CLASSES}
        self.service = service  # selects this queue's shared DRR slots
        self._i = 0

    def push(self, cls: str, item) -> None:
        self.queues[cls].append(item)

    def __len__(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def depth(self, cls: str) -> int:
        return len(self.queues[cls])

    def pop(self):
        """Next item under DRR, or None when all queues are empty."""
        if not len(self):
            return None
        s = _shm.ACTIVE
        if s is None or s.service_index(self.service) < 0:
            return self._pop_from(self.deficit)
        # prefork: deficits live in shared memory (per service, so
        # combined daemons don't cross-couple) and weight fidelity
        # holds across the whole worker fleet, not per process
        with s.drr_lock(self.service):
            return self._pop_from(_ShmDeficit(s, self.service))

    def _pop_from(self, deficit):
        n = len(CLASSES)
        # weights >= 1 guarantee a backlogged class dispatches on its
        # visit, so two passes always yield an item
        for _ in range(2 * n):
            cls = CLASSES[self._i % n]
            q = self.queues[cls]
            if not q:
                # an idle class must not bank deficit for later bursts
                deficit[cls] = 0.0
                self._i += 1
                continue
            if deficit[cls] < 1.0:
                deficit[cls] = deficit[cls] + self.weights.get(cls, 1)
            deficit[cls] = deficit[cls] - 1.0
            item = q.popleft()
            if not q:
                deficit[cls] = 0.0
                self._i += 1
            elif deficit[cls] < 1.0:
                self._i += 1
            return item
        return None  # unreachable with weights >= 1


class _Waiter:
    __slots__ = ("cls", "event", "cancelled")

    def __init__(self, cls: str):
        self.cls = cls
        self.event = threading.Event()
        self.cancelled = False


class _Release:
    """Idempotent release handle so a ``finally: release()`` racing an
    exception path can't double-free an admission slot."""

    __slots__ = ("_gate", "_cls", "_done")

    def __init__(self, gate: "AdmissionGate", cls: str):
        self._gate = gate
        self._cls = cls
        self._done = False

    def __call__(self):
        if not self._done:
            self._done = True
            if self._gate is not None:
                self._gate._release(self._cls)


_NOOP_RELEASE = _Release(None, STANDARD)
_NOOP_RELEASE._done = True

# shed watermarks: fraction of TOTAL queue capacity at which a class
# stops queuing — background gives way first, interactive last
_SHED_WATERMARK = {BACKGROUND: 0.50, STANDARD: 0.85, INTERACTIVE: 1.01}

_QUEUE_ENV = {INTERACTIVE: ("WEED_QOS_QUEUE_INTERACTIVE", 64),
              STANDARD: ("WEED_QOS_QUEUE_STANDARD", 32),
              BACKGROUND: ("WEED_QOS_QUEUE_BACKGROUND", 8)}

class AdmissionGate:
    """Per-daemon front-end admission: weighted-fair queues over a
    bounded in-flight limit.

    ``limit_env`` is read live on every admit (tests flip it
    mid-process); ``fallback_env`` names the deprecated flat knob
    (``WEED_VS_MAX_INFLIGHT``) honored when the new one is unset.
    Limit <= 0 disables queuing entirely — the gate still classifies
    and counts, so /debug/qos and the pacer signal stay live."""

    def __init__(self, service: str, limit_env: str = "",
                 fallback_env: str = "", default_limit: int = 0,
                 now=time.monotonic):
        self.service = service
        self.limit_env = limit_env
        self.fallback_env = fallback_env
        self.default_limit = int(default_limit)
        self.now = now
        self._lock = threading.Lock()
        self._drr = DrrQueue(service=service)
        self.inflight: Dict[str, int] = {c: 0 for c in CLASSES}
        self.admitted: Dict[str, int] = {c: 0 for c in CLASSES}
        self.queued: Dict[str, int] = {c: 0 for c in CLASSES}
        self.shed: Dict[str, int] = {c: 0 for c in CLASSES}
        self.tenants = TenantBuckets(now=now)

    # -- knobs (live reads) ---------------------------------------------------
    def effective_limit(self) -> int:
        for env in (self.limit_env, self.fallback_env):
            if env:
                raw = os.environ.get(env)
                if raw is not None and raw != "":
                    try:
                        return int(raw)
                    except ValueError:
                        pass
        return self.default_limit

    def queue_cap(self, cls: str) -> int:
        env, default = _QUEUE_ENV[cls]
        return max(0, _env_int(env, default))

    def total_queue_cap(self) -> int:
        return sum(self.queue_cap(c) for c in CLASSES)

    # -- admission ------------------------------------------------------------
    def admit(self, cls: Optional[str] = None, tenant: Optional[str] = None,
              wait: bool = True):
        """Admit one request; returns a release callable.  Raises
        RpcError 503 (with a jittered Retry-After) when shed."""
        # deferred: rpc.http_rpc imports this package for header
        # propagation, so the dependency must stay one-way at load time
        from ..rpc.http_rpc import RpcError, current_deadline

        cls = classify.normalize(cls if cls is not None
                                 else classify.current_class())
        if tenant is None:
            tenant = classify.current_tenant()
        if not self.tenants.try_take(tenant):
            self.shed[cls] += 1
            self._mirror(cls)
            _stats.QosTenantThrottledCounter.labels(self.service,
                                                    cls).inc()
            self._count(cls, "shed_tenant")
            raise RpcError(
                f"tenant {tenant!r} over its {cls} request rate", 429,
                headers={"Retry-After": classify.retry_after(1, 3)})
        limit = self.effective_limit()
        if limit <= 0:
            self.admitted[cls] += 1
            self._mirror(cls)
            self._count(cls, "admit")
            return _NOOP_RELEASE
        waiter = None
        with self._lock:
            if self.total_inflight() < limit and not len(self._drr):
                self.inflight[cls] += 1
                self.admitted[cls] += 1
            else:
                waiter = self._try_enqueue(cls, wait)
        if waiter is None:
            self._count(cls, "admit")
            self._gauges(cls)
            return _Release(self, cls)
        # parked: wait for a release to dispatch us (bounded by the
        # queue timeout and any propagated deadline)
        t0 = self.now()
        timeout = _env_float("WEED_QOS_QUEUE_TIMEOUT", 5.0)
        dl = current_deadline()
        if dl is not None:
            timeout = max(0.0, min(timeout, dl - time.time()))
        dispatched = waiter.event.wait(timeout)
        _stats.QosQueueWaitHistogram.labels(cls).observe(
            max(0.0, self.now() - t0))
        if dispatched:
            self.admitted[cls] += 1
            self._count(cls, "admit")
            self._gauges(cls)
            return _Release(self, cls)
        with self._lock:
            if waiter.event.is_set():
                # dispatch raced the timeout: the slot is ours after all
                self.admitted[cls] += 1
            else:
                waiter.cancelled = True
                self.queued[cls] -= 1
                waiter = None
        if waiter is not None:
            self._count(cls, "admit")
            self._gauges(cls)
            return _Release(self, cls)
        self.shed[cls] += 1
        self._count(cls, "shed_timeout")
        self._gauges(cls)
        raise RpcError(
            f"{self.service} {cls} queue wait exceeded", 503,
            headers={"Retry-After": classify.retry_after(1, 3)})

    def _try_enqueue(self, cls: str, wait: bool):
        """Under self._lock: park a waiter, or raise the shed error."""
        from ..rpc.http_rpc import RpcError

        cap = self.queue_cap(cls)
        total = len(self._drr)
        watermark = _SHED_WATERMARK[cls] * self.total_queue_cap()
        if (not wait or self._drr.depth(cls) >= cap
                or total >= watermark):
            self.shed[cls] += 1
            self._count(cls, "shed_queue")
            self._gauges(cls)
            raise RpcError(
                f"{self.service} overloaded: {cls} queue full", 503,
                headers={"Retry-After": classify.retry_after(1, 3)})
        waiter = _Waiter(cls)
        self._drr.push(cls, waiter)
        self.queued[cls] += 1
        self._mirror(cls)
        self._count(cls, "queued")
        return waiter

    def _release(self, cls: str):
        with self._lock:
            self.inflight[cls] = max(0, self.inflight[cls] - 1)
            self._dispatch_locked()
        self._gauges(cls)

    def _dispatch_locked(self):
        limit = self.effective_limit()
        while self.total_inflight() < limit:
            w = self._drr.pop()
            if w is None:
                return
            if w.cancelled:
                continue
            self.queued[w.cls] -= 1
            self.inflight[w.cls] += 1
            self._mirror(w.cls)
            w.event.set()

    def _mirror(self, cls: str):
        """Publish this gate's counters for `cls` to its own
        (service, worker) row — single writer, so no lock.  Rows are
        partitioned by service so the gates of a combined daemon
        (weed.py server) never clobber each other, and each gate's
        limit is enforced against its OWN service's fleet sum rather
        than the cross-service total."""
        s = _shm.ACTIVE
        if s is None:
            return
        for field in ("inflight", "queued", "admitted", "shed"):
            s.gate_set(self.service, cls, field,
                       getattr(self, field).get(cls, 0))

    def _fleet_total(self, field: str, local: Dict[str, int]) -> int:
        s = _shm.ACTIVE
        if s is not None and s.service_index(self.service) >= 0:
            return s.gate_total(field, service=self.service)
        return sum(local.values())

    # -- introspection --------------------------------------------------------
    def total_inflight(self) -> int:
        """This service's fleet-wide in-flight when the shared segment
        is active (prefork), else this process's sum — the admission
        limit is enforced against this value, so limits are fleet-wide
        per service (never coupled across a combined daemon's gates)."""
        return self._fleet_total("inflight", self.inflight)

    def total_queued(self) -> int:
        return self._fleet_total("queued", self.queued)

    def occupancy(self) -> float:
        """(in-flight + queued) / limit, clamped to [0, 1] — the
        foreground-load signal the maintenance pacer consumes."""
        limit = self.effective_limit()
        if limit <= 0:
            return 0.0
        return min(1.0, (self.total_inflight() + self.total_queued())
                   / float(limit))

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "service": self.service,
                "limit": self.effective_limit(),
                "weights": dict(self._drr.weights),
                "inflight": dict(self.inflight),
                "queued": dict(self.queued),
                "admitted": dict(self.admitted),
                "shed": dict(self.shed),
                "queue_caps": {c: self.queue_cap(c) for c in CLASSES},
                "occupancy": round(self.occupancy(), 4),
                "tenants": self.tenants.snapshot(),
            }
            if _shm.ACTIVE is not None:
                snap["shm"] = _shm.ACTIVE.snapshot()
            return snap

    def _count(self, cls: str, outcome: str):
        _stats.QosRequestsCounter.labels(self.service, cls,
                                         outcome).inc()

    def _gauges(self, cls: str):
        _stats.QosInflightGauge.labels(self.service, cls).set(
            self.inflight[cls])
        _stats.QosQueueDepthGauge.labels(self.service, cls).set(
            max(0, self.queued[cls]))
        self._mirror(cls)
        if _shm.ACTIVE is not None:
            _stats.QosSharedGateOccupancyGauge.labels(self.service).set(
                round(self.occupancy(), 4))
