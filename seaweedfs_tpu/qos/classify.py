"""Request classification: QoS class + tenant key, thread-local scope,
and RPC header propagation.

Every request carries a QoS class — ``interactive`` (latency-sensitive
foreground reads), ``standard`` (ordinary writes / unclassified
traffic), or ``background`` (replication fan-out, curator jobs,
deep-scrub and bulk-encode traffic) — and an optional tenant key (the
S3 access key or the collection).  Both ride RPC headers
(``X-QoS-Class`` / ``X-QoS-Tenant``) exactly the way deadlines ride
``X-Deadline``: clients stamp the thread-local values into outbound
calls, ``RpcServer._dispatch`` installs them for the handler's
duration, and pool fan-outs re-pin them with :func:`set_qos` the same
way they re-pin deadlines.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional, Tuple

INTERACTIVE = "interactive"
STANDARD = "standard"
BACKGROUND = "background"

# dispatch-priority order: interactive drains first, background last
CLASSES = (INTERACTIVE, STANDARD, BACKGROUND)

QOS_HEADER = "X-QoS-Class"
TENANT_HEADER = "X-QoS-Tenant"

_ctx = threading.local()


def enabled() -> bool:
    """Master switch: WEED_QOS=0 restores the legacy flat shed gates."""
    return os.environ.get("WEED_QOS", "1") != "0"


def normalize(cls: Optional[str]) -> str:
    return cls if cls in CLASSES else STANDARD


def current_class() -> str:
    return getattr(_ctx, "qos_class", None) or STANDARD


def current_tenant() -> str:
    return getattr(_ctx, "qos_tenant", None) or ""


def set_qos(cls: Optional[str],
            tenant: Optional[str] = None) -> Tuple[Optional[str],
                                                   Optional[str]]:
    """Install (class, tenant) on this thread; returns the previous pair
    for restore — the non-context-manager form used by the server
    dispatch loop and pool fan-outs."""
    prev = (getattr(_ctx, "qos_class", None),
            getattr(_ctx, "qos_tenant", None))
    _ctx.qos_class = cls
    _ctx.qos_tenant = tenant
    return prev


class qos_scope:
    """``with qos_scope("background", tenant="maintenance"):`` — pins the
    class (and optionally the tenant) for the block; nested scopes
    restore the enclosing pair on exit.  ``tenant=None`` keeps the
    enclosing tenant."""

    __slots__ = ("cls", "tenant", "_prev")

    def __init__(self, cls: str, tenant: Optional[str] = None):
        self.cls = normalize(cls)
        self.tenant = tenant

    def __enter__(self):
        keep = current_tenant() if self.tenant is None else self.tenant
        self._prev = set_qos(self.cls, keep)
        return self

    def __exit__(self, *exc):
        set_qos(*self._prev)
        return False


def inject(headers: dict) -> dict:
    """Stamp the thread's QoS context into outbound RPC headers (no-op
    for unclassified standard traffic with no tenant)."""
    cls = getattr(_ctx, "qos_class", None)
    if cls:
        headers.setdefault(QOS_HEADER, cls)
    tenant = getattr(_ctx, "qos_tenant", None)
    if tenant:
        headers.setdefault(TENANT_HEADER, tenant)
    return headers


def from_headers(headers) -> Tuple[str, str]:
    """Server-side extraction: (class, tenant) from the propagation
    headers, defaulting to ``standard`` / no tenant."""
    return (normalize(headers.get(QOS_HEADER)),
            headers.get(TENANT_HEADER) or "")


def class_for_tenant(tenant: str, default: str) -> str:
    """Front-end classification override: WEED_QOS_CLASS_MAP maps tenant
    keys (S3 access keys / collections) to classes, e.g.
    ``analytics=background,mobile-app=interactive``."""
    spec = os.environ.get("WEED_QOS_CLASS_MAP", "")
    if spec and tenant:
        for part in spec.split(","):
            k, _, v = part.partition("=")
            if k.strip() == tenant and v.strip() in CLASSES:
                return v.strip()
    return default


def retry_after(base: int = 1, spread: int = 3,
                rand=random.random) -> str:
    """Jittered Retry-After header value in [base, base+spread] whole
    seconds — constant values synchronize shed clients into retry
    storms; full jitter decorrelates them."""
    base = max(1, int(base))
    spread = max(0, int(spread))
    return str(base + int(rand() * (spread + 1)) if spread else base)
