"""Cross-process QoS state (prefork gateway workers).

With `WEED_HTTP_WORKERS=N` every gateway worker is its own interpreter,
so the per-process dicts in admission.py/quota.py would silently turn
"tenant X gets 100 rps" into "tenant X gets 100 rps *per worker*".
This module moves the cross-process-critical state into one
`multiprocessing.shared_memory` segment:

  * a hash-addressed tenant token-bucket table (integer micro-token
    arithmetic, `CLOCK_MONOTONIC` refill — system-wide on Linux, so
    every process refills against the same clock);
  * per-(service, class) DRR deficit slots, mutated only under the
    service's shared "drr" lock so weight fidelity holds across
    workers;
  * per-(service, worker) admission-gate rows (inflight/queued/
    admitted/shed per class).  Each row has exactly ONE writer — the
    owning gate in the owning worker — so row updates need no lock;
    fleet totals are a read-side sum over one service's rows.

Gate rows and DRR slots are partitioned by SERVICE (a small name
registry in the segment) because a combined `weed server` runs several
PreforkGroups against the one process-global segment, each numbering
its workers 1..N-1 independently: the volume group's worker 1 and the
filer group's worker 1 are different processes, and keying rows by
worker id alone would let them clobber each other — and would couple
every gate's admission limit to the cross-service fleet sum.

Cross-process mutual exclusion uses `fcntl` byte-range locks on a
sidecar lock file rather than `multiprocessing.Lock`: record locks work
between *unrelated* processes (the test harness attaches from fresh
interpreters, and respawned workers must re-acquire cleanly), which
SemLock-based locks cannot.  fcntl locks do not exclude threads of the
same process, so every byte range is paired with an in-process
`threading.Lock`.

Known (documented) slack: the admission limit itself is checked
per-worker against its service's fleet-wide row sum without a global
lock, so the fleet can transiently overshoot the limit by at most one
request per worker.  Tenant buckets and DRR deficits are exact.
"""

from __future__ import annotations

import fcntl
import hashlib
import os
import struct
import tempfile
import threading
import time
from contextlib import contextmanager
from multiprocessing import shared_memory
from typing import Optional

from . import classify

MAX_WORKERS = 32
MAX_SERVICES = 8
N_STRIPES = 16
TENANT_SLOTS = 1024
_SLOTS_PER_STRIPE = TENANT_SLOTS // N_STRIPES
MICRO = 1_000_000  # tokens are stored as integer micro-tokens

_MAGIC = 0x5153484D  # "QSHM"
_HDR = struct.Struct("<IIII")              # magic, version, nworkers, pad
_SLOT = struct.Struct("<QqQQQ")            # hash, micro_tokens, last_ns,
_FIELDS = ("inflight", "queued", "admitted", "shed")       # taken, denied
_NCLASS = len(classify.CLASSES)
_CLS_INDEX = {c: i for i, c in enumerate(classify.CLASSES)}

_HDR_SIZE = 32
_SVC_NAME_LEN = 16                         # service registry entry
_SVC_OFF = _HDR_SIZE
_ROW_SIZE = _NCLASS * len(_FIELDS) * 8     # one (service, worker) row
_SVC_BLOCK = MAX_WORKERS * _ROW_SIZE       # one service's worker rows
_ROWS_OFF = _SVC_OFF + MAX_SERVICES * _SVC_NAME_LEN
_DRR_OFF = _ROWS_OFF + MAX_SERVICES * _SVC_BLOCK
_DRR_SIZE = MAX_SERVICES * _NCLASS * 8
_TENANT_OFF = _DRR_OFF + _DRR_SIZE
_TOTAL_SIZE = _TENANT_OFF + TENANT_SLOTS * _SLOT.size

# lock-byte indexes in the sidecar file: one per tenant stripe, then
# the service registry, then one DRR lock per service slot
_SVC_LOCK = N_STRIPES
_DRR_LOCK0 = N_STRIPES + 1
_N_LOCKS = N_STRIPES + 1 + MAX_SERVICES

ACTIVE: Optional["QosShm"] = None
_worker_id = 0


def set_worker_id(wid: int):
    global _worker_id
    _worker_id = min(max(0, wid), MAX_WORKERS - 1)


def worker_id() -> int:
    return _worker_id


def enabled_env() -> str:
    return os.environ.get("WEED_QOS_SHM", "auto")


class QosShm:
    def __init__(self, name: Optional[str] = None, create: bool = False,
                 nworkers: int = 1):
        if create:
            self.shm = shared_memory.SharedMemory(create=True,
                                                  size=_TOTAL_SIZE)
            self.shm.buf[:_TOTAL_SIZE] = b"\x00" * _TOTAL_SIZE
            _HDR.pack_into(self.shm.buf, 0, _MAGIC, 1,
                           min(nworkers, MAX_WORKERS), 0)
        else:
            self.shm = shared_memory.SharedMemory(name=name)
            # CPython (< 3.13 track=False) registers even attached
            # segments with this process's resource tracker, which
            # unlinks them at exit — an external attacher (probe, test,
            # sideband client) exiting would destroy the fleet's live
            # segment out from under every worker.  We never own a
            # segment we merely attached, so untrack it.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self.shm._name,
                                            "shared_memory")
            except Exception:
                pass
            magic, _ver, nworkers, _ = _HDR.unpack_from(self.shm.buf, 0)
            if magic != _MAGIC:
                self.shm.close()
                raise ValueError(f"{name}: not a QoS segment")
        self.name = self.shm.name
        self.nworkers = nworkers
        self._owner = create
        # sidecar byte-range lock file; one fd per instance, kept open
        # for the segment's whole life (closing ANY fd to a file drops
        # every fcntl lock this process holds on it)
        self.lock_path = os.path.join(
            tempfile.gettempdir(),
            f"weed-qos-{self.name.lstrip('/')}.lock")
        self._lock_fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR,
                                0o644)
        self._tlocks = [threading.Lock() for _ in range(_N_LOCKS)]
        self._svc_cache: dict[str, int] = {}

    def reinit_after_fork(self):
        """Replace (never acquire) the in-process stripe locks: the
        parent keeps serving while forking, so a child can inherit one
        mid-hold and would deadlock on its first bucket/DRR access.
        The fcntl byte-range locks need no reset — record locks are
        per-process and a child holds none at birth."""
        self._tlocks = [threading.Lock() for _ in range(_N_LOCKS)]

    # -- locking --------------------------------------------------------

    @contextmanager
    def _locked(self, idx: int):
        with self._tlocks[idx]:
            fcntl.lockf(self._lock_fd, fcntl.LOCK_EX, 1, idx)
            try:
                yield
            finally:
                fcntl.lockf(self._lock_fd, fcntl.LOCK_UN, 1, idx)

    @contextmanager
    def drr_lock(self, service: str = ""):
        with self._locked(_DRR_LOCK0 + max(0, self.service_index(service))):
            yield

    # -- service registry ------------------------------------------------

    def service_index(self, service: str, register: bool = True) -> int:
        """Slot index of `service` in the segment's name registry,
        claiming a free slot on first sight (register=True).  -1 when
        the service is absent (register=False) or the registry is full
        — callers then degrade to per-process state rather than share
        another service's rows."""
        if not service:
            service = "_"
        idx = self._svc_cache.get(service)
        if idx is not None:
            return idx
        raw = service.encode()[:_SVC_NAME_LEN].ljust(_SVC_NAME_LEN, b"\x00")
        with self._locked(_SVC_LOCK):
            for i in range(MAX_SERVICES):
                off = _SVC_OFF + i * _SVC_NAME_LEN
                cur = bytes(self.shm.buf[off:off + _SVC_NAME_LEN])
                if cur == raw:
                    self._svc_cache[service] = i
                    return i
                if cur == b"\x00" * _SVC_NAME_LEN:
                    if not register:
                        return -1
                    self.shm.buf[off:off + _SVC_NAME_LEN] = raw
                    self._svc_cache[service] = i
                    return i
        return -1

    def services(self) -> list:
        """(slot, name) for every registered service."""
        out = []
        for i in range(MAX_SERVICES):
            off = _SVC_OFF + i * _SVC_NAME_LEN
            raw = bytes(self.shm.buf[off:off + _SVC_NAME_LEN]) \
                .rstrip(b"\x00")
            if raw:
                out.append((i, raw.decode(errors="replace")))
        return out

    # -- gate rows (single writer: the owning service's worker) ---------

    def _field_off(self, sidx: int, wid: int, cls: str,
                   field: str) -> int:
        ci = _CLS_INDEX.get(cls, 1)
        fi = _FIELDS.index(field)
        return (_ROWS_OFF + sidx * _SVC_BLOCK + wid * _ROW_SIZE
                + (ci * len(_FIELDS) + fi) * 8)

    def gate_set(self, service: str, cls: str, field: str, value: int):
        sidx = self.service_index(service)
        if sidx < 0:
            return  # registry full: this gate stays per-process
        off = self._field_off(sidx, _worker_id, cls, field)
        struct.pack_into("<q", self.shm.buf, off, max(0, int(value)))

    def gate_read(self, service, wid: int, cls: str, field: str) -> int:
        sidx = (self.service_index(service, register=False)
                if isinstance(service, str) else service)
        if sidx < 0:
            return 0
        return struct.unpack_from(
            "<q", self.shm.buf, self._field_off(sidx, wid, cls, field))[0]

    def gate_total(self, field: str, cls: Optional[str] = None,
                   service: Optional[str] = None) -> int:
        """Sum a field over one service's worker rows (the value each
        gate enforces its limit against), or over every registered
        service when `service` is None (segment-wide debug totals)."""
        classes = (cls,) if cls else classify.CLASSES
        if service is None:
            sidxs = [i for i, _ in self.services()]
        else:
            i = self.service_index(service, register=False)
            sidxs = [i] if i >= 0 else []
        total = 0
        for sidx in sidxs:
            for wid in range(MAX_WORKERS):
                for c in classes:
                    total += self.gate_read(sidx, wid, c, field)
        return total

    def reset_worker(self, wid: int, service: Optional[str] = None):
        """Zero a (re)spawned worker's row: a crashed worker's stuck
        inflight/queued counts must not poison the fleet occupancy.
        Scoped to `service` when given — in a combined daemon each
        service numbers its workers independently, so one service's
        respawn must not zero another service's live counters."""
        if service is None:
            sidxs = range(MAX_SERVICES)
        else:
            i = self.service_index(service)
            sidxs = [i] if i >= 0 else []
        for sidx in sidxs:
            off = _ROWS_OFF + sidx * _SVC_BLOCK + wid * _ROW_SIZE
            self.shm.buf[off:off + _ROW_SIZE] = b"\x00" * _ROW_SIZE

    # -- DRR deficits ----------------------------------------------------

    def _drr_off(self, cls: str, service: str) -> int:
        sidx = max(0, self.service_index(service))
        return _DRR_OFF + (sidx * _NCLASS + _CLS_INDEX.get(cls, 1)) * 8

    def drr_get(self, cls: str, service: str = "") -> float:
        off = self._drr_off(cls, service)
        return struct.unpack_from("<q", self.shm.buf, off)[0] / MICRO

    def drr_set(self, cls: str, value: float, service: str = ""):
        off = self._drr_off(cls, service)
        struct.pack_into("<q", self.shm.buf, off, int(value * MICRO))

    # -- tenant token buckets -------------------------------------------

    @staticmethod
    def _hash(key: str) -> int:
        h = int.from_bytes(
            hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")
        return h or 1  # 0 means "slot empty"

    def _slot_off(self, idx: int) -> int:
        return _TENANT_OFF + idx * _SLOT.size

    def tenant_take(self, key: str, rate: float, burst: float,
                    n: float = 1.0) -> bool:
        """Take `n` tokens from `key`'s fleet-wide bucket; refill at
        `rate`/s up to `burst`.  rate <= 0 means unlimited."""
        if rate <= 0:
            return True
        h = self._hash(key)
        stripe = h % N_STRIPES
        base = stripe * _SLOTS_PER_STRIPE
        start = (h // N_STRIPES) % _SLOTS_PER_STRIPE
        burst_u = int(burst * MICRO)
        rate_u = int(rate * MICRO)
        need = int(n * MICRO)
        now = time.monotonic_ns()
        with self._locked(stripe):
            idx = None
            # probing stays inside the stripe's contiguous region, so
            # every claim in it is serialized by this stripe's lock
            for i in range(_SLOTS_PER_STRIPE):
                off = self._slot_off(base + (start + i) % _SLOTS_PER_STRIPE)
                slot_hash = struct.unpack_from("<Q", self.shm.buf, off)[0]
                if slot_hash == h:
                    idx = off
                    break
                if slot_hash == 0:
                    # slots are never freed, so a key always sits before
                    # the first empty slot on its probe path: claim it
                    idx = off
                    break
            if idx is None:
                return True  # stripe full (>64 live tenants hashing
                # here): fail open rather than starve an unlucky tenant
            sh, tokens, last_ns, taken, denied = _SLOT.unpack_from(
                self.shm.buf, idx)
            if sh != h:  # claiming a fresh slot
                tokens, last_ns, taken, denied = burst_u, now, 0, 0
            else:
                tokens = min(burst_u,
                             tokens + (now - last_ns) * rate_u // 10**9)
            ok = tokens >= need
            if ok:
                tokens -= need
                taken += 1
            else:
                denied += 1
            _SLOT.pack_into(self.shm.buf, idx, h, tokens, now,
                            taken, denied)
        return ok

    def tenant_stats(self, key: str) -> Optional[dict]:
        h = self._hash(key)
        base = (h % N_STRIPES) * _SLOTS_PER_STRIPE
        start = (h // N_STRIPES) % _SLOTS_PER_STRIPE
        for i in range(_SLOTS_PER_STRIPE):
            off = self._slot_off(base + (start + i) % _SLOTS_PER_STRIPE)
            sh, tokens, _last, taken, denied = _SLOT.unpack_from(
                self.shm.buf, off)
            if sh == h:
                return {"tokens": tokens / MICRO, "taken": taken,
                        "denied": denied}
            if sh == 0:
                return None
        return None

    # -- snapshot / lifecycle -------------------------------------------

    def snapshot(self) -> dict:
        services = {}
        for sidx, name in self.services():
            per_worker = {}
            for wid in range(MAX_WORKERS):
                row = {c: {f: self.gate_read(sidx, wid, c, f)
                           for f in _FIELDS}
                       for c in classify.CLASSES}
                if any(v for cls in row.values() for v in cls.values()):
                    per_worker[str(wid)] = row
            services[name] = {
                "inflight": self.gate_total("inflight", service=name),
                "queued": self.gate_total("queued", service=name),
                "drr_deficit": {c: self.drr_get(c, service=name)
                                for c in classify.CLASSES},
                "workers": per_worker,
            }
        return {
            "segment": self.name,
            "nworkers": self.nworkers,
            "fleet_inflight": self.gate_total("inflight"),
            "fleet_queued": self.gate_total("queued"),
            "services": services,
        }

    def close(self):
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass
        try:
            os.close(self._lock_fd)
        except OSError:
            pass

    def unlink(self):
        try:
            self.shm.unlink()
        except OSError:
            pass
        try:
            os.unlink(self.lock_path)
        except OSError:
            pass


def create(nworkers: int) -> QosShm:
    """Create the segment and make it ACTIVE in this process (the
    prefork parent calls this before forking so children inherit)."""
    global ACTIVE
    if ACTIVE is not None:
        return ACTIVE
    ACTIVE = QosShm(create=True, nworkers=nworkers)
    return ACTIVE


def attach(name: str) -> QosShm:
    """Attach to an existing segment by name (unrelated processes —
    tests, external probes) and make it ACTIVE."""
    global ACTIVE
    ACTIVE = QosShm(name=name)
    return ACTIVE


def destroy():
    """Close and (if owner) unlink the ACTIVE segment."""
    global ACTIVE
    shm = ACTIVE
    ACTIVE = None
    if shm is None:
        return
    owner = shm._owner
    shm.close()
    if owner:
        shm.unlink()
