"""Priority device lanes for the EC pipeline.

The device dispatch FIFOs in ``parallel/batched_encode.py`` and the
deep-scrub loop push work at batch granularity, so lane priority is
enforced at batch boundaries: background dispatchers (scrub re-encode,
bulk encode) call :meth:`DeviceLanes.background_checkpoint` before
every device step and stall while any foreground work — degraded-read
recover decodes, wrapped in :meth:`DeviceLanes.foreground` — is in
flight.  A starvation floor (WEED_QOS_BG_MAX_STALL_MS) lets background
proceed anyway once it has waited long enough, so a continuous read
storm paces scrubs instead of parking them forever.

The clock is injectable (``self.now``) per the repo's fake-clock test
convention; the condition variable wakes on foreground exit, so tests
never sleep.
"""

from __future__ import annotations

import os
import threading
import time

from ..stats import metrics as _stats
from . import classify

FOREGROUND = "foreground"
BACKGROUND = "background"


def _max_stall_seconds() -> float:
    try:
        ms = float(os.environ.get("WEED_QOS_BG_MAX_STALL_MS", "")
                   or 2000.0)
    except ValueError:
        ms = 2000.0
    return max(0.0, ms / 1000.0)


def lanes_enabled() -> bool:
    if not classify.enabled():
        return False
    return os.environ.get("WEED_QOS_LANES", "1") != "0"


class _FgCtx:
    __slots__ = ("lanes",)

    def __init__(self, lanes: "DeviceLanes"):
        self.lanes = lanes

    def __enter__(self):
        self.lanes._fg_enter()
        return self.lanes

    def __exit__(self, *exc):
        self.lanes._fg_exit()
        return False


class DeviceLanes:
    def __init__(self, now=time.monotonic):
        self.now = now
        self._cond = threading.Condition()
        self._fg_active = 0
        self.fg_batches = 0
        self.bg_batches = 0
        self.preemptions = 0
        self.bg_wait_seconds = 0.0

    def foreground(self) -> _FgCtx:
        """Wrap a foreground (degraded-read recover decode) device step;
        queued background batches yield until it exits."""
        return _FgCtx(self)

    def _fg_enter(self):
        with self._cond:
            self._fg_active += 1
            self.fg_batches += 1
        _stats.QosLaneActiveGauge.labels(FOREGROUND).set(self._fg_active)
        _stats.QosLaneBatchesCounter.labels(FOREGROUND).inc()

    def _fg_exit(self):
        with self._cond:
            self._fg_active = max(0, self._fg_active - 1)
            if self._fg_active == 0:
                self._cond.notify_all()
        _stats.QosLaneActiveGauge.labels(FOREGROUND).set(self._fg_active)

    def background_checkpoint(self) -> float:
        """Called by background dispatch loops before each device batch;
        blocks while foreground work is active (up to the starvation
        floor).  Returns the seconds waited."""
        if not lanes_enabled():
            return 0.0
        waited = 0.0
        with self._cond:
            if self._fg_active > 0:
                self.preemptions += 1
                _stats.QosLanePreemptionsCounter.inc()
                t0 = self.now()
                deadline = t0 + _max_stall_seconds()
                while self._fg_active > 0:
                    remaining = deadline - self.now()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                waited = max(0.0, self.now() - t0)
                self.bg_wait_seconds += waited
            self.bg_batches += 1
        if waited:
            _stats.QosLaneWaitSecondsCounter.inc(waited)
        _stats.QosLaneBatchesCounter.labels(BACKGROUND).inc()
        return waited

    def snapshot(self) -> dict:
        with self._cond:
            return {"enabled": lanes_enabled(),
                    "foreground_active": self._fg_active,
                    "foreground_batches": self.fg_batches,
                    "background_batches": self.bg_batches,
                    "preemptions": self.preemptions,
                    "background_wait_seconds":
                        round(self.bg_wait_seconds, 6)}

    def reset(self):
        """Test seam: zero the counters (the process-wide singleton
        outlives any one test)."""
        with self._cond:
            self.fg_batches = 0
            self.bg_batches = 0
            self.preemptions = 0
            self.bg_wait_seconds = 0.0


# process-wide singleton: one device, one pair of lanes
LANES = DeviceLanes()
