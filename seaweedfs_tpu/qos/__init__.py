"""Cluster quality-of-service: tenant-aware admission control,
weighted-fair scheduling, and priority device lanes.

Layers:

* :mod:`.classify` — QoS classes (interactive/standard/background),
  tenant keys, thread-local scope, and X-QoS-Class/X-QoS-Tenant header
  propagation (rides the same dispatch/injection points as deadlines
  and trace context).
* :mod:`.admission` — per-daemon front-end gates: bounded per-class
  queues, deficit-round-robin dispatch, per-tenant token buckets,
  class-aware shedding (background first, interactive last).
* :mod:`.quota` — per-collection byte/ops quotas at master assign and
  S3 PUT.
* :mod:`.lanes` — foreground/background device lanes for the EC
  pipeline: degraded-read recover decodes preempt queued background
  batches (scrub re-encode, bulk encode) at batch granularity.

Every daemon mounts ``GET /debug/qos`` via :func:`mount` for a live
JSON snapshot of its gate, the device lanes, and the quota state.
"""

from __future__ import annotations

from .admission import (AdmissionGate, DrrQueue, TenantBuckets,  # noqa: F401
                        TokenBucket, class_weights)
from .classify import (BACKGROUND, CLASSES, INTERACTIVE,  # noqa: F401
                       QOS_HEADER, STANDARD, TENANT_HEADER,
                       class_for_tenant, current_class, current_tenant,
                       enabled, from_headers, inject, normalize,
                       qos_scope, retry_after, set_qos)
from .lanes import LANES, DeviceLanes, lanes_enabled  # noqa: F401
from .quota import QUOTAS, CollectionQuotas  # noqa: F401
from . import shm  # noqa: F401


def snapshot(gate=None) -> dict:
    """One daemon's QoS state: its admission gate (if it has one), the
    process-wide device lanes, and the quota meter."""
    out = {
        "enabled": enabled(),
        "gate": gate.snapshot() if gate is not None else None,
        "lanes": LANES.snapshot(),
        "quotas": QUOTAS.snapshot(),
    }
    return out


def mount(server, gate=None):
    """Register GET /debug/qos on an RpcServer (the faults.mount /
    profiling.mount pattern)."""
    server.add("GET", "/debug/qos", lambda req: snapshot(gate))
