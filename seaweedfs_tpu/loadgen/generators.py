"""Deterministic workload generators: seed -> request schedule.

Every draw is ``blake2b(f"{seed}:{stream}:{n}")`` mapped to [0, 1) —
the same keyed-hash replay contract as util/faults.py — so schedules
are reproducible byte-for-byte from ``WEED_LOAD_SEED`` alone.  No
process RNG state is consulted anywhere; two processes building the
same schedule concurrently produce identical bytes.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import math
import os
from dataclasses import dataclass
from typing import Optional


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def load_seed() -> int:
    """The workload seed (WEED_LOAD_SEED, default 42)."""
    return int(_env_float("WEED_LOAD_SEED", 42))


def _unit(seed: int, stream: str, n: int) -> float:
    """The n-th uniform draw of a named stream, in [0, 1)."""
    h = hashlib.blake2b(f"{seed}:{stream}:{n}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / float(1 << 64)


class ZipfPopularity:
    """Zipfian object popularity: P(object i) ∝ 1/(i+1)^s.

    The reference's whole design serves this shape — a small hot set
    absorbing most reads off many cheap volume servers.  Sampling is
    inverse-CDF over the precomputed cumulative weights, so draw n is
    a pure function of (seed, stream, n)."""

    def __init__(self, n_objects: int, s: float = 1.1, seed: int = 0,
                 stream: str = "zipf"):
        if n_objects <= 0:
            raise ValueError("n_objects must be positive")
        self.n_objects = n_objects
        self.s = s
        self.seed = seed
        self.stream = stream
        self._cum: list[float] = []
        total = 0.0
        for i in range(n_objects):
            total += 1.0 / float(i + 1) ** s
            self._cum.append(total)
        self._total = total

    def sample(self, n: int) -> int:
        u = _unit(self.seed, self.stream, n) * self._total
        return min(self.n_objects - 1, bisect.bisect_left(self._cum, u))


class SizeMixture:
    """Object-size mixture: weighted size classes, log-uniform within
    each class (the small-file-dominated photo-serving shape)."""

    DEFAULT = ((0.65, 1 << 10, 8 << 10),     # thumbnails
               (0.30, 8 << 10, 64 << 10),    # photos
               (0.05, 64 << 10, 256 << 10))  # originals

    def __init__(self, classes=DEFAULT, seed: int = 0,
                 stream: str = "size"):
        self.classes = tuple(classes)
        self.seed = seed
        self.stream = stream
        self._cum: list[float] = []
        total = 0.0
        for w, _, _ in self.classes:
            total += w
            self._cum.append(total)
        self._total = total

    def sample(self, n: int) -> int:
        u = _unit(self.seed, f"{self.stream}.class", n) * self._total
        idx = min(len(self.classes) - 1,
                  bisect.bisect_left(self._cum, u))
        _, lo, hi = self.classes[idx]
        v = _unit(self.seed, f"{self.stream}.val", n)
        return int(round(lo * (hi / float(lo)) ** v))


def tenant_class(seed: int, tenant: int) -> str:
    """Stable tenant -> QoS class assignment: ~15% interactive
    dashboards, ~75% standard apps, ~10% background crawlers."""
    u = _unit(seed, "tenant.class", tenant)
    if u < 0.15:
        return "interactive"
    if u < 0.90:
        return "standard"
    return "background"


class DiurnalTenantMix:
    """Hundreds of tenants whose request shares swing on a diurnal
    cycle: tenant i's weight is base_i * (1 + amp*sin(2π(t/period +
    phase_i))), phases and bases hashed from the seed.  Weights are
    quantized to time buckets so sampling a long schedule stays
    O(log n_tenants) per draw."""

    def __init__(self, n_tenants: int, seed: int = 0,
                 stream: str = "tenant", amplitude: float = 0.8,
                 period: float = 86400.0, buckets: int = 96):
        if n_tenants <= 0:
            raise ValueError("n_tenants must be positive")
        self.n_tenants = n_tenants
        self.seed = seed
        self.stream = stream
        self.amplitude = min(0.999, max(0.0, amplitude))
        self.period = period
        self.bucket_seconds = period / float(buckets)
        self._phase = [_unit(seed, f"{stream}.phase", i)
                       for i in range(n_tenants)]
        # heterogeneous tenant sizes: a few big tenants, a long tail
        self._base = [0.25 + 2.0 * _unit(seed, f"{stream}.base", i) ** 3
                      for i in range(n_tenants)]
        self._cache: dict[int, tuple[list[float], float]] = {}

    def _cum_at(self, t: float) -> tuple[list[float], float]:
        bucket = int(t / self.bucket_seconds)
        hit = self._cache.get(bucket)
        if hit is not None:
            return hit
        tb = bucket * self.bucket_seconds
        cum: list[float] = []
        total = 0.0
        for i in range(self.n_tenants):
            w = self._base[i] * (1.0 + self.amplitude * math.sin(
                2.0 * math.pi * (tb / self.period + self._phase[i])))
            total += max(1e-9, w)
            cum.append(total)
        if len(self._cache) > 256:
            self._cache.clear()
        self._cache[bucket] = (cum, total)
        return cum, total

    def weight(self, tenant: int, t: float) -> float:
        return self._base[tenant] * (1.0 + self.amplitude * math.sin(
            2.0 * math.pi * (t / self.period + self._phase[tenant])))

    def sample(self, t: float, n: int) -> int:
        cum, total = self._cum_at(t)
        u = _unit(self.seed, f"{self.stream}.pick", n) * total
        return min(self.n_tenants - 1, bisect.bisect_left(cum, u))


def poisson_arrivals(rate_rps: float, duration_s: float, seed: int,
                     stream: str = "arrivals") -> list[float]:
    """Open-loop Poisson arrival times in [0, duration): exponential
    inter-arrivals via inverse transform of the keyed-hash uniforms."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    out: list[float] = []
    t = 0.0
    n = 0
    while True:
        u = _unit(seed, stream, n)
        n += 1
        t += -math.log(1.0 - u) / rate_rps
        if t >= duration_s:
            return out
        out.append(t)


@dataclass
class Request:
    """One scheduled request of the replay."""
    t: float            # arrival offset from schedule start, seconds
    op: str             # "GET" | "PUT"
    obj: int            # object index (zipf-ranked: 0 is hottest)
    size: int           # object bytes (PUT payload / expected GET size)
    tenant: str         # QoS tenant key, e.g. "t0042"
    qos_class: str      # interactive | standard | background

    def to_dict(self) -> dict:
        return {"t": round(self.t, 9), "op": self.op, "obj": self.obj,
                "size": self.size, "tenant": self.tenant,
                "qos_class": self.qos_class}


def build_schedule(seed: Optional[int] = None,
                   duration_s: Optional[float] = None,
                   rate_rps: Optional[float] = None,
                   n_objects: Optional[int] = None,
                   n_tenants: Optional[int] = None,
                   zipf_s: Optional[float] = None,
                   write_ratio: float = 0.05) -> list[Request]:
    """Full schedule: Poisson arrivals x zipf popularity x size
    mixture x diurnal tenant mix.  All knobs default from the
    WEED_LOAD_* environment so `bench.py` phases and operators share
    one configuration surface."""
    if seed is None:
        seed = load_seed()
    if duration_s is None:
        duration_s = _env_float("WEED_LOAD_DURATION", 10.0)
    if rate_rps is None:
        rate_rps = _env_float("WEED_LOAD_RATE", 200.0)
    if n_objects is None:
        n_objects = int(_env_float("WEED_LOAD_OBJECTS", 1000))
    if n_tenants is None:
        n_tenants = int(_env_float("WEED_LOAD_TENANTS", 200))
    if zipf_s is None:
        zipf_s = _env_float("WEED_LOAD_ZIPF_S", 1.1)
    zipf = ZipfPopularity(n_objects, s=zipf_s, seed=seed)
    sizes = SizeMixture(seed=seed)
    mix = DiurnalTenantMix(n_tenants, seed=seed)
    sched: list[Request] = []
    for n, t in enumerate(poisson_arrivals(rate_rps, duration_s, seed)):
        op = "PUT" if _unit(seed, "op", n) < write_ratio else "GET"
        tenant = mix.sample(t, n)
        sched.append(Request(
            t=t, op=op, obj=zipf.sample(n), size=sizes.sample(n),
            tenant=f"t{tenant:04d}",
            qos_class=tenant_class(seed, tenant)))
    return sched


def schedule_bytes(schedule: list[Request]) -> bytes:
    """Canonical serialization (sorted-key JSON lines) — the byte
    string two same-seed runs must reproduce identically."""
    return b"\n".join(
        json.dumps(r.to_dict(), sort_keys=True,
                   separators=(",", ":")).encode()
        for r in schedule)
