"""Workload-replay traffic engine (loadgen).

Seeded-deterministic generators for Haystack-style skewed traffic —
zipfian object popularity, object-size mixtures, a diurnal tenant mix
across hundreds of QoS tenants, and open-loop Poisson request
schedules — plus a replay pool that drives a schedule against a live
mini-cluster with the QoS class/tenant headers installed per request.

Determinism contract: every random decision hashes
``blake2b(f"{seed}:{stream}:{n}")`` exactly like the fault-injection
replay (util/faults.py), so the k-th draw of a named stream is a pure
function of the seed — the same ``WEED_LOAD_SEED`` yields a
byte-identical schedule regardless of worker interleaving.
"""

from .generators import (DiurnalTenantMix, Request, SizeMixture,
                         ZipfPopularity, build_schedule, load_seed,
                         poisson_arrivals, schedule_bytes, tenant_class)
from .replay import ReplayStats, percentile, replay

__all__ = [
    "DiurnalTenantMix", "Request", "SizeMixture", "ZipfPopularity",
    "build_schedule", "load_seed", "poisson_arrivals", "schedule_bytes",
    "tenant_class", "ReplayStats", "percentile", "replay",
]
