"""Replay pool: drive a generated schedule against a live cluster.

Open-loop by default (requests fire at their scheduled Poisson arrival
times — late requests fire immediately, they are never dropped), with
a closed-loop mode for max-throughput storms.  Each request runs under
``qos.qos_scope(qos_class, tenant=...)`` so the X-QoS-* headers ride
every hop exactly like production traffic and per-tenant token buckets
see hundreds of distinct keys.

The pool is multi-process capable: ``processes=N`` forks N children,
each replaying a stride-partitioned slice with its own thread pool and
piping its stats back — real client-side parallelism that does not
share the parent's GIL.  ``processes=0`` (default) stays in-process
with threads, which is what the 1-core CI harness can actually use.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import qos
from .generators import Request

_CLASSES = {"interactive": None, "standard": None, "background": None}


def percentile(sorted_vals: list[float], p: float) -> float:
    """p in [0,1] over an ascending list (0.0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(len(sorted_vals) * p) - 1))
    return sorted_vals[idx]


class ReplayStats:
    """Mergeable per-class latency/failure accounting."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: dict[str, list[float]] = {
            c: [] for c in _CLASSES}
        self.failures: dict[str, int] = {c: 0 for c in _CLASSES}
        self.wall_s = 0.0

    def record(self, qos_class: str, seconds: float, ok: bool):
        cls = qos_class if qos_class in self.latencies else "standard"
        with self.lock:
            if ok:
                self.latencies[cls].append(seconds)
            else:
                self.failures[cls] += 1

    def merge(self, other: dict):
        with self.lock:
            for cls, vals in other.get("latencies", {}).items():
                self.latencies.setdefault(cls, []).extend(vals)
            for cls, n in other.get("failures", {}).items():
                self.failures[cls] = self.failures.get(cls, 0) + n

    def to_dict(self) -> dict:
        with self.lock:
            return {"latencies": {c: list(v)
                                  for c, v in self.latencies.items()},
                    "failures": dict(self.failures)}

    def summary(self) -> dict:
        with self.lock:
            all_lat = sorted(v for vals in self.latencies.values()
                             for v in vals)
            by_class = {}
            for cls, vals in self.latencies.items():
                vals = sorted(vals)
                by_class[cls] = {
                    "requests": len(vals),
                    "failures": self.failures.get(cls, 0),
                    "p50_ms": round(percentile(vals, 0.50) * 1e3, 3),
                    "p99_ms": round(percentile(vals, 0.99) * 1e3, 3),
                }
            n = len(all_lat)
            failures = sum(self.failures.values())
            return {
                "requests": n, "failures": failures,
                "wall_s": round(self.wall_s, 3),
                "rps": round(n / self.wall_s, 1) if self.wall_s else 0.0,
                "p50_ms": round(percentile(all_lat, 0.50) * 1e3, 3),
                "p99_ms": round(percentile(all_lat, 0.99) * 1e3, 3),
                "by_class": by_class,
            }


def _replay_slice(schedule: list[Request],
                  send: Callable[[Request], bool],
                  stats: ReplayStats, start: float, time_scale: float,
                  open_loop: bool,
                  stop: Optional[threading.Event] = None):
    for req in schedule:
        if stop is not None and stop.is_set():
            return
        if open_loop:
            delay = start + req.t * time_scale - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        t0 = time.perf_counter()
        ok = False
        try:
            with qos.qos_scope(req.qos_class, tenant=req.tenant):
                ok = bool(send(req))
        except Exception:
            ok = False
        stats.record(req.qos_class, time.perf_counter() - t0, ok)


def _replay_threads(schedule: list[Request],
                    send: Callable[[Request], bool], workers: int,
                    time_scale: float, open_loop: bool,
                    stop: Optional[threading.Event] = None
                    ) -> ReplayStats:
    stats = ReplayStats()
    start = time.monotonic()
    workers = max(1, workers)
    slices = [schedule[i::workers] for i in range(workers)]
    threads = [threading.Thread(
        target=_replay_slice,
        args=(s, send, stats, start, time_scale, open_loop, stop),
        name=f"loadgen-{i}", daemon=True)
        for i, s in enumerate(slices) if s]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats.wall_s = time.monotonic() - start
    return stats


def replay(schedule: list[Request], send: Callable[[Request], bool],
           workers: int = 8, processes: int = 0,
           time_scale: float = 1.0, open_loop: bool = True,
           stop: Optional[threading.Event] = None) -> dict:
    """Replay `schedule`, calling ``send(req) -> bool`` per request.

    Returns the merged summary dict (requests/failures/rps/p50/p99
    overall and by QoS class).  With ``processes > 0`` the schedule is
    stride-partitioned across forked children (each running `workers`
    threads); exceptions from `send` count as failures, never abort
    the replay."""
    if not schedule:
        return ReplayStats().summary()
    if processes and processes > 1:
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        t_wall = time.monotonic()
        pipes, procs = [], []
        for i in range(processes):
            part = schedule[i::processes]
            if not part:
                continue
            rx, tx = ctx.Pipe(duplex=False)

            def child(part=part, tx=tx):
                st = _replay_threads(part, send, workers, time_scale,
                                     open_loop)
                tx.send(st.to_dict())
                tx.close()

            p = ctx.Process(target=child, daemon=True)
            p.start()
            pipes.append(rx)
            procs.append(p)
        merged = ReplayStats()
        for rx in pipes:
            try:
                merged.merge(rx.recv())
            except EOFError:
                pass  # child died; its requests count as unrecorded
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        merged.wall_s = time.monotonic() - t_wall
        return merged.summary()
    stats = _replay_threads(schedule, send, workers, time_scale,
                            open_loop, stop)
    return stats.summary()
