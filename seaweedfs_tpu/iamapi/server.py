"""AWS IAM-compatible management API.

Parity with weed/iamapi/iamapi_server.go + iamapi_management_handlers.go:
form-encoded Action= requests (CreateUser, ListUsers, GetUser, DeleteUser,
CreateAccessKey, DeleteAccessKey, PutUserPolicy, GetUserPolicy,
DeleteUserPolicy) that mutate the same identity config the S3 gateway
authenticates against; the config persists in the filer at
/etc/iam/identity.json (the reference stores s3 config through the filer
the same way, iamapi_server.go GetS3ApiConfiguration/PutS3ApiConfiguration).
Policy statements map onto the gateway's action list the way the
reference's GetActions does (Get/Put/List/Tagging/Admin on arn buckets).
"""

from __future__ import annotations

import json
import secrets
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Optional

from ..filer.entry import Attr, Entry
from ..filer.filer import Filer
from ..filer.filer_store import NotFoundError
from ..rpc.http_rpc import Request, Response, RpcError, RpcServer
from ..s3api.auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ,
                          ACTION_WRITE, Identity)

IDENTITY_CONFIG_PATH = "/etc/iam/identity.json"


def _policy_to_actions(policy_doc: dict) -> list[str]:
    """Map an IAM policy document onto gateway actions
    (iamapi_management_handlers.go GetActions)."""
    actions: list[str] = []
    for statement in policy_doc.get("Statement", []):
        if statement.get("Effect") != "Allow":
            continue
        stmt_actions = statement.get("Action", [])
        if isinstance(stmt_actions, str):
            stmt_actions = [stmt_actions]
        resources = statement.get("Resource", [])
        if isinstance(resources, str):
            resources = [resources]
        buckets = []
        for res in resources:
            # arn:aws:s3:::bucket/* or arn:aws:s3:::*
            tail = res.split(":::", 1)[-1]
            bucket = tail.split("/", 1)[0]
            buckets.append("" if bucket in ("*", "") else bucket)
        for act in stmt_actions:
            verb = act.split(":", 1)[-1]
            for bucket in buckets or [""]:
                suffix = f":{bucket}" if bucket else ""
                if verb == "*":
                    actions.append(ACTION_ADMIN + suffix)
                elif verb in ("GetObject", "GetObjectAcl"):
                    actions.append(ACTION_READ + suffix)
                elif verb in ("PutObject", "PutObjectAcl", "DeleteObject"):
                    actions.append(ACTION_WRITE + suffix)
                elif verb in ("ListBucket", "ListAllMyBuckets"):
                    actions.append(ACTION_LIST + suffix)
    return sorted(set(actions))


class IamIdentityStore:
    """Identity config shared with the S3 gateway, persisted in the filer."""

    def __init__(self, filer: Filer):
        self.filer = filer

    def load(self) -> dict:
        try:
            entry = self.filer.find_entry(IDENTITY_CONFIG_PATH)
            return json.loads(entry.content.decode())
        except (NotFoundError, ValueError):
            return {"identities": []}

    def save(self, config: dict):
        body = json.dumps(config, indent=2).encode()
        self.filer.create_entry(Entry(
            full_path=IDENTITY_CONFIG_PATH,
            attr=Attr(mtime=time.time(), crtime=time.time(),
                      file_size=len(body)),
            content=body))

    def identities(self) -> list[Identity]:
        return [Identity(name=i["name"],
                         access_key=i.get("access_key", ""),
                         secret_key=i.get("secret_key", ""),
                         actions=i.get("actions", []))
                for i in self.load().get("identities", [])]


class IamApiServer:
    def __init__(self, filer_server, host: str = "127.0.0.1", port: int = 0,
                 s3_server=None):
        self.filer = filer_server.filer
        self.store = IamIdentityStore(self.filer)
        self.s3_server = s3_server  # live-reload its IAM on changes
        self.server = RpcServer(host, port)
        self.server.default_route = self._handle
        # persisted identities take effect immediately on startup, not only
        # after the next IAM mutation
        config = self.store.load()
        if config.get("identities"):
            self._sync_s3(config)

    @property
    def address(self) -> str:
        return self.server.address

    def start(self):
        self.server.start()

    def stop(self):
        self.server.stop()

    def _sync_s3(self, config: dict):
        if self.s3_server is not None:
            from ..s3api.auth import IdentityAccessManagement

            self.s3_server.iam = IdentityAccessManagement([
                Identity(name=i["name"],
                         access_key=i.get("access_key", ""),
                         secret_key=i.get("secret_key", ""),
                         actions=i.get("actions", []))
                for i in config.get("identities", [])])

    # -- request handling ----------------------------------------------------
    def _handle(self, method: str, req: Request):
        if method != "POST":
            raise RpcError("IAM requires POST", 405)
        form = urllib.parse.parse_qs(req.body.decode("utf-8", "replace"))
        params = {k: v[0] for k, v in form.items()}
        params.update({k: str(v) for k, v in req.query.items()})
        action = params.get("Action", "")
        handler = getattr(self, f"_do_{action}", None)
        if handler is None:
            return self._error("InvalidAction", f"unknown action {action}",
                               400)
        return handler(params)

    @staticmethod
    def _error(code: str, message: str, status: int) -> Response:
        root = ET.Element("ErrorResponse")
        err = ET.SubElement(root, "Error")
        ET.SubElement(err, "Code").text = code
        ET.SubElement(err, "Message").text = message
        return Response(ET.tostring(root), status, "application/xml")

    @staticmethod
    def _ok(action: str, payload: Optional[dict] = None) -> Response:
        root = ET.Element(f"{action}Response",
                          xmlns="https://iam.amazonaws.com/doc/2010-05-08/")
        result = ET.SubElement(root, f"{action}Result")

        def build(parent, value):
            if isinstance(value, dict):
                for k, v in value.items():
                    if isinstance(v, list):
                        wrap = ET.SubElement(parent, k)
                        for item in v:
                            member = ET.SubElement(wrap, "member")
                            build(member, item)
                    else:
                        node = ET.SubElement(parent, k)
                        build(node, v)
            else:
                parent.text = "" if value is None else str(value)

        if payload:
            build(result, payload)
        meta = ET.SubElement(root, "ResponseMetadata")
        ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex
        return Response(
            b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root),
            200, "application/xml")

    def _find_user(self, config: dict, name: str) -> Optional[dict]:
        for ident in config.get("identities", []):
            if ident["name"] == name:
                return ident
        return None

    # -- user CRUD -----------------------------------------------------------
    def _do_CreateUser(self, params: dict):
        name = params.get("UserName", "")
        if not name:
            return self._error("InvalidInput", "missing UserName", 400)
        config = self.store.load()
        if self._find_user(config, name):
            return self._error("EntityAlreadyExists", name, 409)
        config.setdefault("identities", []).append(
            {"name": name, "access_key": "", "secret_key": "",
             "actions": []})
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("CreateUser", {"User": {
            "UserName": name, "UserId": name,
            "Arn": f"arn:aws:iam:::user/{name}"}})

    def _do_ListUsers(self, params: dict):
        config = self.store.load()
        return self._ok("ListUsers", {"Users": [
            {"UserName": i["name"], "UserId": i["name"],
             "Arn": f"arn:aws:iam:::user/{i['name']}"}
            for i in config.get("identities", [])
        ], "IsTruncated": "false"})

    def _do_GetUser(self, params: dict):
        name = params.get("UserName", "")
        user = self._find_user(self.store.load(), name)
        if user is None:
            return self._error("NoSuchEntity", name, 404)
        return self._ok("GetUser", {"User": {
            "UserName": name, "UserId": name,
            "Arn": f"arn:aws:iam:::user/{name}"}})

    def _do_UpdateUser(self, params: dict):
        name = params.get("UserName", "")
        new_name = params.get("NewUserName", "")
        config = self.store.load()
        user = self._find_user(config, name)
        if user is None:
            return self._error("NoSuchEntity", name, 404)
        if new_name:
            user["name"] = new_name
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("UpdateUser")

    def _do_DeleteUser(self, params: dict):
        name = params.get("UserName", "")
        config = self.store.load()
        before = len(config.get("identities", []))
        config["identities"] = [i for i in config.get("identities", [])
                                if i["name"] != name]
        if len(config["identities"]) == before:
            return self._error("NoSuchEntity", name, 404)
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("DeleteUser")

    # -- access keys ---------------------------------------------------------
    def _do_CreateAccessKey(self, params: dict):
        name = params.get("UserName", "")
        config = self.store.load()
        user = self._find_user(config, name)
        if user is None:  # AWS auto-creates for unknown users? No: error
            return self._error("NoSuchEntity", name, 404)
        access_key = "AKIA" + secrets.token_hex(8).upper()
        secret_key = secrets.token_urlsafe(30)
        user["access_key"] = access_key
        user["secret_key"] = secret_key
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("CreateAccessKey", {"AccessKey": {
            "UserName": name, "AccessKeyId": access_key,
            "SecretAccessKey": secret_key, "Status": "Active"}})

    def _do_DeleteAccessKey(self, params: dict):
        name = params.get("UserName", "")
        key_id = params.get("AccessKeyId", "")
        config = self.store.load()
        user = self._find_user(config, name)
        if user is None:
            return self._error("NoSuchEntity", name, 404)
        if user.get("access_key") == key_id:
            user["access_key"] = ""
            user["secret_key"] = ""
            self.store.save(config)
            self._sync_s3(config)
        return self._ok("DeleteAccessKey")

    def _do_ListAccessKeys(self, params: dict):
        name = params.get("UserName", "")
        config = self.store.load()
        users = config.get("identities", [])
        if name:
            users = [u for u in users if u["name"] == name]
        return self._ok("ListAccessKeys", {"AccessKeyMetadata": [
            {"UserName": u["name"], "AccessKeyId": u.get("access_key", ""),
             "Status": "Active"}
            for u in users if u.get("access_key")
        ], "IsTruncated": "false"})

    # -- policies ------------------------------------------------------------
    def _do_PutUserPolicy(self, params: dict):
        name = params.get("UserName", "")
        document = params.get("PolicyDocument", "")
        config = self.store.load()
        user = self._find_user(config, name)
        if user is None:
            return self._error("NoSuchEntity", name, 404)
        try:
            policy = json.loads(document)
        except ValueError:
            return self._error("MalformedPolicyDocument", "bad JSON", 400)
        user["actions"] = _policy_to_actions(policy)
        user["policy"] = document
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("PutUserPolicy")

    def _do_GetUserPolicy(self, params: dict):
        name = params.get("UserName", "")
        user = self._find_user(self.store.load(), name)
        if user is None or not user.get("policy"):
            return self._error("NoSuchEntity", name, 404)
        return self._ok("GetUserPolicy", {
            "UserName": name,
            "PolicyName": params.get("PolicyName", "default"),
            "PolicyDocument": user["policy"]})

    def _do_DeleteUserPolicy(self, params: dict):
        name = params.get("UserName", "")
        config = self.store.load()
        user = self._find_user(config, name)
        if user is None:
            return self._error("NoSuchEntity", name, 404)
        user.pop("policy", None)
        user["actions"] = []
        self.store.save(config)
        self._sync_s3(config)
        return self._ok("DeleteUserPolicy")
