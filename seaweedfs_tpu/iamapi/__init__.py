from .server import IamApiServer  # noqa: F401
