""".vif sidecar: persisted per-volume info next to the .dat.

The reference persists a VolumeInfo protobuf (version, replica placement,
tiered-file locations) as <volume>.vif via SaveVolumeInfo
(weed/storage/volume_info/volume_info.go:83); JSON here, same role: the
sidecar survives EC encode (the .dat is deleted) so decode/rebuild know the
needle version, and it carries remote-tier file locations.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class RemoteFile:
    backend_type: str = ""
    backend_id: str = ""
    key: str = ""
    offset: int = 0
    file_size: int = 0
    modified_time: int = 0
    extension: str = ""

    def to_dict(self) -> dict:
        return {"backend_type": self.backend_type,
                "backend_id": self.backend_id, "key": self.key,
                "offset": self.offset, "file_size": self.file_size,
                "modified_time": self.modified_time,
                "extension": self.extension}

    @classmethod
    def from_dict(cls, d: dict) -> "RemoteFile":
        return cls(**{k: d.get(k, getattr(cls, k, ""))
                      for k in ("backend_type", "backend_id", "key", "offset",
                                "file_size", "modified_time", "extension")})


@dataclass
class VolumeInfo:
    version: int = 3
    replica_placement: str = "000"
    ttl: str = ""
    compaction_revision: int = 0
    files: list[RemoteFile] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"version": self.version,
                "replica_placement": self.replica_placement,
                "ttl": self.ttl,
                "compaction_revision": self.compaction_revision,
                "files": [f.to_dict() for f in self.files]}


def save_volume_info(path: str, info: VolumeInfo):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info.to_dict(), f, indent=1)
    os.replace(tmp, path)


def load_volume_info(path: str) -> VolumeInfo | None:
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    return VolumeInfo(
        version=int(d.get("version", 3)),
        replica_placement=str(d.get("replica_placement", "000")),
        ttl=str(d.get("ttl", "")),
        compaction_revision=int(d.get("compaction_revision", 0)),
        files=[RemoteFile.from_dict(x) for x in d.get("files", [])])
