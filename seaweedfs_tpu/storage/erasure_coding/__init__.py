"""Erasure coding: RS(10,4) over striped volume blocks, computed on TPU.

File taxonomy per volume v (reference weed/storage/erasure_coding/
ec_encoder.go:17-23, ec_volume.go:66-72):
  v.dat/.idx -> v.ec00..v.ec13 (shards), v.ecx (sorted index copy),
  v.ecj (deletion journal), v.vif (volume info sidecar).
"""

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1 GB
SMALL_BLOCK_SIZE = 1024 * 1024  # 1 MB


def to_ext(ec_index: int) -> str:
    return f".ec{ec_index:02d}"
