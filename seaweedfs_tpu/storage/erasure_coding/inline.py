"""Inline write-path erasure coding: encode at ingest, no read-back.

The legacy pipeline seals a (replicated) volume, reads every byte back
and cuts 14 shard files — `e2e_scale_stages` showed 93% of its wall in
the write stage, with a 3x replica write amplification stacked on top.
Inline EC makes erasure coding the *primary* write path for EC-policy
collections instead: each needle PUT streams straight into the striped
**append-only shard logs** (`.ec00`..`.ec13`), parity rows are encoded
per stripe by a background flusher (through the QoS background device
lane, optionally on the persistent donated-buffer parity step), and a
fixed-size **stripe commit record** is appended to the `.scl` log so a
crashed server replays to the last complete stripe on mount.  Write
amplification is (k+p)/k (1.4x for RS(10,4)) instead of >= 4x, and
parity is always current — degraded reads never wait on an `ec.encode`
batch job.

On-disk layout of an inline EC volume (collection ``c``, volume ``v``):

    c_v.ec00..ec13   shard logs.  The logical needle stream is striped
                     row-major over the family's k data shards in
                     ``stripe unit``-sized blocks (the classic small-
                     block layout of locate.py with zero large rows, so
                     every existing read / locate / recover path works
                     unchanged);  parity shards carry the encoded rows.
    c_v.eci          needle index append log (16-byte idx entries,
                     logical offsets biased by +8).  Flushed before a
                     write is acked.
    c_v.scl          stripe commit log: 192-byte records (format below).
    c_v.vif          JSON sidecar: code family + ``inline_ec`` config
                     (stripe unit), written at create time.
    c_v.ecx/.ecj     empty placeholders so the EcVolume runtime mounts;
                     lookups use the live needle map instead.

Stripe commit record (192 bytes, big-endian, see README "Inline EC
write path" for the field-by-field doc):

    0   magic  b"SCL1"                       (4)
    4   kind   0 = full stripe, 1 = tail     (1)
    5   reserved                             (3)
    8   row_index   stripe row committed     (8)
    16  logical_size  bytes ingested+durable (8)
    24  idx_size      .eci bytes at commit   (8)
    32  stripe_crc32c data row + parity row  (4)
    36  reserved                             (4)
    40  per-shard append offsets, 14 x u64   (112)
    152 reserved                             (36)
    188 record_crc32c over bytes [0, 188)    (4)

Crash recovery on mount (`InlineEcWriter._recover`) replays to the
last valid commit record, then re-adopts every acked tail write: .eci
entries past the record's ``idx_size`` watermark are validated by
re-reading the needle bytes from the data shard logs (header + CRC),
the index is truncated at the first invalid entry, and parity is
recomputed for every stripe row past the last full commit.  Data and
index bytes are written through (pwrite + flush) before a PUT is
acked, so a SIGKILL loses no acked write.

Policy: ``WEED_EC_INLINE=1`` turns the path on; a collection is
EC-policy when the existing coding-tier resolution
(``WEED_EC_CODE_<COLLECTION>`` > PathConf ``ec_code`` > ``WEED_EC_CODE``)
names a family for it.  Non-EC collections and existing volumes are
untouched; the legacy seal-then-encode path remains for mixed clusters.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ...util import faults as _faults
from .. import types as t
from ..needle import Needle, get_actual_size
from ..needle_map import NeedleMap
from . import LARGE_BLOCK_SIZE, TOTAL_SHARDS_COUNT, to_ext
from . import codes as ec_codes
from .ec_volume import (EcNotFoundError, EcDeletedError, EcVolume,
                        EcVolumeShard)
from .encoder import load_volume_info, save_volume_info
from .locate import inline_shard_extent, locate_data

SCL_MAGIC = b"SCL1"
SCL_RECORD_SIZE = 192
KIND_FULL = 0
KIND_TAIL = 1

# Most rows the flusher commits per fused encode call: bounds the batch
# buffer at ~10 MB for the default 64 KiB unit while still amortising
# the kernel dispatch and parity pwrites across a deep backlog.
_MAX_COMMIT_ROWS = 16

# logical offsets in the needle map are biased so offset 0 (a live
# needle at the very start of the stream) is not mistaken for the
# map's "deleted" sentinel (offset == 0); 8 keeps the /8 idx encoding
_OFFSET_BASE = t.NEEDLE_PADDING_SIZE


# -- knobs -------------------------------------------------------------------

def inline_enabled() -> bool:
    """WEED_EC_INLINE=1 turns the inline write path on (default off)."""
    return os.environ.get("WEED_EC_INLINE", "0").lower() \
        not in ("", "0", "false", "no")


def stripe_unit_bytes(family) -> int:
    """Per-shard stripe block size: WEED_EC_STRIPE_KB (default 64 KiB),
    rounded up so a block is divisible by the family's sub-shard (alpha)
    lane count x 8 — alpha-aligned for pm_msr, needle-padding aligned
    for everyone."""
    try:
        kb = int(os.environ.get("WEED_EC_STRIPE_KB", "") or 64)
    except ValueError:
        kb = 64
    unit = max(1, kb) << 10
    align = max(8, family.sub_shards * 8)
    return -(-unit // align) * align


def tail_flush_interval() -> float:
    """Seconds between tail-stripe parity flushes
    (WEED_EC_INLINE_FLUSH_MS, default 500; 0 disables the timer — tail
    parity then only lands on drain/close)."""
    try:
        ms = float(os.environ.get("WEED_EC_INLINE_FLUSH_MS", "") or 500.0)
    except ValueError:
        ms = 500.0
    return max(0.0, ms / 1000.0)


def device_encode_enabled() -> bool:
    """WEED_EC_INLINE_DEVICE=1 routes stripe parity through the
    persistent donated-buffer device parity step (parallel/mesh.py);
    default is the host GF kernel — faster for single stripes on CPU
    harnesses."""
    return os.environ.get("WEED_EC_INLINE_DEVICE", "0").lower() \
        not in ("", "0", "false", "no")


def inline_family_for(collection: str, path_conf=None) -> Optional[str]:
    """The assign-time policy: the family name when ``collection`` is an
    EC-policy collection AND inline encoding is on, else None (create a
    classic replicated volume).

    "EC-policy" reuses the coding tier's resolution order verbatim —
    WEED_EC_CODE_<COLLECTION> > PathConf.ec_code > WEED_EC_CODE — but
    with no built-in default: a collection nobody configured stays on
    the legacy path."""
    if not inline_enabled():
        return None
    name = os.environ.get(ec_codes._collection_env_key(collection))
    if not name:
        name = getattr(path_conf, "ec_code", "") or None
    if not name:
        name = os.environ.get("WEED_EC_CODE")
    if not name:
        return None
    ec_codes.get_family(name)  # validate before any shard log is cut
    return name


# -- stripe commit records ----------------------------------------------------

_REC_HEAD = struct.Struct(">4sB3xQQQI4x")     # 36 bytes
_REC_OFFS = struct.Struct(">14Q")             # 112 bytes


def pack_record(kind: int, row_index: int, logical_size: int,
                idx_size: int, stripe_crc: int,
                shard_offsets: list[int]) -> bytes:
    from ...ops import crc32c as crc32c_mod

    body = _REC_HEAD.pack(SCL_MAGIC, kind, row_index, logical_size,
                          idx_size, stripe_crc & 0xFFFFFFFF)
    body += _REC_OFFS.pack(*shard_offsets)
    body += b"\x00" * (SCL_RECORD_SIZE - 4 - len(body))
    return body + struct.pack(">I", crc32c_mod.crc32c(body))


def unpack_record(buf: bytes) -> Optional[dict]:
    """Parse + validate one record; None when torn/corrupt."""
    from ...ops import crc32c as crc32c_mod

    if len(buf) != SCL_RECORD_SIZE or buf[:4] != SCL_MAGIC:
        return None
    stored = struct.unpack(">I", buf[-4:])[0]
    if stored != crc32c_mod.crc32c(buf[:-4]):
        return None
    magic, kind, row, logical, idx_size, crc = _REC_HEAD.unpack(
        buf[:_REC_HEAD.size])
    offs = _REC_OFFS.unpack(
        buf[_REC_HEAD.size:_REC_HEAD.size + _REC_OFFS.size])
    return {"kind": kind, "row_index": row, "logical_size": logical,
            "idx_size": idx_size, "stripe_crc": crc,
            "shard_offsets": list(offs)}


def read_commit_log(path: str) -> list[dict]:
    """All valid records in append order, stopping at the first torn or
    corrupt one (everything after a torn record is untrusted)."""
    records = []
    try:
        with open(path, "rb") as f:
            while True:
                buf = f.read(SCL_RECORD_SIZE)
                if len(buf) < SCL_RECORD_SIZE:
                    break
                rec = unpack_record(buf)
                if rec is None:
                    break
                records.append(rec)
    except FileNotFoundError:
        pass
    return records


# -- the stripe accumulator ---------------------------------------------------

class InlineEcWriter:
    """Streams needle blobs into striped shard logs, encodes parity per
    stripe row on a background flusher, and appends commit records.

    Thread model: appends serialize on ``_lock``; a single lazy daemon
    flusher thread drains full rows in order (so ``.scl`` rows commit
    monotonically) and flushes the tail stripe on a timer.  Data and
    .eci bytes are durable-in-page-cache before an append returns — the
    ack contract the crash-recovery replay relies on."""

    def __init__(self, base: str, family: Optional[str] = None,
                 unit: Optional[int] = None, create: bool = False,
                 version: int = 3):
        from ...parallel.batched_encode import _WritebackPacer, _write_knobs

        self.base = base
        self.version = version
        info = load_volume_info(base) or {}
        cfg = info.get("inline_ec") or {}
        if not create and not cfg:
            raise ValueError(f"{base}: not an inline EC volume (no "
                             "inline_ec config in .vif)")
        fam_name = family or info.get("code_family")
        self.family = ec_codes.get_family(fam_name)
        self.unit = int(cfg.get("stripe_unit") or unit
                        or stripe_unit_bytes(self.family))
        self.family.check_block(self.unit)
        self.k = self.family.data_shards
        self.p = self.family.total_shards - self.k
        self.row_bytes = self.k * self.unit
        self.large_block = int(cfg.get("large_block") or LARGE_BLOCK_SIZE)
        if create:
            save_volume_info(base, version=version, extra={
                "code_family": self.family.name,
                "inline_ec": {"stripe_unit": self.unit,
                              "large_block": self.large_block}})
            for ext in (".ecx", ".ecj"):
                if not os.path.exists(base + ext):
                    open(base + ext, "ab").close()
        _, _, flush_bytes, drop = _write_knobs()
        self._pacer = _WritebackPacer(flush_bytes, drop)
        # snapshot the log sizes BEFORE O_CREAT: a deleted/lost shard
        # log is recreated empty by the open below, and only this
        # snapshot lets _recover tell "lost device" from "empty log"
        self._premount_sizes = [
            (os.path.getsize(base + to_ext(i))
             if os.path.exists(base + to_ext(i)) else 0)
            for i in range(TOTAL_SHARDS_COUNT)]
        self._fds = [os.open(base + to_ext(i),
                             os.O_CREAT | os.O_RDWR, 0o644)
                     for i in range(TOTAL_SHARDS_COUNT)]
        self._scatter = None
        self._data_fds = None
        try:
            import ctypes

            from ...ops import native as _native

            cdll = _native.lib()
            if cdll is not None and hasattr(cdll, "sw_inline_scatter"):
                self._scatter = cdll.sw_inline_scatter
                self._data_fds = (ctypes.c_int32 * self.k)(
                    *self._fds[:self.k])
        except Exception:
            pass
        self._scl_path = base + ".scl"
        self._scl_fd = os.open(self._scl_path, os.O_CREAT | os.O_RDWR,
                               0o644)
        self._scl_size = os.path.getsize(self._scl_path)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # commit state
        self.logical_size = 0       # bytes of needle stream ingested
        self.durable_rows = 0       # rows with a FULL commit record
        self.committed_logical = 0  # logical size at the last record
        self._idx_bytes = 0         # .eci append position
        self._pending: deque = deque()  # (row_index, bytes) FIFO
        self._next_row = 0          # index of the row the tail is filling
        self._tail = bytearray()
        self._tail_version = 0      # bumped per append into the tail
        self._tail_committed_version = 0
        self._tail_parity_cache = None  # (row, version) -> (p, unit)
        self._closed = False
        self._flusher: Optional[threading.Thread] = None
        self._dev_step = None       # (step, out_buf) for the device path
        self._metric_handles = None  # cached (logical counter, tail gauge)
        # accounting (physical bytes this writer put on disk)
        self.physical_bytes = 0
        self.stripes_committed = 0
        if not create and os.path.exists(base + ".eci"):
            self._recover()
        self.nm = NeedleMap(base + ".eci")
        self._idx_bytes = os.path.getsize(base + ".eci")

    # -- geometry helpers ---------------------------------------------------

    def shard_extent(self, shard_id: int,
                     logical: Optional[int] = None) -> int:
        """Valid bytes in shard ``shard_id``'s log at logical size L."""
        logical = self.logical_size if logical is None else logical
        if shard_id >= self.k:  # parity extends per committed row
            rows = self.durable_rows
            if self.committed_logical > rows * self.row_bytes:
                rows += 1  # a tail record padded the partial row
            return rows * self.unit
        return inline_shard_extent(logical, self.unit, self.k, shard_id)

    @property
    def tail_bytes(self) -> int:
        return len(self._tail)

    def write_amp(self) -> float:
        if not self.logical_size:
            return 0.0
        return self.physical_bytes / float(self.logical_size)

    # -- append path --------------------------------------------------------

    def append(self, nid: int, size_field: int, blob: bytes) -> int:
        """Write one full needle record into the stream; returns its
        logical offset.  The blob (header..padding) must be 8-aligned,
        which Needle.to_bytes guarantees."""
        if len(blob) % t.NEEDLE_PADDING_SIZE:
            raise ValueError(
                f"needle blob not {t.NEEDLE_PADDING_SIZE}-aligned")
        with self._cond:
            if self._closed:
                raise OSError("inline EC writer closed")
            off = self.logical_size
            self._pwrite_logical(off, blob)
            self.logical_size = off + len(blob)
            self._tail += blob
            self._tail_version += 1
            was_idle = not self._pending
            cut = False
            while len(self._tail) >= self.row_bytes:
                row = bytes(self._tail[:self.row_bytes])
                del self._tail[:self.row_bytes]
                self._pending.append((self._next_row, row))
                self._next_row += 1
                cut = True
            self.nm.put(nid, off + _OFFSET_BASE, size_field)
            self.nm.flush()  # acked writes survive SIGKILL
            self._idx_bytes += t.NEEDLE_MAP_ENTRY_SIZE
            self.physical_bytes += len(blob) + t.NEEDLE_MAP_ENTRY_SIZE
            self._ensure_flusher()
            if cut and was_idle:
                # the flusher re-checks _pending before every wait, so
                # only the empty->non-empty edge needs a wakeup; per-cut
                # notifies just ping-pong the lock with the flusher
                self._cond.notify_all()
        self._note_metrics(len(blob))
        return off

    def delete(self, nid: int):
        with self._cond:
            nv = self.nm.get(nid)
            if nv is None or t.size_is_deleted(nv.size):
                return
            self.nm.delete(nid, nv.offset)
            self.nm.flush()
            self._idx_bytes += t.NEEDLE_MAP_ENTRY_SIZE
            self.physical_bytes += t.NEEDLE_MAP_ENTRY_SIZE

    def _pwrite_logical(self, offset: int, blob: bytes):
        """Write-through: scatter the blob's bytes to their striped
        positions in the data shard logs (no .dat, no read-back).

        Fast path: while the volume sits in the pure-small-row regime
        (zero large rows — everything below ~k GB), block ``i`` lives at
        shard ``i % k`` offset ``(i // k) * unit``, so the scatter is
        two divmods per segment instead of the general interval map."""
        size = len(blob)
        view = memoryview(blob)  # zero-copy segment slicing
        if offset + size < self.k * (self.large_block - self.unit):
            if self._scatter is not None and not _faults.ACTIVE:
                # all segment pwrites in one GIL-dropping native call;
                # chaos runs take the per-segment path so the disk
                # fault hooks still see every shard write
                rc = self._scatter(self._data_fds, self.k, self.unit,
                                   offset, bytes(blob), size)
                if rc == 0:
                    if self._pacer.flush_bytes > 0:
                        pos = 0
                        while pos < size:  # accounting only, no I/O
                            block, inner = divmod(offset + pos, self.unit)
                            row, sid = divmod(block, self.k)
                            take = min(size - pos, self.unit - inner)
                            self._pacer.wrote(self._fds[sid],
                                              row * self.unit + inner, take)
                            pos += take
                    return
                raise OSError(-rc, os.strerror(-rc))
            pos = 0
            while pos < size:
                block, inner = divmod(offset + pos, self.unit)
                row, sid = divmod(block, self.k)
                take = min(size - pos, self.unit - inner)
                self._pwrite_shard(sid, row * self.unit + inner,
                                   view[pos:pos + take])
                pos += take
            return
        pos = 0
        for iv in locate_data(self.large_block, self.unit,
                              max(self.logical_size, offset + len(blob)),
                              offset, len(blob), data_shards=self.k):
            sid, inner = iv.to_shard_id_and_offset(
                self.large_block, self.unit, data_shards=self.k)
            seg = view[pos:pos + iv.size]
            pos += iv.size
            self._pwrite_shard(sid, inner, seg)

    def _pwrite_shard(self, shard_id: int, offset: int, buf):
        from ...parallel.batched_encode import _pwritev_full

        if _faults.ACTIVE:
            _faults.on_disk(self.base + to_ext(shard_id), "write")
        fd = self._fds[shard_id]
        _pwritev_full(fd, [buf], offset)
        self._pacer.wrote(fd, offset, len(buf))

    # -- tail reads (partially-filled stripe) --------------------------------

    def tail_read(self, shard_id: int, offset: int,
                  size: int) -> Optional[bytes]:
        """Serve a shard-log span out of the in-memory stripe state:
        data and parity of rows still pending commit, and the zero-
        padded tail row.  Returns None for spans this writer cannot
        cover (then the disk / remote / reconstruct ladder applies)."""
        out = bytearray()
        while size > 0:
            row = offset // self.unit
            inner = offset % self.unit
            take = min(size, self.unit - inner)
            seg = self._row_segment(row, shard_id)
            if seg is None:
                return None
            out += seg[inner:inner + take]
            offset += take
            size -= take
        return bytes(out)

    def _row_segment(self, row: int, shard_id: int) -> Optional[bytes]:
        with self._lock:
            row_data = None
            first_pending = (self._pending[0][0] if self._pending
                             else self._next_row)
            if row < first_pending:
                return None  # already durable: read from disk
            for r, data in self._pending:
                if r == row:
                    row_data = data
                    break
            if row_data is None:
                if row != self._next_row:
                    return None
                if not self._tail:
                    return None
                row_data = bytes(self._tail).ljust(self.row_bytes, b"\x00")
                cache_key = (row, self._tail_version)
            else:
                cache_key = (row, -1)
            if shard_id < self.k:
                return row_data[shard_id * self.unit:
                                (shard_id + 1) * self.unit]
            cached = self._tail_parity_cache
            if cached is not None and cached[0] == cache_key:
                parity = cached[1]
            else:
                parity = self._encode_row(row_data)
                self._tail_parity_cache = (cache_key, parity)
            return parity[shard_id - self.k].tobytes()

    # -- parity encode -------------------------------------------------------

    def _encode_row(self, row: bytes) -> np.ndarray:
        """(k * unit,) row bytes -> (p, unit) parity."""
        return self._encode_span(np.frombuffer(row, dtype=np.uint8)
                                 .reshape(self.k, self.unit))

    def _encode_span(self, data: np.ndarray) -> np.ndarray:
        """(k, W) data blocks -> (p, W) parity, via the host GF kernel
        or the persistent donated-buffer device parity step.  W is any
        multiple of the (alpha-aligned) stripe unit: GF math is
        column-wise, so a batch of consecutive rows encodes in one
        call with each row's parity landing in its own W-slice."""
        from ...ops.codec import _apply_rows_host

        if device_encode_enabled():
            try:
                if data.shape[1] == self.unit:
                    return self._encode_row_device(data)
                # the donated device step is compiled at unit width:
                # feed a batch through it row by row
                return np.hstack([
                    self._encode_row_device(np.ascontiguousarray(
                        data[:, o:o + self.unit]))
                    for o in range(0, data.shape[1], self.unit)])
            except Exception:
                pass  # device path is best-effort; host always works
        # the native AVX2/GFNI ladder, not the NumPy table reference —
        # per-stripe encode sits on the ack path's critical drain
        return self.family.encode_blocks(data, apply_fn=_apply_rows_host)

    def _encode_row_device(self, data: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        from ...parallel import mesh as mesh_mod

        fam = self.family
        alpha = fam.sub_shards
        lanes = np.ascontiguousarray(fam.to_lanes(data))
        ka = lanes.shape[0]
        data32 = lanes.reshape(ka, 1, -1).view(np.int32)
        if self._dev_step is None:
            mesh = mesh_mod.make_ec_mesh(mesh_mod.shard_devices()[:1])
            step = mesh_mod.make_parity_step(
                mesh, matrix=fam.parity_matrix(),
                key=("inline", fam.name, self.unit))
            out = jnp.zeros((self.p * alpha, 1, data32.shape[2]),
                            dtype=jnp.int32)
            self._dev_step = [step, out]
        step, out = self._dev_step
        parity_dev = step(jnp.asarray(data32), out)
        parity = np.asarray(parity_dev)
        self._dev_step[1] = parity_dev  # donated slot for the next row
        lanes_out = parity.reshape(self.p * alpha, -1).view(np.uint8)
        return np.ascontiguousarray(fam.from_lanes(lanes_out))

    # -- the flusher ---------------------------------------------------------

    def _ensure_flusher(self):
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"inline-ec-flush")
            self._flusher.start()

    def _flush_loop(self):
        while True:
            task = None
            with self._cond:
                while task is None:
                    if self._pending:
                        # drain a contiguous run of cut rows in one
                        # batch: one fused encode + one parity pwrite
                        # per shard instead of per-row calls
                        batch = []
                        for r, row in self._pending:
                            if batch and r != batch[-1][0] + 1:
                                break
                            batch.append((r, row))
                            if len(batch) >= _MAX_COMMIT_ROWS:
                                break
                        task = ("rows", batch)
                        break
                    dirty = (self._tail
                             and self._tail_version
                             != self._tail_committed_version)
                    if self._closed:
                        task = ("tail",) if dirty else ("exit",)
                        break
                    interval = tail_flush_interval()
                    if dirty and interval <= 0:
                        dirty = False
                    if not self._cond.wait(
                            timeout=interval if dirty else 1.0):
                        if dirty:
                            task = ("tail",)
                            break
            if task[0] == "exit":
                return
            try:
                if task[0] == "rows":
                    self._commit_rows(task[1])
                    with self._cond:
                        done = {r for r, _ in task[1]}
                        while self._pending and \
                                self._pending[0][0] in done:
                            self._pending.popleft()
                        self._cond.notify_all()
                else:
                    self._commit_tail()
            except Exception:
                # a failing commit must not kill the flusher; the row
                # stays pending and recovery recomputes it on mount
                time.sleep(0.05)

    def _commit_row(self, row_index: int, row: bytes):
        self._commit_rows([(row_index, row)])

    def _commit_rows(self, batch: list):
        """Encode + write a contiguous run of full stripe rows' parity
        in ONE fused kernel call and one pwrite per parity shard, then
        append the per-row commit records — the background device lane
        yields to foreground degraded-read decodes first."""
        from ...qos.lanes import LANES

        t0 = time.perf_counter()
        LANES.background_checkpoint()
        first = batch[0][0]
        unit = self.unit
        data = np.empty((self.k, len(batch) * unit), dtype=np.uint8)
        for i, (_, row) in enumerate(batch):
            data[:, i * unit:(i + 1) * unit] = np.frombuffer(
                row, dtype=np.uint8).reshape(self.k, unit)
        parity = self._encode_span(data)
        # parity[j] is already the shard log segment for rows
        # first..first+R-1 laid end to end: one write per parity shard
        for j in range(self.p):
            self._pwrite_shard(self.k + j, first * unit,
                               parity[j].tobytes())
        with self._lock:
            logical = self.logical_size
            idx_size = self._idx_bytes
        for i, (row_index, row) in enumerate(batch):
            self._append_record(
                KIND_FULL, row_index, logical, idx_size, row,
                np.ascontiguousarray(parity[:, i * unit:(i + 1) * unit]))
        with self._lock:
            self.durable_rows = max(self.durable_rows,
                                    batch[-1][0] + 1)
            self.committed_logical = max(self.committed_logical, logical)
            self.physical_bytes += len(batch) * (self.p * unit
                                                 + SCL_RECORD_SIZE)
        self._note_commit(KIND_FULL, time.perf_counter() - t0,
                          rows=len(batch))

    def _commit_tail(self):
        from ...qos.lanes import LANES

        t0 = time.perf_counter()
        with self._lock:
            if not self._tail:
                return
            row_index = self._next_row
            version = self._tail_version
            row = bytes(self._tail).ljust(self.row_bytes, b"\x00")
            logical = self.logical_size
            idx_size = self._idx_bytes
        LANES.background_checkpoint()
        parity = self._encode_row(row)
        for i in range(self.p):
            self._pwrite_shard(self.k + i, row_index * self.unit,
                               parity[i].tobytes())
        self._append_record(KIND_TAIL, row_index, logical, idx_size,
                            row, parity)
        with self._lock:
            self._tail_committed_version = version
            self.committed_logical = max(self.committed_logical, logical)
            self.physical_bytes += self.p * self.unit + SCL_RECORD_SIZE
        self._note_commit(KIND_TAIL, time.perf_counter() - t0)

    def _append_record(self, kind: int, row_index: int, logical: int,
                       idx_size: int, row: bytes, parity: np.ndarray):
        from ...ops import crc32c as crc32c_mod
        from ...parallel.batched_encode import _pwritev_full

        crc = crc32c_mod.crc32c(row)
        crc = crc32c_mod.crc32c(np.ascontiguousarray(parity).tobytes(),
                                crc)
        offs = [self.shard_extent(i, logical) if i < self.k
                else (row_index + 1) * self.unit
                for i in range(TOTAL_SHARDS_COUNT)]
        rec = pack_record(kind, row_index, logical, idx_size, crc, offs)
        if _faults.ACTIVE:
            _faults.on_disk(self._scl_path, "commit")
        _pwritev_full(self._scl_fd, [rec], self._scl_size)
        self._scl_size += SCL_RECORD_SIZE
        self.stripes_committed += 1

    # -- drain / close -------------------------------------------------------

    def drain(self, tail: bool = True, timeout: float = 30.0):
        """Block until every cut row is committed; with ``tail`` also
        force a tail-stripe commit of whatever is buffered."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._ensure_flusher()
            self._cond.notify_all()
            while self._pending:
                if not self._cond.wait(
                        timeout=max(0.0, deadline - time.monotonic())):
                    break
                if time.monotonic() >= deadline:
                    break
        if tail:
            self._commit_tail()

    def sync(self):
        for fd in self._fds:
            os.fsync(fd)
        os.fsync(self._scl_fd)
        self.nm.sync()

    def close(self, final_flush: bool = True):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        flusher = self._flusher
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout=10.0)
        if final_flush:
            # drain anything the flusher left behind
            while True:
                with self._lock:
                    item = self._pending.popleft() if self._pending \
                        else None
                if item is None:
                    break
                self._commit_row(*item)
            if self._tail and \
                    self._tail_version != self._tail_committed_version:
                self._commit_tail()
        self.nm.close()
        self._pacer.forget(self._fds)
        for fd in self._fds:
            os.close(fd)
        os.close(self._scl_fd)

    # -- crash recovery -------------------------------------------------------

    def _recover(self):
        """Mount-time replay: last valid commit record -> validate acked
        tail writes from the .eci log -> recompute tail parity."""
        records = read_commit_log(self._scl_path)
        durable_rows = 0
        committed_logical = 0
        trusted_idx = 0
        if records:
            last = records[-1]
            committed_logical = last["logical_size"]
            trusted_idx = last["idx_size"]
            durable_rows = last["row_index"] + (
                1 if last["kind"] == KIND_FULL else 0)
        # drop any torn trailing record
        valid_scl = len(records) * SCL_RECORD_SIZE
        if valid_scl != self._scl_size:
            os.ftruncate(self._scl_fd, valid_scl)
            self._scl_size = valid_scl
        # a shard log shorter than its committed extent is a lost or
        # replaced device, not a crash: heal it from the survivors
        # before anything below reads the data logs
        self._heal_short_shards(
            committed_logical, durable_rows,
            tail_rows=1 if records and records[-1]["kind"] == KIND_TAIL
            else 0)
        logical, idx_keep = self._replay_idx(committed_logical,
                                             trusted_idx)
        self.logical_size = logical
        self._idx_bytes = idx_keep
        self.durable_rows = durable_rows
        # canonicalize the logs: un-acked pre-crash bytes past each
        # shard's valid extent must never be readable (parity below is
        # recomputed over zero padding, and degraded reads zero-fill
        # past a data log's end on the same assumption)
        for sid in range(self.k):
            os.ftruncate(self._fds[sid], inline_shard_extent(
                logical, self.unit, self.k, sid))
        for i in range(self.p):
            os.ftruncate(self._fds[self.k + i], durable_rows * self.unit)
        self.committed_logical = committed_logical
        self._next_row = logical // self.row_bytes
        # reload the tail row's valid bytes so later appends and tail
        # parity see the real stream (never garbage past `logical`)
        self._tail = bytearray(self._read_logical(
            self._next_row * self.row_bytes,
            logical - self._next_row * self.row_bytes))
        self._tail_version = 1
        # recompute parity for every row past the last FULL commit —
        # the "replay to last complete stripe" step
        for row in range(durable_rows, self._next_row):
            start = row * self.row_bytes
            self._commit_row(row, self._read_logical(
                start, self.row_bytes))
        if self._tail:
            self._commit_tail()

    def _replay_idx(self, committed_logical: int,
                    trusted_idx: int) -> tuple[int, int]:
        """Walk the .eci append log in order; entries past the commit
        watermark are validated against the shard-log bytes.  Truncates
        the log at the first invalid entry.  Returns (logical size,
        kept idx bytes)."""
        from .. import idx as idx_mod

        path = self.base + ".eci"
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = b""
        esz = t.NEEDLE_MAP_ENTRY_SIZE
        keep = len(raw) - len(raw) % esz
        logical = committed_logical
        pos = 0
        while pos + esz <= keep:
            nid, offset, size = idx_mod.unpack_entry(raw[pos:pos + esz])
            if offset == 0 or size == t.TOMBSTONE_FILE_SIZE:
                pos += esz
                continue  # tombstone: no data bytes to validate
            start = offset - _OFFSET_BASE
            end = start + get_actual_size(size, self.version)
            if pos + esz <= trusted_idx and end <= committed_logical:
                logical = max(logical, end)
                pos += esz
                continue
            blob = self._read_logical(start, end - start,
                                      limit=max(logical, end))
            n = Needle()
            try:
                n.read_bytes(blob, start, size, self.version)
                if n.id != nid:
                    raise ValueError("id mismatch")
            except Exception:
                keep = pos  # first invalid entry: cut here
                break
            logical = max(logical, end)
            pos += esz
        if keep < len(raw):
            with open(path, "r+b") as f:
                f.truncate(keep)
        return logical, keep

    def _heal_short_shards(self, committed_logical: int,
                           durable_rows: int, tail_rows: int):
        """Rebuild the committed region of any shard log that mounted
        shorter than its committed extent (deleted, truncated or
        replaced on a fresh device — O_CREAT has already recreated a
        missing log as an empty file, so without this reads would
        serve zeros instead of reconstructing).  Data columns are
        decoded row-by-row from k survivors against the committed
        parity; parity columns are then re-encoded from the (healed)
        data.  Bytes past the commit watermark are not recoverable
        from a lost device and are handled by the idx replay, which
        drops entries whose bytes no longer validate."""
        if committed_logical <= 0:
            return
        total = self.k + self.p
        n_rows = durable_rows + tail_rows      # parity rows on disk
        data_rows = -(-committed_logical // self.row_bytes)
        # first damaged row per shard (== intact up to that row)
        dmg = {}
        for sid in range(total):
            if sid < self.k:
                expect = inline_shard_extent(
                    committed_logical, self.unit, self.k, sid)
            else:
                expect = n_rows * self.unit
            have = min(self._premount_sizes[sid], expect)
            if have < expect:
                dmg[sid] = have // self.unit
        if not dmg:
            return

        def column(sid: int, row: int) -> bytes:
            """shard ``sid``'s unit for stripe ``row``, zero-padded to
            the committed extent like the parity was encoded over."""
            off = row * self.unit
            if sid < self.k:
                valid = inline_shard_extent(
                    committed_logical, self.unit, self.k, sid)
                take = max(0, min(self.unit, valid - off))
            else:
                take = self.unit
            buf = os.pread(self._fds[sid], take, off) if take else b""
            return buf.ljust(self.unit, b"\x00")

        for row in range(data_rows):
            targets = [sid for sid, frow in dmg.items()
                       if sid < self.k and frow <= row]
            if not targets:
                continue
            alive = [sid for sid in range(total)
                     if dmg.get(sid, n_rows + 1) > row
                     and (sid < self.k or row < n_rows)]
            try:
                survivors = self.family.choose_survivors(alive)
            except Exception as e:
                raise OSError(
                    f"{self.base}: inline EC volume lost shards "
                    f"{sorted(dmg)} beyond the {self.family.name} "
                    f"tolerance; stripe row {row} is unrecoverable"
                ) from e
            inputs = np.stack([
                np.frombuffer(column(sid, row), dtype=np.uint8)
                for sid in survivors])
            out = self.family.decode_blocks(survivors, inputs, targets)
            for i, sid in enumerate(targets):
                self._pwrite_shard(sid, row * self.unit,
                                   out[i].tobytes())
        # parity columns: re-encode every damaged row from the data
        for row in range(n_rows):
            targets = [sid for sid, frow in dmg.items()
                       if sid >= self.k and frow <= row]
            if not targets:
                continue
            row_data = b"".join(column(sid, row)
                                for sid in range(self.k))
            parity = self._encode_row(row_data)
            for sid in targets:
                self._pwrite_shard(sid, row * self.unit,
                                   parity[sid - self.k].tobytes())

    def _read_logical(self, offset: int, size: int,
                      limit: Optional[int] = None) -> bytes:
        """Gather a logical-stream span back out of the data shard
        logs, zero-padding past each shard's valid extent (so garbage
        beyond the replayed logical size never pollutes parity)."""
        if size <= 0:
            return b""
        limit = self.logical_size if limit is None else limit
        out = bytearray()
        for iv in locate_data(self.large_block, self.unit,
                              max(limit, offset + size), offset, size,
                              data_shards=self.k):
            sid, inner = iv.to_shard_id_and_offset(
                self.large_block, self.unit, data_shards=self.k)
            valid = inline_shard_extent(limit, self.unit, self.k, sid)
            take = max(0, min(iv.size, valid - inner))
            buf = os.pread(self._fds[sid], take, inner) if take else b""
            if len(buf) < iv.size:
                buf += b"\x00" * (iv.size - len(buf))
            out += buf
        return bytes(out)

    # -- telemetry ------------------------------------------------------------

    def _note_metrics(self, nbytes: int):
        try:
            handles = self._metric_handles
            if handles is None:
                from ...stats import metrics as _stats

                handles = self._metric_handles = (
                    _stats.EcInlineBytesCounter.labels("logical"),
                    _stats.EcInlineTailBytes)
            handles[0].inc(nbytes)
            handles[1].set(len(self._tail))
        except Exception:
            pass

    def _note_commit(self, kind: int, seconds: float, rows: int = 1):
        try:
            from ...stats import metrics as _stats

            _stats.EcInlineStripesCommitted.labels(
                "tail" if kind == KIND_TAIL else "full").inc(rows)
            _stats.EcInlineCommitSeconds.observe(seconds)
            _stats.EcInlineTailBytes.set(len(self._tail))
            _stats.EcInlineWriteAmp.set(round(self.write_amp(), 4))
            _stats.EcInlineBytesCounter.labels("physical").inc(
                rows * (self.p * self.unit + SCL_RECORD_SIZE))
        except Exception:
            pass

    def status(self) -> dict:
        with self._lock:
            return {
                "family": self.family.name,
                "stripe_unit": self.unit,
                "logical_size": self.logical_size,
                "committed_logical": self.committed_logical,
                "durable_rows": self.durable_rows,
                "pending_rows": len(self._pending),
                "tail_bytes": len(self._tail),
                "stripes_committed": self.stripes_committed,
                "physical_bytes": self.physical_bytes,
                "write_amp": round(self.write_amp(), 4),
                "file_count": self.nm.file_count,
                "deleted_count": self.nm.deleted_count,
            }


# -- the volume ---------------------------------------------------------------

class InlineEcVolume(EcVolume):
    """An EC volume that is written inline: all 14 shard logs live on
    this server, lookups go through the live needle map (the sorted
    .ecx only exists for sealed volumes), and reads reuse the whole
    EcVolume ladder — local shard pread, the in-memory tail stripe,
    then reconstruction."""

    def __init__(self, directory: str, collection: str, vid: int,
                 family: Optional[str] = None, create: bool = False,
                 stripe_unit: Optional[int] = None, version: int = 3):
        base = (os.path.join(directory, f"{collection}_{vid}")
                if collection else os.path.join(directory, str(vid)))
        self.writer = InlineEcWriter(base, family=family,
                                     unit=stripe_unit, create=create,
                                     version=version)
        super().__init__(directory, collection, vid, version=version,
                         large_block_size=self.writer.large_block,
                         small_block_size=self.writer.unit)
        for sid in range(TOTAL_SHARDS_COUNT):
            if os.path.exists(base + to_ext(sid)):
                self.add_shard(EcVolumeShard(directory, collection, vid,
                                             sid))
        self.tail_reader = self.writer.tail_read
        self.read_only = False
        self.last_modified_ts = time.time()

    # heartbeat / master bookkeeping ------------------------------------------
    @property
    def is_inline(self) -> bool:
        return True

    @property
    def logical_size(self):
        return self.writer.logical_size

    @logical_size.setter
    def logical_size(self, _):
        pass  # EcVolume.__init__ default assignment; writer owns it

    @property
    def shard_size(self) -> int:
        rows = -(-self.writer.logical_size // self.writer.row_bytes)
        return rows * self.writer.unit

    def file_count(self) -> int:
        return self.writer.nm.file_count

    def deleted_count(self) -> int:
        return self.writer.nm.deleted_count

    def deleted_size(self) -> int:
        return self.writer.nm.deleted_bytes

    def max_file_key(self) -> int:
        return self.writer.nm.max_file_key()

    # -- write path -----------------------------------------------------------
    def write_needle(self, n: Needle,
                     check_cookie: bool = True) -> tuple[int, int, bool]:
        if not n.append_at_ns:
            n.append_at_ns = time.time_ns()
        blob = n.to_bytes(self.version)
        off = self.writer.append(n.id, n.size, blob)
        self.last_modified_ts = time.time()
        return off, n.size, False

    def delete_needle(self, needle_id: int):
        self.writer.delete(needle_id)
        self.last_modified_ts = time.time()

    # -- read path ------------------------------------------------------------
    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        nv = self.writer.nm.get(needle_id)
        if nv is None:
            raise EcNotFoundError(f"needle {needle_id:x} not found")
        if t.size_is_deleted(nv.size):
            raise EcDeletedError(f"needle {needle_id:x} deleted")
        return nv.offset - _OFFSET_BASE, nv.size

    # -- lifecycle ------------------------------------------------------------
    def close(self):
        self.writer.close()
        super().close()

    def destroy(self):
        self.writer.close(final_flush=False)
        super().destroy()
        for ext in (".scl", ".eci"):
            try:
                os.remove(self.base_file_name() + ext)
            except FileNotFoundError:
                pass


# -- deep-scrub audit ---------------------------------------------------------

def verify_inline_volume(directory: str, collection: str,
                         vid: int) -> dict:
    """The curator's deep-scrub for inline volumes: mount (running the
    crash-recovery replay), recompute every committed stripe row's
    parity and CRC against the shard logs and the commit records, then
    re-read every live needle (header + CRC).  Same result shape as
    deep_scrub_host."""
    ev = InlineEcVolume(directory, collection, vid)
    try:
        return audit_inline_volume(ev)
    finally:
        ev.close()


def audit_inline_volume(ev: "InlineEcVolume") -> dict:
    """Audit an already-mounted inline volume (the maintenance worker's
    deep-scrub job runs against the live writer)."""
    from ...ops import crc32c as crc32c_mod

    w = ev.writer
    bad_rows: list[int] = []
    checked = bad = 0
    bad_needles: list[int] = []
    w.drain()
    records = read_commit_log(w._scl_path)
    latest: dict[int, dict] = {}
    for rec in records:
        latest[rec["row_index"]] = rec
    for row_index, rec in sorted(latest.items()):
        row = w._read_logical(row_index * w.row_bytes, w.row_bytes)
        parity_bytes = np.ascontiguousarray(
            w._encode_row(row)).tobytes()
        on_disk = b"".join(
            os.pread(w._fds[w.k + i], w.unit, row_index * w.unit)
            for i in range(w.p))
        if on_disk != parity_bytes:
            bad_rows.append(row_index)
            continue
        # a full stripe is immutable after commit, so its recorded
        # CRC must still match; a tail record's row keeps growing —
        # only the freshest one is checkable against current bytes
        if rec["kind"] == KIND_FULL \
                or rec["logical_size"] == w.logical_size:
            crc = crc32c_mod.crc32c(parity_bytes,
                                    crc32c_mod.crc32c(row))
            if crc != rec["stripe_crc"]:
                bad_rows.append(row_index)
    for nid, nv in list(w.nm.items_ascending()):
        if t.size_is_deleted(nv.size):
            continue
        checked += 1
        try:
            ev.read_needle(nid)
        except Exception:
            bad += 1
            if len(bad_needles) < 64:
                bad_needles.append(nid)
    return {"volume": ev.volume_id, "collection": ev.collection,
            "inline": True,
            "rows_checked": len(latest),
            "corrupt": sorted(set(bad_rows)), "missing": [],
            "clean": not bad_rows,
            "needles_checked": checked, "needles_bad": bad,
            "bad_needles": bad_needles,
            "ok": not (bad_rows or bad)}
