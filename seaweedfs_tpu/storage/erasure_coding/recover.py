"""Fast degraded-read machinery: recovered-block cache, single-flight
coalescing, batched multi-span decode, and per-stage stats.

The decode-side counterpart of the encode pipeline's write-behind stage.
A dead shard mid-incident is read by MANY clients at once, usually at
adjacent offsets; the naive ladder re-fetches 10 survivor spans and
re-runs the GF math per request.  Here:

  * recoveries are BLOCK-ALIGNED and the recovered blocks live in a
    bounded LRU (pattern: filer/reader_cache.py ChunkCache), so
    back-to-back reads of the same dead block are a dict hit;
  * concurrent misses on the same block are SINGLE-FLIGHTED: one leader
    does the survivor fan-out + decode, the rest wait on its result
    (an error propagates to the waiters but is never cached — the next
    read retries with whatever survivors are healthy then);
  * concurrent misses on DIFFERENT blocks that resolved the same
    survivor set are stacked column-wise and decoded in one GF mat-vec
    (the read-side analogue of parallel/batched_encode.py's span
    batching: the decode row is per-(survivors, target), so spans
    concatenate for free).

Knobs (env, read per call so daemons/tests flip them live):
  WEED_EC_RECOVER_CACHE_MB   recovered-block LRU budget per EC volume
                             (default 64; 0 disables caching)
  WEED_EC_RECOVER_BLOCK_KB   recovery granularity (default 256; 0 =
                             exact spans, no alignment)
  WEED_EC_RECOVER_COALESCE   0 disables single-flight + batching
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ... import tracing


def recover_knobs() -> tuple[int, int, bool]:
    """(cache_bytes, block_bytes, coalesce) from the WEED_EC_RECOVER_*
    env knobs."""
    mb = os.environ.get("WEED_EC_RECOVER_CACHE_MB", "")
    cache_bytes = int(float(mb) * (1 << 20)) if mb else (64 << 20)
    kb = os.environ.get("WEED_EC_RECOVER_BLOCK_KB", "")
    block_bytes = int(float(kb) * 1024) if kb else (256 << 10)
    coalesce = os.environ.get("WEED_EC_RECOVER_COALESCE", "1").lower() \
        not in ("0", "false", "no")
    return cache_bytes, block_bytes, coalesce


class RecoverStats:
    """Cumulative degraded-read telemetry, process-wide.  Busy seconds
    per stage (fetch = survivor reads, decode = GF math, serve = span
    assembly/cache bookkeeping around them) plus cache and coalescing
    counters; mirrored into the Prometheus vectors on every update."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self.fetch_seconds = 0.0
            self.decode_seconds = 0.0
            self.serve_seconds = 0.0
            self.cache_hits = 0
            self.cache_misses = 0
            self.coalesced = 0
            self.spans = 0
            self.batches = 0
            self.batched_spans = 0
            self.recovered_bytes = 0

    def add_stage(self, stage: str, seconds: float):
        with self._lock:
            if stage == "fetch":
                self.fetch_seconds += seconds
            elif stage == "decode":
                self.decode_seconds += seconds
            else:
                self.serve_seconds += seconds
        self._push_stage(stage)

    def _push_stage(self, stage: str):
        from ...stats import metrics as stats

        with self._lock:
            val = {"fetch": self.fetch_seconds,
                   "decode": self.decode_seconds,
                   "serve": self.serve_seconds}[stage]
        stats.EcRecoverStageSeconds.labels(stage).set(round(val, 6))

    def cache_event(self, result: str, n: int = 1):
        from ...stats import metrics as stats

        with self._lock:
            if result == "hit":
                self.cache_hits += n
            elif result == "miss":
                self.cache_misses += n
            else:
                self.coalesced += n
        stats.EcRecoverCacheCounter.labels(result).inc(n)

    def decoded(self, n_spans: int, nbytes: int):
        from ...stats import metrics as stats

        with self._lock:
            self.spans += n_spans
            self.batches += 1
            if n_spans > 1:
                self.batched_spans += n_spans
            self.recovered_bytes += nbytes
        stats.EcRecoverSpanCounter.labels(
            "batched" if n_spans > 1 else "solo").inc(n_spans)
        stats.EcRecoverBytesCounter.inc(nbytes)

    def snapshot(self, wall: Optional[float] = None) -> dict:
        """Point-in-time dict of everything above; with `wall` (seconds
        of observed load) stage busy fractions are included — the
        degraded-read pipeline's own answer to "which stage is the
        bottleneck"."""
        with self._lock:
            out = {
                "fetch_seconds": round(self.fetch_seconds, 3),
                "decode_seconds": round(self.decode_seconds, 3),
                "serve_seconds": round(self.serve_seconds, 3),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "coalesced": self.coalesced,
                "spans": self.spans,
                "batches": self.batches,
                "batched_spans": self.batched_spans,
                "recovered_bytes": self.recovered_bytes,
            }
        lookups = out["cache_hits"] + out["cache_misses"]
        out["cache_hit_ratio"] = (
            round(out["cache_hits"] / lookups, 3) if lookups else 0.0)
        if wall and wall > 0:
            for k in ("fetch", "decode", "serve"):
                out[f"{k}_frac"] = round(out[f"{k}_seconds"] / wall, 3)
        # the device slab pool serving the recover device path: resident
        # hits here are survivor-stack uploads the pool saved (one
        # incident's repeated decodes against the same survivor set)
        from ...ops import device_pool

        pool = device_pool.get_pool()
        snap = pool.snapshot()
        out["device_pool"] = {
            k: snap[k] for k in ("resident_slabs", "resident_hits",
                                 "resident_misses", "bytes",
                                 "evictions")}
        return out


STATS = RecoverStats()


class _Flight:
    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value: Optional[bytes] = None
        self.error: Optional[BaseException] = None


class RecoveredBlockCache:
    """Bounded byte-budget LRU of recovered shard blocks with
    single-flight miss coalescing.  Keys are (shard_id, offset, length);
    entries are the recovered bytes — immutable content (EC shard files
    never change after encode), so there is no invalidation story beyond
    eviction."""

    def __init__(self, stats: RecoverStats = STATS):
        self._data: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._flights: dict[tuple, _Flight] = {}
        self.stats = stats

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._data)

    def clear(self):
        with self._lock:
            self._data.clear()
            self._bytes = 0

    def _get(self, key: tuple) -> Optional[bytes]:
        with self._lock:
            data = self._data.get(key)
            if data is not None:
                self._data.move_to_end(key)
            return data

    def _put(self, key: tuple, data: bytes, capacity: int):
        if len(data) > capacity:
            return  # oversized: never cache (chunk_cache size gate)
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[key] = data
            self._bytes += len(data)
            while self._bytes > capacity:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)

    def get_or_recover(self, key: tuple, recover: Callable[[], bytes],
                       capacity: int, coalesce: bool) -> bytes:
        """Serve `key` from the LRU, else recover it — at most once at a
        time per key when `coalesce` is on.  16 concurrent readers of a
        dead block cost ONE survivor fan-out and ONE decode; the 15
        followers block on the leader's flight.  A leader failure wakes
        the followers with the error and caches nothing."""
        if capacity > 0:
            data = self._get(key)
            if data is not None:
                self.stats.cache_event("hit")
                return data
        if not coalesce:
            self.stats.cache_event("miss")
            data = recover()
            if capacity > 0:
                self._put(key, data, capacity)
            return data
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                # double-check under the lock: a just-landed leader may
                # have populated the cache between _get and here
                data = self._data.get(key) if capacity > 0 else None
                if data is not None:
                    self._data.move_to_end(key)
                leader = data is None
                if leader:
                    flight = self._flights[key] = _Flight()
            else:
                leader = False
                data = None
        if data is not None:
            self.stats.cache_event("hit")
            return data
        if not leader:
            self.stats.cache_event("coalesced")
            # a wedged leader (e.g. a remote fetch past its own timeout)
            # must not strand followers forever: time out and self-serve
            if flight.event.wait(timeout=120.0):
                if flight.error is not None:
                    raise flight.error
                return flight.value
            return recover()
        self.stats.cache_event("miss")
        try:
            value = recover()
        except BaseException as e:
            flight.error = e
            raise
        else:
            flight.value = value
            if capacity > 0:
                self._put(key, value, capacity)
            return value
        finally:
            with self._lock:
                self._flights.pop(key, None)
            flight.event.set()


class _DecodeReq:
    __slots__ = ("inputs", "event", "out", "error")

    def __init__(self, inputs: np.ndarray):
        self.inputs = inputs
        self.event = threading.Event()
        self.out: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class SpanDecodeBatcher:
    """Stacks concurrent decode requests that share a (survivor-set,
    target) key into ONE GF mat-vec.  The decode row depends only on the
    key, so spans at different offsets concatenate column-wise: a leader
    drains everything queued for its key, decodes the stacked (d, ΣL)
    input in one call, then splits the output back per request.
    Requests arriving while a decode is in flight queue for the next
    round (the leader loops until its key's queue is empty)."""

    def __init__(self, decode_fn: Callable[[tuple, int, np.ndarray],
                                           np.ndarray],
                 stats: RecoverStats = STATS):
        self._decode_fn = decode_fn
        self._lock = threading.Lock()
        self._queues: dict[tuple, list[_DecodeReq]] = {}
        self._busy: set[tuple] = set()
        self.stats = stats

    def decode(self, survivors: tuple, target: int,
               inputs: np.ndarray) -> np.ndarray:
        """inputs: (d, L) survivor stack in `survivors` order -> (L,)
        recovered bytes of `target`."""
        key = (survivors, target)
        req = _DecodeReq(inputs)
        with self._lock:
            self._queues.setdefault(key, []).append(req)
            leader = key not in self._busy
            if leader:
                self._busy.add(key)
        if not leader:
            if req.event.wait(timeout=60.0):
                if req.error is not None:
                    raise req.error
                return req.out
            # leader vanished (shouldn't happen): decode our own span
            return self._decode_batch(survivors, target, [req])[0]
        try:
            while True:
                with self._lock:
                    batch = self._queues.pop(key, [])
                    if not batch:
                        self._busy.discard(key)
                        return req.out
                self._decode_batch(survivors, target, batch)
        except BaseException:
            with self._lock:
                self._busy.discard(key)
                stranded = self._queues.pop(key, [])
            for r in stranded:  # late joiners must not wait forever
                r.error = req.error or r.error
                r.event.set()
            raise

    def _decode_batch(self, survivors: tuple, target: int,
                      batch: list[_DecodeReq]) -> list[np.ndarray]:
        from ...qos.lanes import LANES

        sp = tracing.start("ec.recover.decode", tags={"spans": len(batch)})
        prev = tracing.swap(sp)
        try:
            if len(batch) == 1:
                stacked = batch[0].inputs
            else:
                stacked = np.concatenate([r.inputs for r in batch], axis=1)
            # foreground device lane: while this decode runs, queued
            # background batches (scrub re-encode, bulk encode) yield
            # at their next checkpoint
            with LANES.foreground():
                out = self._decode_fn(survivors, target, stacked)
            outs = []
            col = 0
            for r in batch:
                width = r.inputs.shape[1]
                r.out = out[col:col + width]
                outs.append(r.out)
                col += width
            self.stats.decoded(len(batch), int(stacked.nbytes))
            return outs
        except BaseException as e:
            for r in batch:
                r.error = e
            sp.status = f"error: {type(e).__name__}"
            raise
        finally:
            tracing.restore(prev)
            sp.finish()
            self.stats.add_stage("decode", sp.duration or 0.0)
            for r in batch:
                r.event.set()
