"""Needle -> shard interval math, matching ec_locate.go bit for bit.

A volume's logical .dat is striped row-major over the family's data shards
(10 for RS/Cauchy, the default): first nLargeRows rows of 1 GB blocks, then
rows of 1 MB blocks (zero-padded).  A (offset, size) span in the .dat maps
to one or more Intervals, each naming a block index + inner offset;
ToShardIdAndOffset then maps a block to (shard id, offset within the .ecNN
file).  The large/small two-tier scheme exists so the large-row count is
derivable from a shard's file size (ec_locate.go:18-19).

``data_shards`` defaults to the classic 10 so existing callers and volumes
are untouched; repair-efficient code families with a different stripe width
(pm_msr stripes over 5) pass their own.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import DATA_SHARDS_COUNT


@dataclass
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(self, large_block_size: int,
                               small_block_size: int,
                               data_shards: int = DATA_SHARDS_COUNT,
                               ) -> tuple[int, int]:
        ec_file_offset = self.inner_block_offset
        row_index = self.block_index // data_shards
        if self.is_large_block:
            ec_file_offset += row_index * large_block_size
        else:
            ec_file_offset += (self.large_block_rows_count * large_block_size
                               + row_index * small_block_size)
        ec_file_index = self.block_index % data_shards
        return ec_file_index, ec_file_offset


def locate_data(large_block_length: int, small_block_length: int,
                dat_size: int, offset: int, size: int,
                data_shards: int = DATA_SHARDS_COUNT) -> list[Interval]:
    block_index, is_large, inner_offset = _locate_offset(
        large_block_length, small_block_length, dat_size, offset, data_shards)
    # +k*small ensures the large-row count is derivable from shard size
    n_large_rows = (dat_size + data_shards * small_block_length) // (
        large_block_length * data_shards)

    intervals: list[Interval] = []
    while size > 0:
        interval = Interval(
            block_index=block_index,
            inner_block_offset=inner_offset,
            size=0,
            is_large_block=is_large,
            large_block_rows_count=n_large_rows,
        )
        block_remaining = (large_block_length if is_large
                           else small_block_length) - inner_offset
        if size <= block_remaining:
            interval.size = size
            intervals.append(interval)
            return intervals
        interval.size = block_remaining
        intervals.append(interval)
        size -= interval.size
        block_index += 1
        if is_large and block_index == n_large_rows * data_shards:
            is_large = False
            block_index = 0
        inner_offset = 0
    return intervals


def inline_shard_extent(logical_size: int, unit: int, data_shards: int,
                        shard_id: int) -> int:
    """Valid byte extent of one data shard's append-only log when
    ``logical_size`` stream bytes have been striped row-major in
    ``unit``-sized blocks over ``data_shards`` shards (the inline EC
    pure-small-block layout: zero large rows).

    Shards before the block the stream head is in have a full block in
    the current row; the head shard has the partial remainder; later
    shards end at the previous row."""
    full_rows, rem = divmod(logical_size, unit * data_shards)
    head_block, head_rem = divmod(rem, unit)
    extent = full_rows * unit
    if shard_id < head_block:
        extent += unit
    elif shard_id == head_block:
        extent += head_rem
    return extent


def _locate_offset(large_block_length: int, small_block_length: int,
                   dat_size: int, offset: int,
                   data_shards: int = DATA_SHARDS_COUNT,
                   ) -> tuple[int, bool, int]:
    large_row_size = large_block_length * data_shards
    n_large_rows = dat_size // large_row_size
    if offset < n_large_rows * large_row_size:
        return (offset // large_block_length, True,
                offset % large_block_length)
    offset -= n_large_rows * large_row_size
    return (offset // small_block_length, False,
            offset % small_block_length)
